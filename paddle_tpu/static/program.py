"""paddle.static Program/Executor compatibility layer (reference:
`python/paddle/fluid/framework.py` Program/Variable,
`python/paddle/fluid/executor.py:625` Executor).

TPU-native design: there is no ProgramDesc IR — while static mode is on,
every dispatched op is RECORDED (name, pure-jax primal, input refs,
attrs, outputs) into the current Program via the dispatch chokepoint
(`core/dispatch.py _static_record_hook`).  On first replay the recorded
op list is finalized into SSA form: intermediates become slot indices
(their Tensor objects are released), leaves (placeholders, parameters,
captured constants) are read LIVE at run time — so parameter updates
between Executor.run calls take effect, exactly like the reference
executor reading scope variables.  `Executor.run` replays the SSA DAG
under `jax.jit` with feeds substituted: the InterpreterCore's job done
by the compiler (SURVEY.md §7).

Shape-derived attributes are GUARDED: dims read from feed-derived
tensors during recording come back as SymbolicDim ints; any op that bakes
one into its attrs/primal closure (reshape/flatten computing a target from
a `None` batch dim recorded as 1) is flagged, and Executor.run raises if a
feed contradicts the baked size instead of replaying silently-wrong
numbers (reference programs re-infer shapes at run time).
"""
from __future__ import annotations

import contextlib
import weakref
from typing import Dict, List, Optional

import numpy as np
import jax
from ..core.jax_compat import jax_export
import jax.numpy as jnp

from ..core import dispatch as dispatch_mod
from ..core import dtype as dtype_mod
from ..core import tensor as tensor_mod
from ..core.tensor import SymbolicDim, Tensor


def _symbolic_feeds(obj, _depth=0):
    """Union of feed names of every SymbolicDim reachable in obj (attrs,
    lists, dicts, or a primal's closure cells — reshape-style ops bake
    computed targets there)."""
    if _depth > 6:
        return frozenset()
    if isinstance(obj, SymbolicDim):
        return obj.feeds or frozenset(["<unknown>"])
    out = frozenset()
    if isinstance(obj, (list, tuple, set)):
        for v in obj:
            out |= _symbolic_feeds(v, _depth + 1)
    elif isinstance(obj, dict):
        for v in obj.values():
            out |= _symbolic_feeds(v, _depth + 1)
    elif callable(obj) and getattr(obj, "__closure__", None):
        for c in obj.__closure__:
            out |= _symbolic_feeds(c.cell_contents, _depth + 1)
    return out


class _RawOp:
    __slots__ = ("name", "primal", "inputs", "kwargs", "outputs")

    def __init__(self, name, primal, inputs, kwargs, outputs):
        self.name = name
        self.primal = primal
        self.inputs = inputs      # list of Tensor | const
        self.kwargs = kwargs
        self.outputs = outputs    # list of Tensor (strong refs until
        #                           finalize; keeps ids stable)


class _SSAOp:
    __slots__ = ("name", "primal", "in_refs", "kwargs", "out_slots")

    def __init__(self, name, primal, in_refs, kwargs, out_slots):
        self.name = name
        self.primal = primal
        # in_refs: ('slot', i) | ('leaf', i) | ('const', value)
        self.in_refs = in_refs
        self.kwargs = kwargs
        self.out_slots = out_slots


class Program:
    """Recorded op list + feed/fetch registry (reference
    `framework.py Program`)."""

    def __init__(self):
        self._raw: List[_RawOp] = []
        self._ssa: Optional[List[_SSAOp]] = None
        self._leaves: List[Tensor] = []           # live-read at replay
        self._feed_vars: Dict[str, Tensor] = {}
        # fetch resolution: id -> (weakref, kind, index); validated by
        # identity at fetch time so a reused id can never mis-resolve
        self._locator: Dict[int, tuple] = {}
        self._name_locator: Dict[str, tuple] = {}
        self._declared_shapes: Dict[str, list] = {}
        self._cache = {}
        self._n_post_run = 0   # ops dispatched (and dropped) after finalize
        # shape-taint bookkeeping: feeds declared with None/-1 dims and the
        # tensors derived from them; ops that baked a SymbolicDim into
        # their attrs/closure are listed with reasons for the run check
        self._sym_feeds: Dict[str, list] = {}    # name -> [axis, ...]
        self._sym_dummy: Dict[int, list] = {}    # dummy size -> [feed, ...]
        # id -> weakref (identity membership; Tensor.__eq__ is elementwise
        # so hash-based sets cannot hold tensors)
        self._descendants: Dict[int, object] = {}
        self._baked_shape_ops: List[str] = []
        # set by Optimizer.minimize while this program records: running
        # the program then TRAINS (reference: the ProgramDesc contains
        # the backward + sgd ops, so exe.run applies updates)
        self._train_spec = None            # (loss Tensor, Optimizer)
        self._train_cache: Dict[tuple, object] = {}

    def _is_descendant(self, t) -> bool:
        r = self._descendants.get(id(t))
        return r is not None and r() is t

    def _add_descendant(self, t):
        self._descendants[id(t)] = weakref.ref(t)

    # -- recording ------------------------------------------------------
    def _record(self, name, primal, tensor_args, kwargs, outs):
        if self._ssa is not None:
            # Ops dispatched after Executor.run finalized this program are
            # between-runs eager computations (LR schedules, metrics built
            # with paddle ops).  They already executed through dispatch and
            # their values are live on the output Tensors — drop the
            # recording (keeping it would pin every intermediate array for
            # the life of the program; the reference re-lowers the whole
            # ProgramDesc on append instead).  Fetching such a tensor from
            # this program still errors by identity validation.
            self._n_post_run += 1
            return
        if self._sym_feeds:
            tainted = any(isinstance(a, Tensor) and self._is_descendant(a)
                          for a in tensor_args)
            if tainted:
                for o in outs:
                    if isinstance(o, Tensor):
                        self._add_descendant(o)
            feeds = _symbolic_feeds((primal, kwargs))
            if feeds:
                self._baked_shape_ops.append((name, feeds))
        self._raw.append(_RawOp(name, primal, list(tensor_args),
                                dict(kwargs), list(outs)))
        self._cache.clear()

    def _register_data(self, name, t: Tensor, declared_shape=None):
        self._feed_vars[name] = t
        if declared_shape is not None:
            self._declared_shapes[name] = list(declared_shape)

    def global_block(self):
        return self

    @property
    def ops(self):
        return self._raw if self._ssa is None else self._ssa

    def list_vars(self):
        return list(self._feed_vars.values())

    # -- finalize to SSA ------------------------------------------------
    def _finalize(self):
        if self._ssa is not None:
            return
        slot_of: Dict[int, int] = {}
        leaf_of: Dict[int, int] = {}
        n_slots = 0
        ssa = []
        for op in self._raw:
            if op.name == "__alias__":
                # in-place rebind: target (outputs[0]) now denotes the
                # source's (inputs[0]) value for all LATER consumers
                src_t = op.inputs[0]
                dst_t = op.outputs[0]
                if id(src_t) in slot_of:
                    slot_of[id(dst_t)] = slot_of[id(src_t)]
                    self._locator[id(dst_t)] = (
                        weakref.ref(dst_t), "slot", slot_of[id(src_t)])
                continue
            in_refs = []
            for a in op.inputs:
                if isinstance(a, Tensor):
                    if id(a) in slot_of:
                        in_refs.append(("slot", slot_of[id(a)]))
                    else:
                        li = leaf_of.get(id(a))
                        if li is None:
                            li = len(self._leaves)
                            leaf_of[id(a)] = li
                            self._leaves.append(a)   # live-read later
                            self._locator[id(a)] = (
                                weakref.ref(a), "leaf", li)
                            if getattr(a, "name", None):
                                self._name_locator[a.name] = ("leaf", li)
                        in_refs.append(("leaf", li))
                else:
                    in_refs.append(("const", a))
            out_slots = []
            for o in op.outputs:
                s = n_slots
                n_slots += 1
                slot_of[id(o)] = s
                out_slots.append(s)
                self._locator[id(o)] = (weakref.ref(o), "slot", s)
                if getattr(o, "name", None):
                    self._name_locator[o.name] = ("slot", s)
            ssa.append(_SSAOp(op.name, op.primal, in_refs, op.kwargs,
                              out_slots))
        # placeholders that never feed an op still need locators
        for fname, t in self._feed_vars.items():
            if id(t) not in self._locator:
                li = len(self._leaves)
                self._leaves.append(t)
                self._locator[id(t)] = (weakref.ref(t), "leaf", li)
                self._name_locator[fname] = ("leaf", li)
        self._n_slots = n_slots
        self._ssa = ssa
        self._raw = []            # release intermediate Tensor refs

    def _locate(self, target):
        """Resolve a fetch/feed target (Tensor or name) to
        ('leaf'|'slot', index) with identity validation."""
        if isinstance(target, str):
            loc = self._name_locator.get(target)
            if loc is None:
                raise KeyError(f"no variable named {target!r} in this "
                               "program")
            return loc
        ent = self._locator.get(id(target))
        if ent is not None:
            ref, kind, idx = ent
            if ref() is target:
                return (kind, idx)
        raise KeyError("fetch target was not produced by this program")

    # -- replay ---------------------------------------------------------
    def _replay(self, feed_arrays: Dict[str, object], fetch_locs):
        self._finalize()
        ssa = self._ssa
        n_slots = self._n_slots
        feed_leaf_idx = {}
        for fname in feed_arrays:
            kind, idx = self._locate(self._feed_vars[fname])
            if kind != "leaf":
                raise KeyError(f"feed target {fname!r} is not a leaf")
            feed_leaf_idx[fname] = idx

        def run(feeds, leaf_arrays):
            leaves = list(leaf_arrays)
            for fname, arr in feeds.items():
                leaves[feed_leaf_idx[fname]] = arr
            env: List[object] = [None] * n_slots
            for op in ssa:
                args = []
                for kind, v in op.in_refs:
                    if kind == "slot":
                        args.append(env[v])
                    elif kind == "leaf":
                        args.append(leaves[v])
                    else:
                        args.append(v)
                out = op.primal(*args, **op.kwargs)
                outs = out if isinstance(out, (tuple, list)) else (out,)
                for s, o in zip(op.out_slots, outs):
                    env[s] = o
            result = []
            for kind, idx in fetch_locs:
                result.append(env[idx] if kind == "slot" else leaves[idx])
            return tuple(result)

        key = (tuple(sorted(feed_arrays)), tuple(fetch_locs))
        jitted = self._cache.get(key)
        if jitted is None:
            jitted = jax.jit(run)
            self._cache[key] = jitted
        # leaves read LIVE: parameter updates between runs take effect
        leaf_arrays = [t._data for t in self._leaves]
        return jitted(feed_arrays, leaf_arrays)

    # -- training replay -------------------------------------------------
    def _train_replay(self, feed_arrays: Dict[str, object], fetch_locs):
        """Run the program AS A TRAIN STEP (set up by Optimizer.minimize):
        the recorded forward graph is re-dispatched through apply_op under
        `to_static`, so the autograd tape, the optimizer update, and the
        parameter/accumulator writes all compile into one XLA program —
        the same machinery the eager train loop uses.  (The pure replay
        path cannot train: backward and optimizer math run on raw arrays
        through vjp closures, invisible to the op recorder — reference
        programs instead carry explicit grad/sgd ops in the ProgramDesc.)"""
        self._finalize()
        loss_t, opt = self._train_spec
        loss_kind, loss_idx = self._locate(loss_t)
        feed_names = tuple(sorted(feed_arrays))
        feed_leaf_idx = {}
        for fname in feed_names:
            kind, idx = self._locate(self._feed_vars[fname])
            if kind != "leaf":
                raise KeyError(f"feed target {fname!r} is not a leaf")
            feed_leaf_idx[fname] = idx

        key = (feed_names, tuple(fetch_locs))
        step = self._train_cache.get(key)
        if step is None:
            from ..core import dispatch
            from ..jit import to_static

            ssa = self._ssa
            leaves = self._leaves

            def step_fn(*feed_ts):
                sub = {feed_leaf_idx[nm]: ft
                       for nm, ft in zip(feed_names, feed_ts)}
                env: List[object] = [None] * self._n_slots

                def resolve(kind, v):
                    if kind == "slot":
                        return env[v]
                    if kind == "leaf":
                        return sub.get(v, leaves[v])
                    return v

                # suspend static recording: we are EXECUTING the program,
                # and enable_static leaves the record hook pointed at the
                # current default program
                with dispatch.no_static_record():
                    for op in ssa:
                        args = [resolve(k, v) for k, v in op.in_refs]
                        outs = dispatch.apply_op(
                            op.name, op.primal, args, dict(op.kwargs),
                            n_outs=len(op.out_slots))
                        outs = outs if isinstance(outs, tuple) else (outs,)
                        for s, o in zip(op.out_slots, outs):
                            env[s] = o
                    loss = resolve(loss_kind, loss_idx)
                    loss.backward()
                    opt.step()
                    opt.clear_grad()
                return tuple(resolve(k, i) for k, i in fetch_locs)

            step = to_static(step_fn)
            self._train_cache[key] = step

        feed_ts = [Tensor._wrap(feed_arrays[nm]) for nm in feed_names]
        outs = step(*feed_ts)
        outs = outs if isinstance(outs, (tuple, list)) else (outs,)
        return tuple(o._value() if isinstance(o, Tensor) else o
                     for o in outs)

    def __repr__(self):
        n = len(self._raw) if self._ssa is None else len(self._ssa)
        return f"Program(num_ops={n})"


_default_main = Program()
_default_startup = Program()
_current_main: Program = _default_main
_current_startup: Program = _default_startup


def default_main_program() -> Program:
    return _current_main


def default_startup_program() -> Program:
    return _current_startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    """Scope the recording target (reference framework.py
    program_guard)."""
    global _current_main, _current_startup
    old_m, old_s = _current_main, _current_startup
    _current_main = main_program
    if startup_program is not None:
        _current_startup = startup_program
    _sync_hook()   # records only while static mode is enabled
    try:
        yield
    finally:
        _current_main = old_m
        _current_startup = old_s
        _sync_hook()


def _record_hook(name, primal, tensor_args, kwargs, outs):
    _current_main._record(name, primal, tensor_args, kwargs, outs)


def _taint_shape(t, dims):
    """Shape reads during recording: wrap feed-derived dims in SymbolicDim
    so attrs computed from them are detectable (the documented reshape
    footgun).  Placeholders taint their declared None axes; derived
    tensors taint dims carrying a feed's distinctive dummy size — the
    taint names WHICH feeds it derives from, so the run-time check only
    fires for contradicting feeds."""
    prog = _current_main
    if not prog._sym_feeds:
        return dims
    name = getattr(t, "name", "")
    axes = prog._sym_feeds.get(name)
    if axes is not None and t is prog._feed_vars.get(name):
        return [SymbolicDim(d, {name}) if i in axes else d
                for i, d in enumerate(dims)]
    if prog._is_descendant(t):
        return [SymbolicDim(d, prog._sym_dummy[d])
                if d in prog._sym_dummy else d for d in dims]
    return dims


def _install_hook():
    dispatch_mod._static_record_hook = _record_hook
    tensor_mod._shape_taint_hook = _taint_shape


def _remove_hook():
    dispatch_mod._static_record_hook = None
    tensor_mod._shape_taint_hook = None


def _sync_hook():
    """Hook active only while static mode is on."""
    import paddle_tpu as paddle

    if getattr(paddle, "_static_mode", False):
        _install_hook()
    else:
        _remove_hook()


def data(name, shape, dtype=None, lod_level=0):
    """Declare a feed placeholder (reference static.data): a zero tensor
    registered with the current Program; Executor.run feeds override it.

    `None`/-1 dims are recorded at size 1 and may be fed at any size.
    Ops whose attributes derive from such a dim at build time
    (reshape/flatten with computed targets) bake the build-time dummy —
    detected via SymbolicDim taint; Executor.run raises on a
    contradicting feed rather than replaying wrong numbers.
    """
    dt = dtype_mod.convert_dtype(dtype) if dtype else \
        dtype_mod.get_default_dtype()
    sym_axes = [i for i, s_ in enumerate(shape)
                if s_ is None or int(s_) < 0]
    # None dims record at a DISTINCTIVE dummy size (not 1: size-1 dims are
    # everywhere — keepdim axes, singleton channels — and would false-flag
    # the shape-bake guard).  The FIRST None axis of every feed shares ONE
    # dummy: it is the batch axis in practice, and `pred - y` style ops
    # combining two feeds' batch dims must broadcast at record time (a
    # per-feed batch dummy made x:[None,4] minus y:[None,1] a record-time
    # shape error).  Additional None axes cycle through odd primes so
    # their dim VALUE still identifies the deriving feed.
    concrete = []
    sym_val = {}
    first_none = sym_axes[0] if sym_axes else None
    for i, s_ in enumerate(shape):
        if i in sym_axes:
            v = _SYM_SIZE_POOL[0] if i == first_none \
                else _next_sym_size(_current_main)
            sym_val[i] = v
            concrete.append(v)
        else:
            concrete.append(int(s_))
    t = Tensor._wrap(jnp.zeros(concrete, dt), stop_gradient=True)
    t.name = name
    # declared shape kept on the Program (None dims export symbolically)
    _current_main._register_data(name, t, declared_shape=shape)
    if sym_axes:
        _current_main._sym_feeds[name] = sym_axes
        for v in sym_val.values():
            _current_main._sym_dummy.setdefault(v, []).append(name)
        _current_main._add_descendant(t)
    return t


_SYM_SIZE_POOL = (61, 67, 71, 73, 79, 83, 89, 97, 101, 103, 107, 109, 113)


def _next_sym_size(prog) -> int:
    # pool[0] is reserved as the shared batch dummy (data() above)
    for v in _SYM_SIZE_POOL[1:]:
        if v not in prog._sym_dummy:
            return v
    return _SYM_SIZE_POOL[
        1 + len(prog._sym_dummy) % (len(_SYM_SIZE_POOL) - 1)]


class Scope:
    """Minimal scope (reference framework Scope): name -> Tensor."""

    def __init__(self):
        self._vars = {}

    def var(self, name):
        return self._vars.setdefault(name, Tensor._wrap(jnp.zeros(())))

    def find_var(self, name):
        return self._vars.get(name)


_global_scope = Scope()


def global_scope():
    return _global_scope


@contextlib.contextmanager
def scope_guard(scope):
    global _global_scope
    old = _global_scope
    _global_scope = scope
    try:
        yield
    finally:
        _global_scope = old


def cpu_places(device_count=None):
    from ..core.device import CPUPlace

    n = device_count or 1
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    raise RuntimeError("cuda_places: no CUDA devices in the TPU build; "
                       "this build executes on TPU/CPU via XLA")


class Executor:
    """Replay executor (reference `fluid/executor.py:625`): `run`
    substitutes feeds into the recorded program and returns fetched
    arrays. Fetch targets may be Tensors or variable names."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True, **kwargs):
        prog = program or _current_main
        if isinstance(prog, CompiledProgram):
            prog = prog._program
        if isinstance(prog, _LoadedProgram):
            feed_arrays = {k: jnp.asarray(np.asarray(v))
                           for k, v in (feed or {}).items()}
            outs = prog.run(feed_arrays)
            picked = [outs[i] for i in (fetch_list
                                        or range(len(outs)))]
            if return_numpy:
                return [np.asarray(o) for o in picked]
            return [Tensor._wrap(o) for o in picked]
        feed = feed or {}
        fetch_list = fetch_list or []
        feed_arrays = {}
        for k, v in feed.items():
            if k not in prog._feed_vars:
                raise KeyError(f"feed target {k!r} was not declared with "
                               "static.data in this program")
            want = prog._feed_vars[k]._data
            arr = jnp.asarray(np.asarray(v)).astype(want.dtype)
            if prog._baked_shape_ops:
                baked_here = sorted({n for n, fs in prog._baked_shape_ops
                                     if k in fs or "<unknown>" in fs})
                axes = prog._sym_feeds.get(k, ()) if baked_here else ()
                for ax in axes:
                    if ax < arr.ndim and arr.shape[ax] != want.shape[ax]:
                        raise RuntimeError(
                            f"feed {k!r} has size {arr.shape[ax]} at its "
                            f"None-declared axis {ax}, but ops "
                            f"{baked_here} baked an attribute computed "
                            f"from the build-time dummy size "
                            f"{want.shape[ax]} — the replay would be "
                            "silently wrong.  Declare the real size in "
                            "static.data, or avoid computing shape "
                            "attributes from a None dim (reference "
                            "programs re-infer these at run time)")
            feed_arrays[k] = arr
        prog._finalize()
        fetch_locs = tuple(prog._locate(t) for t in fetch_list)
        if prog._train_spec is not None:
            outs = prog._train_replay(feed_arrays, fetch_locs)
        else:
            outs = prog._replay(feed_arrays, fetch_locs)
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor._wrap(o) for o in outs]

    def close(self):
        pass


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    t = Tensor._wrap(jnp.full(tuple(int(s) for s in shape), value,
                              dtype_mod.convert_dtype(dtype)))
    t.persistable = persistable
    if name:
        t.name = name
    return t


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Reference static.gradients: grads of targets w.r.t. inputs via
    the eager tape (ops recorded under static mode also ran eagerly, so
    the tape exists)."""
    from ..core.autograd import grad as _grad

    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    return _grad(list(targets), list(inputs),
                 grad_outputs=target_gradients, allow_unused=True,
                 retain_graph=True)


append_backward = gradients  # closest analog: produce grads explicitly


def name_scope(prefix=None):
    return contextlib.nullcontext()


@contextlib.contextmanager
def device_guard(device=None):
    yield


class BuildStrategy:
    """Config stub (reference BuildStrategy): knobs are XLA's job."""

    def __init__(self):
        self.memory_optimize = None
        self.enable_inplace = None
        self.fuse_all_optimizer_ops = False
        self.fuse_elewise_add_act_ops = False


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10


class CompiledProgram:
    """Pass-through (reference compiler.py CompiledProgram): replay is
    already jit-compiled; with_data_parallel is a no-op wrapper."""

    def __init__(self, program, build_strategy=None):
        self._program = program

    def with_data_parallel(self, *a, **k):
        return self

    def __getattr__(self, name):
        return getattr(self._program, name)


ParallelExecutor = CompiledProgram


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=False,
          print_phase='both'):
    """Debug print op (reference fluid.layers.Print)."""
    arr = input._value() if isinstance(input, Tensor) else input
    jax.debug.print((message or "") + " {}", arr)
    return input


class ExponentialMovingAverage:
    """EMA of trainable parameters (reference
    `fluid/optimizer.py ExponentialMovingAverage`): update() after each
    step; apply()/restore() swap shadow weights in and out."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = float(decay)
        self._shadow = {}
        self._backup = {}
        self._tracked = []
        self._step = 0

    def update(self, parameters=None):
        if parameters is None:
            raise ValueError("pass parameters=model.parameters()")
        self._step += 1
        # bias-limited dynamic decay like the reference
        d = min(self._decay, (1 + self._step) / (10 + self._step))
        tracked = []
        for p in parameters:
            key = p.name or f"param_{id(p)}"
            prev = self._shadow.get(key)
            arr = p._value().astype(jnp.float32)
            self._shadow[key] = arr if prev is None else \
                d * prev + (1 - d) * arr
            tracked.append((p, key))
        self._tracked = tracked

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        for p, key in self._tracked:
            self._backup[key] = p._value()
            p._set_data(self._shadow[key].astype(p._value().dtype))
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        for p, key in self._tracked:
            if key in self._backup:
                p._set_data(self._backup.pop(key))


def accuracy(input, label, k=1, correct=None, total=None):
    from ..metric import accuracy as _acc

    return _acc(input, label, k=k)


def auc(input, label, curve='ROC', num_thresholds=4095, topk=1,
        slide_steps=1):
    from ..metric import Auc

    m = Auc(curve=curve, num_thresholds=min(num_thresholds, 4095))
    preds = np.asarray(input.numpy() if isinstance(input, Tensor)
                       else input)
    if preds.ndim == 1 or preds.shape[-1] == 1:
        preds = np.stack([1 - preds.reshape(-1),
                          preds.reshape(-1)], axis=1)
    m.update(preds, np.asarray(label.numpy()
                               if isinstance(label, Tensor) else label))
    val = m.accumulate()
    return (Tensor._wrap(jnp.asarray(val, jnp.float32)),) * 3


# -- inference model serialization (reference fluid/io.py
# save_inference_model/load_inference_model; format here: serialized
# StableHLO via jax.export + a pickle sidecar with feed/fetch meta) -----

class _LoadedProgram:
    """Deserialized inference program: runnable by Executor.run with
    feed={name: array}, fetch_list=the returned fetch handles."""

    def __init__(self, exported, feed_names, n_fetch):
        self._exported = exported
        self._feed_names = list(feed_names)
        self._n_fetch = n_fetch

    def run(self, feed):
        args = [feed[n] for n in self._feed_names]
        return self._exported.call(*args)


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Freeze the program for deployment: parameters are baked into the
    exported StableHLO; only `feed_vars` stay as runtime inputs."""
    import pickle

    prog = program or default_main_program()
    prog._finalize()
    feed_names = [getattr(t, "name", None) or str(i)
                  for i, t in enumerate(feed_vars)]
    for t, n in zip(feed_vars, feed_names):
        if n not in prog._feed_vars:
            raise KeyError(f"feed var {n!r} was not declared with "
                           "static.data")
    fetch_locs = tuple(prog._locate(t) for t in fetch_vars)
    feed_locs = [prog._locate(prog._feed_vars[n]) for n in feed_names]
    leaf_arrays = [t._data for t in prog._leaves]
    ssa = prog._ssa
    n_slots = prog._n_slots

    def infer(*feed_arrays):
        leaves = list(leaf_arrays)
        for (kind, idx), arr in zip(feed_locs, feed_arrays):
            leaves[idx] = arr
        env = [None] * n_slots
        for op in ssa:
            args = [env[v] if kind == "slot"
                    else (leaves[v] if kind == "leaf" else v)
                    for kind, v in op.in_refs]
            out = op.primal(*args, **op.kwargs)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            for s, o in zip(op.out_slots, outs):
                env[s] = o
        return tuple(env[idx] if kind == "slot" else leaves[idx]
                     for kind, idx in fetch_locs)

    # None/-1 declared dims export as SYMBOLIC dims so the frozen model
    # accepts any size there (jax shape polymorphism)
    shapes = []
    n_sym = 0
    for n in feed_names:
        t = prog._feed_vars[n]
        declared = prog._declared_shapes.get(n, list(t._data.shape))
        parts = []
        symbolic = False
        for s in declared:
            if s is None or int(s) < 0:
                parts.append(f"_sdim{n_sym}")
                n_sym += 1
                symbolic = True
            else:
                parts.append(str(int(s)))
        if symbolic:
            dims = jax_export.symbolic_shape(", ".join(parts))
            shapes.append(jax.ShapeDtypeStruct(tuple(dims),
                                               t._data.dtype))
        else:
            shapes.append(jax.ShapeDtypeStruct(t._data.shape,
                                               t._data.dtype))
    exported = jax_export.export(jax.jit(infer))(*shapes)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    with open(path_prefix + ".pdiparams", "wb") as f:
        pickle.dump({"feed_names": feed_names,
                     "n_fetch": len(fetch_vars)}, f)


def load_inference_model(path_prefix, executor=None, **kwargs):
    """Returns (program, feed_target_names, fetch_targets) — run with
    `Executor.run(program, feed={...}, fetch_list=fetch_targets)`."""
    import pickle

    with open(path_prefix + ".pdmodel", "rb") as f:
        exported = jax_export.deserialize(f.read())
    with open(path_prefix + ".pdiparams", "rb") as f:
        meta = pickle.load(f)
    prog = _LoadedProgram(exported, meta["feed_names"], meta["n_fetch"])
    fetch_targets = list(range(meta["n_fetch"]))
    return prog, meta["feed_names"], fetch_targets


def serialize_program(feed_vars, fetch_vars, program=None):
    """Bytes = pickled {hlo, feed_names, n_fetch}; deserialize_program
    rebuilds a runnable _LoadedProgram."""
    import os
    import pickle
    import tempfile

    prog = program or default_main_program()
    with tempfile.TemporaryDirectory() as d:
        save_inference_model(os.path.join(d, "m"), feed_vars, fetch_vars,
                             program=prog)
        with open(os.path.join(d, "m.pdmodel"), "rb") as f:
            hlo = f.read()
        with open(os.path.join(d, "m.pdiparams"), "rb") as f:
            meta = pickle.load(f)
    return pickle.dumps({"hlo": hlo, **meta})


def deserialize_program(data):
    import pickle

    blob = pickle.loads(data)
    exported = jax_export.deserialize(blob["hlo"])
    return _LoadedProgram(exported, blob["feed_names"], blob["n_fetch"])


# -- program state save/load (reference static/io.py
# save/load_program_state, serialize/deserialize_persistables) ---------

def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def serialize_persistables(feed_vars, fetch_vars, program=None):
    """Pickle the live leaf (parameter) arrays of the program."""
    import pickle

    prog = program or default_main_program()
    prog._finalize()
    state = {i: np.asarray(t._data) for i, t in enumerate(prog._leaves)}
    return pickle.dumps(state)


def deserialize_persistables(program, data, executor=None):
    import pickle

    state = pickle.loads(data)
    program._finalize()
    for i, arr in state.items():
        if i < len(program._leaves):
            t = program._leaves[i]
            t._set_data(jnp.asarray(arr).astype(t._data.dtype))


def save_program_state(dirname=None, program=None):
    prog = program or default_main_program()
    prog._finalize()
    return {i: np.asarray(t._data) for i, t in enumerate(prog._leaves)}


def load_program_state(state_or_dirname=None, var_list=None):
    """Reference loads a params dir; here program state round-trips as
    in-memory dicts (save_program_state -> set_program_state) or through
    serialize/deserialize_persistables for on-disk bytes. A directory
    path raises instead of silently returning the live state."""
    if isinstance(state_or_dirname, dict) or state_or_dirname is None:
        return state_or_dirname if state_or_dirname is not None \
            else save_program_state()
    raise NotImplementedError(
        "load_program_state from a directory is not supported: persist "
        "state with serialize_persistables/save_to_file and restore via "
        "deserialize_persistables, or pass the dict from "
        "save_program_state")


def set_program_state(program, state):
    program._finalize()
    for i, arr in state.items():
        if isinstance(i, int) and i < len(program._leaves):
            t = program._leaves[i]
            t._set_data(jnp.asarray(arr).astype(t._data.dtype))


def normalize_program(program, feed_vars, fetch_vars):
    """Reference: prune to the feed->fetch subgraph. The SSA replay
    already executes only recorded ops; returned unchanged."""
    return program


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Reference static py_func: host-python op inside a program. Eager
    recording runs the function directly; a custom backward wraps it as
    a PyLayer."""
    from ..autograd import PyLayer

    xs = x if isinstance(x, (list, tuple)) else [x]
    if backward_func is None:
        return func(*xs)

    class _PyFunc(PyLayer):
        @staticmethod
        def forward(ctx, *args):
            ctx.save_for_backward(*args)
            return func(*args)

        @staticmethod
        def backward(ctx, *grads):
            return backward_func(*ctx.saved_tensor(), *grads)

    return _PyFunc.apply(*xs)


# reference static Variable is the graph-mode tensor handle; here the
# Tensor facade plays both roles, so isinstance checks against
# static.Variable hold for everything static.data / ops return
Variable = Tensor


def xpu_places(device_ids=None):
    raise RuntimeError("xpu_places: no XPU devices in the TPU build")


def npu_places(device_ids=None):
    raise RuntimeError("npu_places: no NPU devices in the TPU build")


def mlu_places(device_ids=None):
    raise RuntimeError("mlu_places: no MLU devices in the TPU build")


class IpuStrategy:
    def __init__(self, *a, **k):
        raise NotImplementedError("IPU support is not part of the TPU "
                                  "build")


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        raise NotImplementedError("IPU support is not part of the TPU "
                                  "build")


def ipu_shard_guard(*a, **k):
    raise NotImplementedError("IPU support is not part of the TPU build")


def set_ipu_shard(*a, **k):
    raise NotImplementedError("IPU support is not part of the TPU build")
