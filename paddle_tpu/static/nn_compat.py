"""paddle.static.nn — fluid-style functional layer builders.

Reference: python/paddle/static/nn/__init__.py (re-exporting
fluid/layers/nn.py builders: fc:195, conv2d:1451, embedding, batch_norm,
…) and fluid/layers/control_flow.py (case:2565, switch_case:3684,
py_func).

TPU-native design: every builder is a thin functional veneer over the
paddle_tpu.nn layer (parameters created through create_parameter so they
register with the active static Program) — the reference's LayerHelper
append_op machinery is the dispatch recorder here.  The LoD ``sequence_*``
family and the sampled-softmax/CRF ops are legacy variable-length-tensor
APIs with no 2.x tensor equivalent; they raise with the descope reason
(pad + mask via paddle.nn instead).
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = [
    "fc", "embedding", "conv2d", "conv2d_transpose", "conv3d",
    "conv3d_transpose", "batch_norm", "instance_norm", "layer_norm",
    "group_norm", "data_norm", "spectral_norm", "deform_conv2d", "prelu",
    "bilinear_tensor_product", "case", "switch_case", "py_func",
    "crf_decoding", "nce", "multi_box_head", "row_conv",
    "sparse_embedding",
    "sequence_concat", "sequence_conv", "sequence_enumerate",
    "sequence_expand", "sequence_expand_as", "sequence_first_step",
    "sequence_last_step", "sequence_pad", "sequence_pool",
    "sequence_reshape", "sequence_reverse", "sequence_scatter",
    "sequence_slice", "sequence_softmax", "sequence_unpad",
]


def _param(shape, dtype="float32", attr=None, is_bias=False):
    from .. import create_parameter

    return create_parameter(
        list(shape), dtype,
        default_initializer=None if not is_bias else _zeros_init())


def _zeros_init():
    from ..nn.initializer import Constant

    return Constant(0.0)


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """Fully-connected over flattened trailing dims (reference
    fluid/layers/nn.py fc)."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    xs = [x] if isinstance(x, Tensor) else list(x)
    outs = []
    for xi in xs:
        shape = xi.shape
        in_dim = int(np.prod(shape[num_flatten_dims:]))
        # 0 = keep original dim (paddle reshape semantics): never bake a
        # feed's None-dim dummy into the reshape attr
        flat = xi.reshape([0] * num_flatten_dims + [in_dim])
        w = _param([in_dim, size], str(xi.dtype))
        outs.append(flat.matmul(w))
    out = outs[0]
    for o in outs[1:]:
        out = out + o
    if bias_attr is not False:
        b = _param([size], str(xs[0].dtype), is_bias=True)
        out = out + b
    if activation:
        out = getattr(F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    import paddle_tpu.nn.functional as F

    w = _param(list(size), dtype)
    return F.embedding(input, w, padding_idx=padding_idx)


def _conv(ndim, transpose):
    import paddle_tpu.nn.functional as F

    fn = {
        (2, False): F.conv2d, (2, True): F.conv2d_transpose,
        (3, False): F.conv3d, (3, True): F.conv3d_transpose,
    }[(ndim, transpose)]

    def builder(input, num_filters, filter_size, stride=1, padding=0,
                dilation=1, groups=1, param_attr=None, bias_attr=None,
                use_cudnn=True, act=None, name=None,
                output_size=None, data_format="NCHW" if ndim == 2
                else "NCDHW"):
        import paddle_tpu.nn.functional as F

        c_in = input.shape[1]
        ks = [filter_size] * ndim if isinstance(filter_size, int) \
            else list(filter_size)
        g = max(int(groups or 1), 1)
        if transpose:
            w = _param([c_in, num_filters // g] + ks, str(input.dtype))
        else:
            w = _param([num_filters, c_in // g] + ks, str(input.dtype))
        b = None
        if bias_attr is not False:
            b = _param([num_filters], str(input.dtype), is_bias=True)
        kw = dict(stride=stride, padding=padding, groups=g)
        if not transpose:
            kw["dilation"] = dilation
        out = fn(input, w, bias=b, **kw)
        if act:
            out = getattr(F, act)(out)
        return out

    return builder


conv2d = _conv(2, False)
conv2d_transpose = _conv(2, True)
conv3d = _conv(3, False)
conv3d_transpose = _conv(3, True)


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=True,
               use_global_stats=False, is_test=False):
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    c = input.shape[1]
    w = _param([c], str(input.dtype))
    b = _param([c], str(input.dtype), is_bias=True)
    rm = paddle.zeros([c], str(input.dtype))
    rv = paddle.ones([c], str(input.dtype))
    out = F.batch_norm(input, rm, rv, weight=w, bias=b,
                       training=not (is_test or use_global_stats),
                       momentum=momentum, epsilon=epsilon,
                       data_format=data_layout)
    if act:
        out = getattr(F, act)(out)
    return out


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    import paddle_tpu.nn.functional as F

    c = input.shape[1]
    w = None if param_attr is False else _param([c], str(input.dtype))
    b = None if bias_attr is False else _param([c], str(input.dtype),
                                               is_bias=True)
    return F.instance_norm(input, weight=w, bias=b, eps=epsilon)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    import paddle_tpu.nn.functional as F

    shape = input.shape[begin_norm_axis:]
    n = int(np.prod(shape))
    w = _param([n], str(input.dtype)) if scale else None
    b = _param([n], str(input.dtype), is_bias=True) if shift else None
    flat_norm = list(input.shape[:begin_norm_axis]) + [n]
    out = F.layer_norm(input.reshape(flat_norm), n, weight=w, bias=b,
                       epsilon=epsilon).reshape(list(input.shape))
    if act:
        out = getattr(F, act)(out)
    return out


def group_norm(input, groups, epsilon=1e-5, param_attr=None,
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    import paddle_tpu.nn.functional as F

    c = input.shape[1]
    w = None if param_attr is False else _param([c], str(input.dtype))
    b = None if bias_attr is False else _param([c], str(input.dtype),
                                               is_bias=True)
    out = F.group_norm(input, groups, epsilon=epsilon, weight=w, bias=b)
    if act:
        out = getattr(F, act)(out)
    return out


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              enable_scale_and_shift=False, **kwargs):
    """Batch-statistics-free normalization (reference data_norm: running
    sums learned as parameters)."""
    mean = input.mean(axis=0, keepdim=True)
    std = ((input - mean) ** 2).mean(axis=0, keepdim=True)
    out = (input - mean) / (std + epsilon).sqrt()
    if act:
        import paddle_tpu.nn.functional as F

        out = getattr(F, act)(out)
    return out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    from ..nn.layer.norm import SpectralNorm

    sn = SpectralNorm(weight.shape, axis=dim, power_iters=power_iters,
                      epsilon=eps)
    return sn(weight)


def deform_conv2d(input, offset, mask, num_filters, filter_size,
                  stride=1, padding=0, dilation=1, groups=1,
                  deformable_groups=1, im2col_step=1, param_attr=None,
                  bias_attr=None, name=None):
    from ..vision.ops import deform_conv2d as _dc

    c_in = input.shape[1]
    ks = [filter_size] * 2 if isinstance(filter_size, int) \
        else list(filter_size)
    w = _param([num_filters, c_in // max(groups, 1)] + ks,
               str(input.dtype))
    b = None
    if bias_attr is not False:
        b = _param([num_filters], str(input.dtype), is_bias=True)
    return _dc(input, offset, w, bias=b, stride=stride, padding=padding,
               dilation=dilation, deformable_groups=deformable_groups,
               groups=groups, mask=mask)


def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
    import paddle_tpu.nn.functional as F

    if mode == "all":
        n = 1
    elif mode == "channel":
        n = x.shape[1]
    else:
        n = int(np.prod(x.shape[1:]))
    w = _param([n], str(x.dtype))
    return F.prelu(x, w)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    import paddle_tpu.nn.functional as F

    w = _param([size, x.shape[-1], y.shape[-1]], str(x.dtype))
    b = None
    if bias_attr is not False:
        b = _param([size], str(x.dtype), is_bias=True)
    out = F.bilinear(x, y, w, bias=b)
    if act:
        out = getattr(F, act)(out)
    return out


# -- control flow ------------------------------------------------------------

def case(pred_fn_pairs, default=None, name=None):
    """First branch whose predicate holds (reference
    control_flow.py:2565) — lowered as a nested `cond` chain, so it works
    both eagerly and traced."""
    from .nn import cond

    if not pred_fn_pairs:
        raise TypeError("pred_fn_pairs may not be empty")

    def build(pairs):
        (pred, fn) = pairs[0]
        if len(pairs) == 1:
            if default is None:
                return fn()   # reference: last fn is the fallback
            return cond(pred, fn, default)
        return cond(pred, fn, lambda: build(pairs[1:]))

    return build(list(pred_fn_pairs))


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Branch by integer index (reference control_flow.py:3684)."""
    from .nn import cond

    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    else:
        items = list(enumerate(branch_fns))

    def build(pairs):
        idx, fn = pairs[0]
        same = (branch_index == idx)
        if len(pairs) == 1:
            if default is None:
                return fn()
            return cond(same, fn, default)
        return cond(same, fn, lambda: build(pairs[1:]))

    return build(items)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host-python op (reference py_func_op): runs ``func`` on concrete
    values.  Under jit this is a host callback boundary; eager it just
    calls through."""
    xs = [x] if isinstance(x, Tensor) else list(x)
    res = func(*xs)
    return res if res is not None else out


# -- LoD legacy (descoped with reasons) -------------------------------------

def _lod_stub(name):
    def fn(*a, **k):
        raise NotImplementedError(
            f"static.nn.{name} operates on LoD (variable-length) tensors, "
            "a fluid-era representation with no 2.x tensor equivalent; "
            "use padded tensors + masks (paddle.nn, sequence_mask) "
            "instead")
    fn.__name__ = name
    fn.__qualname__ = name
    return fn


for _n in ("sequence_concat", "sequence_conv", "sequence_enumerate",
           "sequence_expand", "sequence_expand_as", "sequence_first_step",
           "sequence_last_step", "sequence_pad", "sequence_pool",
           "sequence_reshape", "sequence_reverse", "sequence_scatter",
           "sequence_slice", "sequence_softmax", "sequence_unpad",
           "crf_decoding", "nce", "multi_box_head", "row_conv",
           "sparse_embedding"):
    globals()[_n] = _lod_stub(_n)
