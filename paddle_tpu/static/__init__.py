"""paddle.static parity surface.

The reference's static-graph mode (Program/Executor) is obsolete under
XLA — `paddle.jit.to_static` IS the static mode (SURVEY.md §7).  This
namespace keeps the API entry points users reach for: InputSpec, the
control-flow ops, and no-op mode toggles.
"""
from ..jit import InputSpec  # noqa: F401
from . import nn  # noqa: F401
from .nn import cond, while_loop  # noqa: F401
from .program import (  # noqa: F401
    Program, program_guard, default_main_program, default_startup_program,
    data, Executor, Scope, global_scope, scope_guard, cpu_places,
    cuda_places, create_global_var, gradients, append_backward,
    name_scope, device_guard, BuildStrategy, ExecutionStrategy,
    CompiledProgram, ParallelExecutor, Print, ExponentialMovingAverage,
    accuracy, auc, save_inference_model, load_inference_model,
    serialize_program, deserialize_program, save_to_file, load_from_file,
    serialize_persistables, deserialize_persistables, save_program_state,
    load_program_state, set_program_state, normalize_program, py_func,
    Variable, xpu_places, npu_places, mlu_places, IpuStrategy,
    IpuCompiledProgram, ipu_shard_guard, set_ipu_shard,
)
from ..framework.io import save, load  # noqa: F401 — state save/load
from ..nn.layer_base import ParamAttr as _ParamAttr


class WeightNormParamAttr(_ParamAttr):
    """Reference WeightNormParamAttr (fluid/param_attr.py): ParamAttr
    plus the weight-norm `dim`. Weight normalization itself is applied
    by nn.utils.weight_norm-style reparameterization; the attr carries
    the intent through layer construction."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        super().__init__(name=name, initializer=initializer,
                         learning_rate=learning_rate,
                         regularizer=regularizer, trainable=trainable,
                         need_clip=need_clip)
        self.dim = dim


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """static.create_parameter (same factory as the top-level API;
    imported lazily — the top-level symbol is defined after subpackage
    imports run)."""
    import paddle_tpu as paddle

    return paddle.create_parameter(shape, dtype, name=name, attr=attr,
                                   is_bias=is_bias,
                                   default_initializer=default_initializer)

__all__ = [
    "InputSpec", "nn", "cond", "while_loop", "Program", "program_guard",
    "default_main_program", "default_startup_program", "data", "Executor",
    "Scope", "global_scope", "scope_guard", "cpu_places", "cuda_places",
    "create_global_var", "gradients", "append_backward", "name_scope",
    "device_guard", "BuildStrategy", "ExecutionStrategy",
    "CompiledProgram", "ParallelExecutor", "Print",
    "ExponentialMovingAverage", "accuracy", "auc", "save", "load",
    "save_inference_model", "load_inference_model", "serialize_program",
    "deserialize_program", "save_to_file", "load_from_file",
    "serialize_persistables", "deserialize_persistables",
    "save_program_state", "load_program_state", "set_program_state",
    "normalize_program", "py_func", "Variable", "xpu_places",
    "npu_places", "mlu_places", "IpuStrategy", "IpuCompiledProgram",
    "ipu_shard_guard", "set_ipu_shard",
    "create_parameter", "WeightNormParamAttr",
]
