"""paddle.static parity surface.

The reference's static-graph mode (Program/Executor) is obsolete under
XLA — `paddle.jit.to_static` IS the static mode (SURVEY.md §7).  This
namespace keeps the API entry points users reach for: InputSpec, the
control-flow ops, and no-op mode toggles.
"""
from ..jit import InputSpec  # noqa: F401
from . import nn  # noqa: F401
from .nn import cond, while_loop  # noqa: F401

__all__ = ["InputSpec", "nn", "cond", "while_loop"]
