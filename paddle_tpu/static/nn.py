"""Control-flow ops for compiled programs (reference:
python/paddle/fluid/layers/control_flow.py — cond:2334, while_loop:1104 —
and the dygraph-to-static transformers, dygraph_to_static/
ifelse_transformer.py, loop_transformer.py).

TPU-native design: the reference rewrites python `if`/`while` into
ConditionalBlock/While ops via AST transforms.  Here the bridge is explicit
and functional — `cond` and `while_loop` lower to `lax.cond` /
`lax.while_loop` when the predicate is traced (inside `to_static`), and
simply execute eagerly (tape on, fully differentiable) when it is concrete.
Tensor-dependent python `if` under `to_static` would silently bake one
branch; these are the supported forms.
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp

from ..core import autograd
from ..core.dispatch import apply_op
from ..core.tensor import Tensor


def _no_record():
    """Composite control-flow internals record as ONE op — their branch
    bodies' sub-dispatches must not leak into the program (they would
    replay tracer garbage)."""
    from ..core.dispatch import no_static_record

    return no_static_record()

from .nn_compat import *  # noqa: F401,F403 — fluid-style builders
from . import nn_compat as _nn_compat

__all__ = ["cond", "while_loop"] + list(_nn_compat.__all__)


def _is_traced(t) -> bool:
    arr = t._value() if isinstance(t, Tensor) else t
    return isinstance(arr, jax.core.Tracer)


def _static_recording() -> bool:
    """True while a Program is recording (enable_static + program scope).
    Record-time values are concrete PLACEHOLDERS, so a concrete pred must
    NOT fold the control flow away — the baked branch would replay for
    every future feed (a cond over a feed-derived pred recorded only
    `x - 1` before this check existed)."""
    from ..core import dispatch

    return getattr(dispatch, "_static_record_hook", None) is not None


def _leaves_of(fn) -> list:
    """Tensors a branch/body function can read without taking them as
    operands: bound-Layer state, plus any Tensor (or Layer) captured in
    the function's closure — the reference's cond/while_loop let
    closures just work, so a closured feed placeholder must become a
    lifted input rather than a baked record-time constant."""
    from ..nn.layer_base import Layer

    def layer_state(layer):
        return list(layer.parameters()) + \
            [b for _, b in layer.named_buffers()]

    leaves = []
    layer = getattr(fn, "__self__", None)
    if isinstance(layer, Layer):
        leaves.extend(layer_state(layer))
    candidates = []
    for cell in getattr(fn, "__closure__", None) or ():
        try:
            candidates.append(cell.cell_contents)
        except ValueError:
            continue
    code = getattr(fn, "__code__", None)
    glb = getattr(fn, "__globals__", None)
    if code is not None and glb is not None:
        # module-level tensors the function reads (co_names bounds this
        # to names it actually references)
        candidates.extend(glb.get(nm) for nm in code.co_names
                          if nm in glb)
    for v in candidates:
        if isinstance(v, Tensor):
            leaves.append(v)
        elif isinstance(v, Layer):
            leaves.extend(layer_state(v))
    # dedupe by identity, preserving order
    seen, out = set(), []
    for t in leaves:
        if id(t) not in seen:
            seen.add(id(t))
            out.append(t)
    return out


def cond(pred, true_fn: Callable, false_fn: Callable, operands: Sequence = (),
         params: Optional[Sequence] = None, name=None):
    """Two-way branch on a boolean scalar Tensor.

    Eager (concrete pred): runs the taken branch directly — closures and
    autograd work as normal.  Traced (inside to_static): lowers to
    `lax.cond`; both branches must take ``*operands`` and return matching
    structures, and parameters they touch must be listed in ``params`` (or
    the fns be bound Layer methods) so gradients flow — same contract as
    fleet recompute.
    """
    if not _is_traced(pred) and \
            not (_static_recording() and isinstance(pred, Tensor)):
        taken = true_fn if bool(
            pred.item() if isinstance(pred, Tensor) else pred) else false_fn
        return taken(*operands)

    externals = list(params) if params is not None else \
        (_leaves_of(true_fn) + _leaves_of(false_fn))
    # dedupe by identity (the same tensor may be closured in both fns)
    _seen = set()
    externals = [t for t in externals
                 if not (id(t) in _seen or _seen.add(id(t)))]
    tensor_ops = [o for o in operands if isinstance(o, Tensor)]
    n_ops = len(tensor_ops)
    n_outs = _probe_n_outs(true_fn, operands)

    def _branch(fn):
        def g(arrays):
            op_arrays = arrays[:n_ops]
            ext_arrays = arrays[n_ops:]
            it = iter(op_arrays)
            full = [Tensor._wrap(next(it)) if isinstance(o, Tensor) else o
                    for o in operands]
            saved = [(t, t._data) for t in externals]
            try:
                for t, a in zip(externals, ext_arrays):
                    t._data = a
                with autograd.no_grad(), _no_record():
                    out = fn(*full)
            finally:
                for t, a in saved:
                    t._data = a
            outs = out if isinstance(out, (tuple, list)) else (out,)
            flat = tuple(o._value() if isinstance(o, Tensor)
                         else jnp.asarray(o) for o in outs)
            return flat[0] if n_outs == 1 else flat
        return g

    def primal(pred_arr, *arrays):
        return jax.lax.cond(jnp.asarray(pred_arr).reshape(()),
                            _branch(true_fn), _branch(false_fn),
                            list(arrays))

    return apply_op("cond", primal,
                    [pred] + tensor_ops + list(externals), n_outs=n_outs)


def _probe_n_outs(fn, operands) -> int:
    """Branch output arity via eval_shape (no FLOPs, no tape)."""
    import jax

    def f(*arrs):
        it = iter(arrs)
        full = [Tensor._wrap(next(it)) if isinstance(o, Tensor) else o
                for o in operands]
        with autograd.no_grad(), _no_record():
            out = fn(*full)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        return tuple(o._value() if isinstance(o, Tensor) else jnp.asarray(o)
                     for o in outs)

    shapes = jax.eval_shape(
        f, *[o._value() for o in operands if isinstance(o, Tensor)])
    return len(shapes)


def while_loop(cond_fn: Callable, body_fn: Callable, loop_vars: Sequence,
               is_test: bool = False, name=None):
    """``while cond_fn(*vars): vars = body_fn(*vars)``.

    Eager: a python loop — differentiable, any closure.  Traced: lowers to
    `lax.while_loop` (forward-only, like XLA's While; the reference's
    backward-of-while is likewise restricted) over the Tensor loop vars;
    body/cond must be pure functions of them.
    """
    loop_vars = list(loop_vars)
    traced = any(_is_traced(v) for v in loop_vars if isinstance(v, Tensor)) \
        or (_static_recording()
            and any(isinstance(v, Tensor) for v in loop_vars))
    if not traced:
        out = loop_vars
        while bool(_as_scalar(cond_fn(*out))):
            res = body_fn(*out)
            out = list(res) if isinstance(res, (tuple, list)) else [res]
        return out

    idx = [i for i, v in enumerate(loop_vars) if isinstance(v, Tensor)]

    def _call(fn, arrays, scalar=False):
        full = list(loop_vars)
        for j, i in enumerate(idx):
            full[i] = Tensor._wrap(arrays[j])
        with autograd.no_grad(), _no_record():
            out = fn(*full)
        if scalar:
            return jnp.asarray(
                out._value() if isinstance(out, Tensor) else out).reshape(())
        outs = out if isinstance(out, (tuple, list)) else (out,)
        res = list(arrays)
        k = 0
        for i, o in enumerate(outs):
            if isinstance(o, Tensor):
                res[k] = o._value()
                k += 1
        return tuple(res)

    def primal(*arrays):
        return jax.lax.while_loop(
            lambda vs: _call(cond_fn, vs, scalar=True),
            lambda vs: _call(body_fn, vs),
            tuple(arrays))

    tensors = [loop_vars[i] for i in idx]
    outs = apply_op("while_loop", primal, tensors, n_outs=len(tensors))
    outs = outs if isinstance(outs, tuple) else (outs,)
    result = list(loop_vars)
    for j, i in enumerate(idx):
        result[i] = outs[j]
    return result


def _as_scalar(v):
    if isinstance(v, Tensor):
        return v.item()
    return v
