"""paddle.autograd.PyLayer — user-defined forward/backward (reference
`python/paddle/autograd/py_layer.py`: PyLayer + PyLayerContext).

TPU-native realization: `apply` runs the user's forward under `no_grad`
(its internal ops are invisible to the tape, exactly like the reference's
custom-op boundary) and records ONE TapeNode whose vjp is the user's
`backward`. The backward receives/returns Tensors; the tape sees raw
arrays, so a thin shim converts at the boundary."""
from __future__ import annotations

from typing import Any, List

from ..core.autograd import TapeNode, no_grad, is_grad_enabled
from ..core.tensor import Tensor


class PyLayerContext:
    """`ctx` object passed to forward/backward (reference
    PyLayerContext: save_for_backward / saved_tensor + free attrs)."""

    def __init__(self):
        self._saved: tuple = ()
        self._materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tuple(tensors)

    def saved_tensor(self):
        return self._saved

    def set_materialize_grads(self, value: bool):
        """False: outputs that received no gradient pass None to
        backward instead of materialized zero tensors."""
        self._materialize_grads = bool(value)


class PyLayerMeta(type):
    def __init__(cls, name, bases, attrs):
        super().__init__(name, bases, attrs)
        if any(isinstance(b, PyLayerMeta) for b in bases) \
                and "apply" in attrs:
            raise RuntimeError(
                "do not override PyLayer.apply; define forward/backward")


class PyLayer(metaclass=PyLayerMeta):
    """Subclass with @staticmethod forward(ctx, *args) and
    backward(ctx, *grads); call via MyLayer.apply(*args)."""

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError(
            "PyLayer subclasses must implement forward")

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError(
            "PyLayer subclasses must implement backward")

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()

        with no_grad():
            outs = cls.forward(ctx, *args, **kwargs)

        single_out = not isinstance(outs, (tuple, list))
        outs_list: List[Tensor] = [outs] if single_out else list(outs)
        for o in outs_list:
            if not isinstance(o, Tensor):
                raise TypeError(
                    "PyLayer.forward must return Tensor(s); got "
                    f"{type(o).__name__}")

        # Re-wrap every output in a FRESH Tensor over the same payload.
        # Returning an input (or any tensor with live tape history)
        # unchanged must neither clobber that tensor's _grad_node nor
        # mutate its stop_gradient — this node owns only its own views
        # (the reference's forward outputs are likewise new VarBases).
        arg_ids = {id(a) for a in args if isinstance(a, Tensor)}
        fresh: List[Tensor] = []
        for o in outs_list:
            if id(o) in arg_ids or o._grad_node is not None:
                fresh.append(Tensor._wrap(o._value()))
            else:
                fresh.append(o)
        outs_list = fresh

        # positional Tensor inputs that want grad define the node inputs
        # (kwargs never receive grads — matches the reference contract)
        diff_inputs = [
            a for a in args
            if isinstance(a, Tensor) and not a.stop_gradient
        ]
        if not is_grad_enabled() or not diff_inputs:
            for o in outs_list:
                o.stop_gradient = True
            return outs_list[0] if single_out else tuple(outs_list)

        for o in outs_list:
            o.stop_gradient = False

        def vjp_fn(cotangents):
            cts = (cotangents,) if not isinstance(cotangents, tuple) \
                else cotangents
            ct_tensors = [None if c is None else Tensor._wrap(c)
                          for c in cts]
            with no_grad():
                grads = cls.backward(ctx, *ct_tensors)
            if not isinstance(grads, (tuple, list)):
                grads = (grads,)
            n_expected = len(diff_inputs)
            if len(grads) != n_expected:
                raise ValueError(
                    f"{cls.__name__}.backward returned {len(grads)} "
                    f"gradients for {n_expected} differentiable inputs")
            out: List[Any] = []
            for g in grads:
                if g is None:
                    out.append(None)
                elif isinstance(g, Tensor):
                    out.append(g._value())
                else:
                    out.append(g)
            return tuple(out)

        node = TapeNode(vjp_fn, inputs=diff_inputs, outputs=outs_list,
                        name=cls.__name__,
                        materialize=ctx._materialize_grads)
        for o in outs_list:
            o._grad_node = node
        return outs_list[0] if single_out else tuple(outs_list)


# reference alias (paddle 2.3 exposes both under autograd)
LegacyPyLayer = PyLayer
