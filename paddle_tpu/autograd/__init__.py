"""paddle.autograd parity namespace (reference: python/paddle/autograd) —
re-exports the eager tape engine from core.autograd."""
from ..core.autograd import (  # noqa: F401
    backward, grad, no_grad, enable_grad, set_grad_enabled, is_grad_enabled,
)
from .py_layer import PyLayer, PyLayerContext  # noqa: F401

__all__ = ["backward", "grad", "no_grad", "enable_grad", "set_grad_enabled",
           "is_grad_enabled", "PyLayer", "PyLayerContext"]
