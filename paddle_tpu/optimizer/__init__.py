"""paddle.optimizer surface (reference: python/paddle/optimizer)."""
from .optimizer import (
    Optimizer, SGD, Momentum, Adam, AdamW, Adamax, Adagrad, RMSProp,
    Adadelta, Lamb, Lars, LarsMomentumOptimizer,
)
from . import lr
