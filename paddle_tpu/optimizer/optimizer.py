"""Optimizer base (reference: python/paddle/optimizer/optimizer.py:91).

TPU-native design: parameter updates are pure-jax expressions applied through
the trace-aware ``_set_data`` path, so ``opt.step()`` inside a ``to_static``
train step compiles into the same XLA program as forward+backward (the
reference reaches the same shape via fused adamw ops in ProgramDesc).
Accumulator state lives in Tensors keyed by parameter name, mirroring the
reference's accumulator scope vars.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.autograd import no_grad
from ..core import dtype as dtype_mod
from .lr import LRScheduler


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        self._learning_rate = learning_rate
        # reference optimizer.py:91 accepts a flat Tensor list OR a list
        # of group dicts ({'params': [...], 'learning_rate': factor,
        # 'weight_decay'/'beta1'/...: per-group overrides}); group
        # 'learning_rate' multiplies the global lr, like
        # optimize_attr['learning_rate'] (_create_param_lr :566)
        self._param_groups = None
        self._param_overrides: Dict[int, dict] = {}
        if parameters is not None:
            plist = list(parameters)
            if plist and isinstance(plist[0], dict):
                flat: list = []
                self._param_groups = []
                seen = set()
                for group in plist:
                    g = dict(group)
                    if "params" not in g:
                        raise ValueError(
                            "each optimizer parameter group dict needs a "
                            f"'params' key; got keys {sorted(g)}")
                    ps = g.get("params")
                    ps = [ps] if isinstance(ps, Tensor) else list(ps)
                    g["params"] = ps
                    ov = {k: v for k, v in g.items() if k != "params"}
                    for p in ps:
                        if id(p) in seen:
                            raise ValueError(
                                "some parameters appear in more than one "
                                "optimizer parameter group")
                        seen.add(id(p))
                        if ov:
                            self._param_overrides[id(p)] = ov
                        flat.append(p)
                    self._param_groups.append(g)
                self._parameter_list = flat
            else:
                self._parameter_list = plist
        else:
            self._parameter_list = None
        self._lr_factor = 1.0
        self._grad_clip = grad_clip
        self._name = name
        self._regularizer = None
        if isinstance(weight_decay, float) or isinstance(weight_decay, int):
            self._weight_decay = float(weight_decay)
        elif weight_decay is None:
            self._weight_decay = None
        else:  # paddle.regularizer.L1Decay/L2Decay (or coeff-duck-typed)
            self._weight_decay = float(getattr(weight_decay, "_coeff", getattr(
                weight_decay, "coeff", 0.0)))
            if hasattr(weight_decay, "_grad_term"):
                self._regularizer = weight_decay
        # name → {acc_name: Tensor}
        self._accumulators: Dict[str, Dict[str, Tensor]] = {}
        self._acc_inits: Dict[tuple, object] = {}  # float init or callable thunk
        self._global_step = 0

    # -- lr ----------------------------------------------------------------

    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError(
                "set_lr is not allowed when learning rate is an LRScheduler; "
                "use scheduler.step() instead")
        self._learning_rate = float(value)
        if self._lr_t is not None:
            self._lr_t._set_data(jnp.asarray(float(value), dtype=jnp.float32))

    _lr_t = None

    def _lr_array(self):
        """Learning rate as a jax scalar.  Under a to_static trace the value
        is read through a persistent Tensor so it becomes a *program input* —
        scheduler steps and set_lr between compiled calls do not recompile
        (the reference feeds lr as a scope variable for the same reason)."""
        from ..core import tensor as tensor_mod

        if isinstance(self._learning_rate, LRScheduler):
            lr = self._learning_rate._lr_tensor()._value()
        elif tensor_mod._trace_hook is not None:
            if self._lr_t is None:
                self._lr_t = tensor_mod.external_tensor(
                    np.float32(self.get_lr()))
            lr = self._lr_t._value()
        else:
            lr = jnp.asarray(self.get_lr(), dtype=jnp.float32)
        if self._lr_factor != 1.0:
            # per-group factor (reference optimize_attr['learning_rate'],
            # applied as global_lr * param_lr in _create_param_lr :580)
            lr = lr * jnp.float32(self._lr_factor)
        return lr

    # -- accumulators -------------------------------------------------------

    def _param_key(self, p: Tensor) -> str:
        return p.name or f"param_{id(p)}"

    def _get_accumulator(self, name: str, p: Tensor, init=0.0,
                         dtype=None, shape=None, init_from=None) -> Tensor:
        key = self._param_key(p)
        accs = self._accumulators.setdefault(key, {})
        if name not in accs:
            from ..core import tensor as tensor_mod

            dt = dtype or p._value().dtype
            shape = tuple(p.shape) if shape is None else tuple(shape)
            # external_tensor: accumulators lazily created inside a traced
            # train step must still be persistent program state
            if init_from is not None:
                accs[name] = tensor_mod.external_tensor(init_from)
            else:
                accs[name] = tensor_mod.external_tensor(
                    lambda: jnp.full(shape, init, dtype=dt))
            # init value kept for skip-step rollback (amp GradScaler);
            # derived accumulators (master weights) store their thunk so
            # rollback re-derives from the rolled-back param
            self._acc_inits[(key, name)] = (
                init_from if init_from is not None else init)
        return accs[name]

    # -- main entry points ---------------------------------------------------

    def _collect_params_grads(self):
        params = self._parameter_list
        if params is None:
            raise ValueError("optimizer constructed without parameters")
        out = []
        for p in params:
            if not getattr(p, "trainable", True):
                continue
            g = p.grad
            if g is None:
                continue
            out.append((p, g))
        return out

    # attr <-> group-dict key pairs a group may override (reference
    # _update_param_group in each optimizer subclass).  weight decay
    # lives under different attrs per family: coupled `_weight_decay`
    # (SGD/Momentum regularizer fold), decoupled `_wd` (AdamW/Lamb),
    # `_lars_weight_decay` (Lars) — swap every one that exists.
    _GROUP_OVERRIDE_ATTRS = (
        ("_weight_decay", "weight_decay"), ("_wd", "weight_decay"),
        ("_lars_weight_decay", "weight_decay"),
        ("_beta1", "beta1"), ("_beta2", "beta2"),
        ("_epsilon", "epsilon"), ("_momentum", "momentum"))

    def _update_with_overrides(self, p, garr):
        ov = self._param_overrides.get(id(p))
        if not ov:
            self._update_param(p, garr)
            return
        saved = {}
        for attr, key in self._GROUP_OVERRIDE_ATTRS:
            if key in ov and hasattr(self, attr):
                saved[attr] = getattr(self, attr)
                setattr(self, attr, ov[key])
        if "learning_rate" in ov:
            self._lr_factor = float(ov["learning_rate"])
        try:
            self._update_param(p, garr)
        finally:
            for attr, val in saved.items():
                setattr(self, attr, val)
            self._lr_factor = 1.0

    @no_grad()
    def step(self):
        params_grads = self._collect_params_grads()
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        self._global_step += 1
        for p, g in params_grads:
            garr = g._value() if isinstance(g, Tensor) else g
            if garr.dtype in (jnp.bfloat16, jnp.float16):
                garr = garr.astype(jnp.float32)
            self._update_with_overrides(p, garr)

    minimize_step = step

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ..core import dispatch

        if dispatch._static_record_hook is not None:
            # static-graph idiom: minimize marks the recording program as
            # a TRAIN program (reference: the ProgramDesc carries the
            # backward + sgd ops after minimize, so exe.run applies
            # updates every call).  Never run an eager step here — the
            # placeholders hold dummy values.
            from ..nn.layer_base import Parameter
            from ..static import program as prog_mod

            prog = prog_mod.default_main_program()
            if parameters is not None:
                self._parameter_list = list(parameters)
            if self._parameter_list is None:
                seen, params = set(), []
                for op in prog._raw:
                    for a in op.inputs:
                        if (isinstance(a, Parameter)
                                and not a.stop_gradient
                                and getattr(a, "trainable", True)
                                and id(a) not in seen):
                            seen.add(id(a))
                            params.append(a)
                if not params:
                    raise ValueError(
                        "minimize() found no trainable Parameters in the "
                        "recording program (was it already run/finalized, "
                        "or built without static.nn/create_parameter "
                        "layers?); pass parameters= explicitly")
                self._parameter_list = params
            prog._train_spec = (loss, self)
            prog._train_cache.clear()     # a re-minimize replaces the spec
            return None, None
        if parameters is not None and self._parameter_list is None:
            self._parameter_list = list(parameters)
        loss.backward()
        self.step()
        return None, None

    def clear_grad(self, set_to_zero=False):
        if self._parameter_list:
            for p in self._parameter_list:
                p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    def _apply(self, p: Tensor, new_value):
        p._set_data(new_value.astype(p._value().dtype))

    # -- master weights (AMP-O2 / reference multi_precision) ---------------
    # When a parameter is stored in a low dtype (bf16/f16 after
    # amp.decorate), the optimizer keeps an f32 master copy in its
    # accumulators: updates accumulate in f32 and the param gets the
    # cast-down view, so lr*grad increments far below bf16 resolution are
    # not lost (reference: optimizer.py _multi_precision master weights).
    def _is_low_precision(self, p: Tensor):
        return p._data.dtype in (jnp.bfloat16, jnp.float16)

    def _master_tensor(self, p: Tensor) -> Tensor:
        # init thunk reads p._data (the raw payload), which stays the
        # concrete pre-step array even while a to_static trace is
        # active (trace reads go through env, not the attribute)
        return self._get_accumulator(
            "master_weight", p, dtype=jnp.float32,
            init_from=lambda: p._data.astype(jnp.float32))

    def _master_value(self, p: Tensor):
        if self._is_low_precision(p):
            return self._master_tensor(p)._value().astype(jnp.float32)
        return p._value().astype(jnp.float32)

    def _apply_master(self, p: Tensor, new32):
        if self._is_low_precision(p):
            self._master_tensor(p)._set_data(new32)
        self._apply(p, new32)

    def _update_param(self, p: Tensor, g):
        raise NotImplementedError

    def _decayed_grad(self, p, g):
        """Regularization folded into the gradient (reference: coupled
        weight decay for SGD/Momentum family). L1/L2 shape comes from the
        paddle.regularizer object when one was passed."""
        if self._regularizer is not None:
            g = g + self._regularizer._grad_term(
                p._value()).astype(g.dtype)
        elif self._weight_decay:
            g = g + self._weight_decay * p._value().astype(g.dtype)
        return g

    # -- state dict ----------------------------------------------------------

    def state_dict(self):
        sd = {}
        for pkey, accs in self._accumulators.items():
            for aname, t in accs.items():
                sd[f"{pkey}/{aname}"] = t
        sd["@global_step"] = self._global_step
        if isinstance(self._learning_rate, LRScheduler):
            sd["@lr_scheduler"] = self._learning_rate.state_dict()
        return sd

    def set_state_dict(self, state_dict):
        import numpy as np

        # Saved accumulator keys carry the SAVING run's parameter names.
        # Auto-generated names (linear_0.weight, …) restart per process, so
        # a model built later in the same process gets different names; map
        # saved param keys onto the current parameter list by position (the
        # accumulator dict iterates in parameter order on both sides).
        saved_pkeys = []
        for k in state_dict:
            if k.startswith("@"):
                continue
            pk = k.rsplit("/", 1)[0]
            if pk not in saved_pkeys:
                saved_pkeys.append(pk)
        params = list(self._parameter_list or [])
        cur_names = [self._param_key(p) for p in params]
        remap = {}
        if saved_pkeys and set(saved_pkeys) != set(cur_names) \
                and len(saved_pkeys) == len(cur_names):
            remap = dict(zip(saved_pkeys, cur_names))
            # validate the positional pairing: every non-scalar saved
            # accumulator must match its mapped parameter's shape — else
            # this is a different model, not a renamed one
            shapes = {self._param_key(p): tuple(p.shape) for p in params}
            for k, v in state_dict.items():
                if k.startswith("@"):
                    continue
                pk = remap[k.rsplit("/", 1)[0]]
                vs = tuple(getattr(v, "shape", ()) or ())
                if vs and vs != shapes[pk]:
                    raise ValueError(
                        f"optimizer state {k!r} (shape {vs}) does not fit "
                        f"parameter {pk!r} (shape {shapes[pk]}); the saved "
                        f"state appears to be for a different model")

        for k, v in state_dict.items():
            if k == "@global_step":
                self._global_step = int(v)
                continue
            if k == "@lr_scheduler":
                if isinstance(self._learning_rate, LRScheduler):
                    self._learning_rate.set_state_dict(v)
                continue
            pkey, aname = k.rsplit("/", 1)
            pkey = remap.get(pkey, pkey)
            arr = v._value() if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
            accs = self._accumulators.setdefault(pkey, {})
            existing = accs.get(aname)
            if existing is not None \
                    and tuple(existing.shape) == tuple(arr.shape) \
                    and existing._value().dtype == arr.dtype:
                # restore IN PLACE: a compiled train step lifted the
                # existing accumulator tensor as persistent program
                # state, so a mid-run restore (divergence-sentry
                # rollback) must write through the same object —
                # replacing it would leave the program updating a
                # tensor the optimizer no longer reads
                existing._set_data(arr)
            else:
                accs[aname] = Tensor._wrap(arr)


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _update_param(self, p, g):
        g = self._decayed_grad(p, g)
        lr = self._lr_array()
        self._apply_master(p, self._master_value(p)
                           - lr * g.astype(jnp.float32))


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _update_param(self, p, g):
        g = self._decayed_grad(p, g)
        # all update math in f32: an f16/bf16 lr or velocity would flush
        # warmup-scale values (< f16 subnormal floor) to zero
        lr = self._lr_array()
        g32 = g.astype(jnp.float32)
        vel = self._get_accumulator("velocity", p, dtype=jnp.float32)
        v_new = self._momentum * vel._value() + g32
        vel._set_data(v_new)
        if self._use_nesterov:
            upd = g32 + self._momentum * v_new
        else:
            upd = v_new
        self._apply_master(p, self._master_value(p) - lr * upd)


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None, *, moment_dtype=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._multi_precision = multi_precision
        # TPU extension (not in the reference API): store moment1/moment2 in
        # a narrower dtype ("bfloat16") to halve optimizer HBM traffic —
        # ~8 B/param/step saved; at 345M params that is ~2.8 GB/step off the
        # AdamW update's ~9.7 GB.  The update math itself stays f32 (moments
        # are widened on read, rounded on store).  bf16's 8 mantissa bits
        # add ~0.4% relative noise to the moments; default stays f32.
        self._moment_dtype = (None if moment_dtype is None
                              else jnp.dtype(moment_dtype))

    def _adam_update(self, p, g, decoupled_wd=0.0):
        lr = self._lr_array()
        mdt = self._moment_dtype or jnp.float32
        m = self._get_accumulator("moment1", p, dtype=mdt)
        v = self._get_accumulator("moment2", p, dtype=mdt)
        b1p = self._get_accumulator("beta1_pow", p, init=1.0, dtype=jnp.float32, shape=())
        b2p = self._get_accumulator("beta2_pow", p, init=1.0, dtype=jnp.float32, shape=())
        g32 = g.astype(jnp.float32)
        m_new = self._beta1 * m._value().astype(jnp.float32) \
            + (1 - self._beta1) * g32
        v_new = self._beta2 * v._value().astype(jnp.float32) \
            + (1 - self._beta2) * jnp.square(g32)
        b1p_new = b1p._value() * self._beta1
        b2p_new = b2p._value() * self._beta2
        m._set_data(m_new.astype(mdt))
        v._set_data(v_new.astype(mdt))
        b1p._set_data(b1p_new)
        b2p._set_data(b2p_new)
        m_hat = m_new / (1.0 - b1p_new)
        v_hat = v_new / (1.0 - b2p_new)
        p32 = self._master_value(p)
        if decoupled_wd:
            p32 = p32 * (1.0 - lr * decoupled_wd)
        new32 = p32 - lr * m_hat / (jnp.sqrt(v_hat) + self._epsilon)
        self._apply_master(p, new32)

    def _update_param(self, p, g):
        g = self._decayed_grad(p, g)
        self._adam_update(p, g)


class AdamW(Adam):
    """Decoupled weight decay (reference: optimizer/adamw.py → fused adamw op)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None,
                 *, moment_dtype=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision, name,
                         moment_dtype=moment_dtype)
        self._wd = float(weight_decay) if not hasattr(weight_decay, "_coeff") \
            else float(weight_decay._coeff)
        self._apply_decay_param_fun = apply_decay_param_fun

    def _update_param(self, p, g):
        wd = self._wd
        if self._apply_decay_param_fun is not None and \
                not self._apply_decay_param_fun(p.name):
            wd = 0.0
        self._adam_update(p, g, decoupled_wd=wd)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _update_param(self, p, g):
        g = self._decayed_grad(p, g)
        lr = self._lr_array()
        m = self._get_accumulator("moment", p, dtype=jnp.float32)
        u = self._get_accumulator("inf_norm", p, dtype=jnp.float32)
        b1p = self._get_accumulator("beta1_pow", p, init=1.0, dtype=jnp.float32, shape=())
        g32 = g.astype(jnp.float32)
        m_new = self._beta1 * m._value() + (1 - self._beta1) * g32
        u_new = jnp.maximum(self._beta2 * u._value(), jnp.abs(g32))
        b1p_new = b1p._value() * self._beta1
        m._set_data(m_new); u._set_data(u_new); b1p._set_data(b1p_new)
        self._apply_master(p, self._master_value(p)
                           - lr / (1 - b1p_new) * m_new
                           / (u_new + self._epsilon))


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _update_param(self, p, g):
        g = self._decayed_grad(p, g)
        lr = self._lr_array()
        acc = self._get_accumulator("moment", p, init=self._init_acc,
                                    dtype=jnp.float32)
        g32 = g.astype(jnp.float32)
        acc_new = acc._value() + jnp.square(g32)
        acc._set_data(acc_new)
        self._apply_master(p, self._master_value(p)
                           - lr * g32
                           / (jnp.sqrt(acc_new) + self._epsilon))


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _update_param(self, p, g):
        g = self._decayed_grad(p, g)
        lr = self._lr_array()
        ms = self._get_accumulator("mean_square", p, dtype=jnp.float32)
        mom = self._get_accumulator("momentum", p, dtype=jnp.float32)
        g32 = g.astype(jnp.float32)
        ms_new = self._rho * ms._value() + (1 - self._rho) * jnp.square(g32)
        ms._set_data(ms_new)
        denom = ms_new
        if self._centered:
            mg = self._get_accumulator("mean_grad", p, dtype=jnp.float32)
            mg_new = self._rho * mg._value() + (1 - self._rho) * g32
            mg._set_data(mg_new)
            denom = ms_new - jnp.square(mg_new)
        upd = self._momentum * mom._value() + lr * g32 / jnp.sqrt(denom + self._epsilon)
        mom._set_data(upd)
        self._apply_master(p, self._master_value(p) - upd)


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon, self._rho = epsilon, rho

    def _update_param(self, p, g):
        g = self._decayed_grad(p, g)
        lr = self._lr_array()
        avg_sq_g = self._get_accumulator("avg_squared_grad", p, dtype=jnp.float32)
        avg_sq_u = self._get_accumulator("avg_squared_update", p, dtype=jnp.float32)
        g32 = g.astype(jnp.float32)
        asg = self._rho * avg_sq_g._value() + (1 - self._rho) * jnp.square(g32)
        upd = -jnp.sqrt((avg_sq_u._value() + self._epsilon) /
                        (asg + self._epsilon)) * g32
        asu = self._rho * avg_sq_u._value() + (1 - self._rho) * jnp.square(upd)
        avg_sq_g._set_data(asg)
        avg_sq_u._set_data(asu)
        self._apply_master(p, self._master_value(p) + lr * upd)


class Lars(Optimizer):
    """LARS momentum (reference: fluid/optimizer.py:1969
    LarsMomentumOptimizer; kernel lars_momentum_op.h):

        local_lr = lr * lars_coeff * ||p|| / (eps + ||g|| + wd * ||p||)
        velocity = mu * velocity + local_lr * (g + wd * p)
        p       -= velocity

    Layers whose name matches ``exclude_from_weight_decay`` skip the decay
    term (both in local_lr and the velocity update), like the reference's
    name-substring match.
    """

    def __init__(self, learning_rate, momentum, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 exclude_from_weight_decay=None, epsilon=0,
                 multi_precision=False, rescale_grad=1.0, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._momentum = float(momentum)
        self._lars_coeff = float(lars_coeff)
        self._lars_weight_decay = float(lars_weight_decay)
        self._epsilon = float(epsilon)
        self._exclude = list(exclude_from_weight_decay or [])
        self._rescale_grad = float(rescale_grad)

    def _update_param(self, p, g):
        lr = self._lr_array()
        g32 = g.astype(jnp.float32) * self._rescale_grad
        p32 = self._master_value(p)
        wd = self._lars_weight_decay
        pname = p.name or ""
        if any(tok in pname for tok in self._exclude):
            wd = 0.0
        p_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
        g_norm = jnp.sqrt(jnp.sum(jnp.square(g32)))
        # reference kernel guard: fall back to plain lr when either norm
        # is zero (fresh zero-init params would otherwise stall at 0)
        local_lr = jnp.where(
            (p_norm > 0) & (g_norm > 0),
            lr * self._lars_coeff * p_norm
            / (self._epsilon + g_norm + wd * p_norm),
            lr)
        vel = self._get_accumulator("velocity", p, dtype=jnp.float32)
        v_new = self._momentum * vel._value() + local_lr * (g32 + wd * p32)
        vel._set_data(v_new)
        self._apply_master(p, p32 - v_new)


# reference class name (fluid/optimizer.py:1969)
LarsMomentumOptimizer = Lars


class Lamb(Optimizer):
    """Layer-wise adaptive moments (reference: optimizer/lamb.py; the
    distributed_fused_lamb op family collapses to this math under jit)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._wd = lamb_weight_decay
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _update_param(self, p, g):
        lr = self._lr_array()
        m = self._get_accumulator("moment1", p, dtype=jnp.float32)
        v = self._get_accumulator("moment2", p, dtype=jnp.float32)
        b1p = self._get_accumulator("beta1_pow", p, init=1.0, dtype=jnp.float32, shape=())
        b2p = self._get_accumulator("beta2_pow", p, init=1.0, dtype=jnp.float32, shape=())
        g32 = g.astype(jnp.float32)
        m_new = self._beta1 * m._value() + (1 - self._beta1) * g32
        v_new = self._beta2 * v._value() + (1 - self._beta2) * jnp.square(g32)
        b1p_new = b1p._value() * self._beta1
        b2p_new = b2p._value() * self._beta2
        m._set_data(m_new); v._set_data(v_new)
        b1p._set_data(b1p_new); b2p._set_data(b2p_new)
        m_hat = m_new / (1 - b1p_new)
        v_hat = v_new / (1 - b2p_new)
        p32 = self._master_value(p)
        wd = self._wd
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd = 0.0
        r = m_hat / (jnp.sqrt(v_hat) + self._epsilon) + wd * p32
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        self._apply_master(p, p32 - lr * trust * r)
