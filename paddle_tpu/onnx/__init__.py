"""paddle.onnx (reference: python/paddle/onnx/export.py — a thin wrapper
that requires the external ``paddle2onnx`` package at call time).

TPU-native note: the in-tree deployment format is ``jit.save``'s
serialized StableHLO (jax.export), which is the XLA-ecosystem
equivalent; ONNX conversion would go StableHLO→ONNX via external
tooling.  Like the reference without paddle2onnx installed, ``export``
raises with instructions.
"""

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    try:
        import paddle2onnx  # noqa: F401
    except ImportError:
        raise RuntimeError(
            "paddle.onnx.export requires the external 'paddle2onnx' "
            "converter (the reference has the same runtime dependency). "
            "For TPU-native deployment use paddle.jit.save, which "
            "serializes the program as portable StableHLO.")
    raise NotImplementedError(
        "paddle2onnx does not understand the TPU build's StableHLO "
        "artifacts; export via jit.save + external StableHLO->ONNX "
        "tooling")
