"""paddle.metric (reference: python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        """Optional pre-processing on Tensors; default passthrough."""
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
            label_np = label_np.squeeze(-1)
        topk_idx = np.argsort(-pred_np, axis=-1)[..., : self.maxk]
        correct = topk_idx == label_np[..., None]
        return correct

    def update(self, correct, *args):
        correct = _np(correct)
        flat = correct.reshape(-1, correct.shape[-1])
        n = flat.shape[0]
        res = []
        for i, k in enumerate(self.topk):
            hits = float(flat[:, :k].any(axis=-1).sum())
            self.total[i] += hits
            self.count[i] += n
            res.append(hits / max(n, 1))
        return res[0] if len(res) == 1 else res

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = (_np(preds).reshape(-1) > 0.5).astype(np.int64)
        labels = _np(labels).reshape(-1).astype(np.int64)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = (_np(preds).reshape(-1) > 0.5).astype(np.int64)
        labels = _np(labels).reshape(-1).astype(np.int64)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC AUC via threshold bucketing (reference: metrics.py Auc)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds = _np(preds)
        labels = _np(labels).reshape(-1)
        if preds.ndim == 2:
            preds = preds[:, -1]
        else:
            preds = preds.reshape(-1)
        buckets = (preds * self.num_thresholds).astype(np.int64)
        buckets = np.clip(buckets, 0, self.num_thresholds)
        for b, l in zip(buckets, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            p, n = self._stat_pos[i], self._stat_neg[i]
            auc += n * (tot_pos + p / 2.0)
            tot_pos += p
            tot_neg += n
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        return auc / (tot_pos * tot_neg)

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional top-k accuracy (paddle.metric.accuracy)."""
    import jax.numpy as jnp
    from ..ops._helpers import nondiff

    def _primal(pred, lbl):
        topk = jnp.argsort(-pred, axis=-1)[..., :k]
        l = lbl
        if l.ndim == pred.ndim and l.shape[-1] == 1:
            l = jnp.squeeze(l, -1)
        hit = (topk == l[..., None]).any(axis=-1)
        return jnp.mean(hit.astype(jnp.float32))

    return nondiff("accuracy", _primal, [input, label])
