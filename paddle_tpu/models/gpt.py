"""GPT decoder-only transformer — the flagship model family.

Reference parity: the GPT used across the reference's hybrid-parallel and
auto-parallel tests (unittests/auto_parallel_gpt_model.py; fused kernels
operators/fused/fused_attention_op.cu, fused_feedforward_op) and the
Megatron construction of mp_layers.py.

TPU-native design decisions:
- Q/K/V is ONE ColumnParallelLinear of width 3*hidden whose output dim is
  laid out head-major [n_heads, 3*head_dim]: after reshape the sharded dim
  lands on n_heads, so GSPMD keeps heads on the "model" axis through the
  whole attention block with zero resharding (a fused-qkv layout the
  reference implements inside fused_attention with per-rank slicing).
- Attention runs through ops.pallas.flash_attention (Pallas kernel on TPU,
  XLA oracle elsewhere); is_causal=True, no materialized [S,S] mask.
- Sequence dim carries the "sep" axis (context parallelism — capability
  beyond the reference, SURVEY.md §5.7).
- Activation recompute per decoder layer via fleet recompute
  (jax.checkpoint) when config.recompute is on.
- LM head ties the vocab-parallel embedding weight (SharedLayerDesc
  semantics without the grad-sync machinery: one parameter object).
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Optional

import numpy as np
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer_base import Layer
from ..nn.layer.common import Dropout, Embedding, Linear
from ..nn.layer.container import LayerList
from ..nn.layer.norm import LayerNorm
from ..ops.pallas import flash_attention as _flash_attention
from ..distributed.fleet.meta_parallel.parallel_layers.mp_layers import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    ParallelCrossEntropy,
)
from ..distributed.fleet.utils.recompute import recompute
from ..distributed.sharding_spec import (
    BATCH_AXES, MODEL_AXIS, SEQ_AXIS, mark_sharding, set_param_spec,
)


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 1024
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    intermediate_size: Optional[int] = None  # default 4*hidden
    max_position_embeddings: int = 1024
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    initializer_range: float = 0.02
    layer_norm_epsilon: float = 1e-5
    tie_word_embeddings: bool = True
    recompute: bool = False
    # >1 enables chunked compute/collective overlap in every Megatron-TP
    # layer (distributed/fleet/meta_parallel/overlap.py); 1 = baseline
    tp_overlap_chunks: int = 1

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def ffn_size(self) -> int:
        return self.intermediate_size or 4 * self.hidden_size


def gpt_tiny(**kw) -> GPTConfig:
    return GPTConfig(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=64,
                     hidden_dropout_prob=0.0,
                     attention_probs_dropout_prob=0.0, **kw)


def gpt2_345m(**kw) -> GPTConfig:
    return GPTConfig(vocab_size=50304, hidden_size=1024,
                     num_hidden_layers=24, num_attention_heads=16,
                     max_position_embeddings=1024, **kw)


def gpt3_13b(**kw) -> GPTConfig:
    return GPTConfig(vocab_size=50304, hidden_size=5120,
                     num_hidden_layers=40, num_attention_heads=40,
                     max_position_embeddings=2048, **kw)


GPT_CONFIGS = {"tiny": gpt_tiny, "gpt2-345m": gpt2_345m, "gpt3-13b": gpt3_13b}


def _act_spec(last=None):
    return P(BATCH_AXES, SEQ_AXIS, last)


class GPTAttention(Layer):
    """Causal self-attention, heads sharded over the model axis."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.n_heads = config.num_attention_heads
        self.head_dim = config.head_dim
        h = config.hidden_size
        init = I.Normal(std=config.initializer_range)
        # fused qkv, head-major output layout [n_heads, 3*head_dim]
        self.qkv_proj = ColumnParallelLinear(
            h, 3 * h, weight_attr=init, gather_output=False,
            overlap_chunks=config.tp_overlap_chunks)
        self.out_proj = RowParallelLinear(
            h, h, weight_attr=init, input_is_parallel=True,
            overlap_chunks=config.tp_overlap_chunks)
        self.dropout_p = config.attention_probs_dropout_prob

    def forward(self, x, cache_ctx=None):
        B, S, _ = x.shape
        qkv = self.qkv_proj(x)                                  # [B,S,3h]/mp
        qkv = qkv.reshape([B, S, self.n_heads, 3 * self.head_dim])
        qkv = mark_sharding(qkv, P(BATCH_AXES, SEQ_AXIS, MODEL_AXIS, None))
        q, k, v = qkv.split(3, axis=-1)                         # [B,S,H,D]
        if cache_ctx is None:
            ctx = _flash_attention(
                q, k, v, dropout_p=self.dropout_p, is_causal=True,
                training=self.training)
        elif cache_ctx.mode == "prefill":
            # prompt forward writes K/V into the cache; attention routes
            # through the context — ordinary causal for the contiguous
            # layout, gather-by-block-table with a cached-prefix mask for
            # the paged layout (the tail bucket attends onto shared blocks)
            cache_ctx.write_prefill(k, v)
            ctx = cache_ctx.prefill_attention(q, k, v)
        else:               # decode (S == 1) or verify (S == k+1) window
            # write + attend routed through the context: the paged cache
            # may stream blocks through the Pallas flash-decoding kernel
            # instead of gathering a contiguous copy (ROADMAP item 2);
            # verify mode routes the same call to the cache's W-token
            # speculative window attention — models stay single-path
            ctx = cache_ctx.decode_attention(q, k, v)
        ctx = mark_sharding(ctx, P(BATCH_AXES, SEQ_AXIS, MODEL_AXIS, None))
        ctx = ctx.reshape([B, S, self.n_heads * self.head_dim])
        return self.out_proj(ctx)


class GPTMLP(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        init = I.Normal(std=config.initializer_range)
        self.fc1 = ColumnParallelLinear(
            config.hidden_size, config.ffn_size, weight_attr=init,
            gather_output=False,
            overlap_chunks=config.tp_overlap_chunks)
        self.fc2 = RowParallelLinear(
            config.ffn_size, config.hidden_size, weight_attr=init,
            input_is_parallel=True,
            overlap_chunks=config.tp_overlap_chunks)

    def forward(self, x):
        return self.fc2(F.gelu(self.fc1(x), approximate=True))


class GPTDecoderLayer(Layer):
    """Pre-LN block (reference: fused_attention + fused_feedforward
    semantics: LN → attn → dropout → residual; LN → mlp → dropout →
    residual)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        eps = config.layer_norm_epsilon
        self.ln1 = LayerNorm(config.hidden_size, epsilon=eps)
        self.attn = GPTAttention(config)
        self.ln2 = LayerNorm(config.hidden_size, epsilon=eps)
        self.mlp = GPTMLP(config)
        self.dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, x, cache_ctx=None):
        x = x + self.dropout(self.attn(self.ln1(x), cache_ctx))
        x = x + self.dropout(self.mlp(self.ln2(x)))
        return mark_sharding(x, _act_spec())


class GPTEmbeddings(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        init = I.Normal(std=config.initializer_range)
        self.word_embeddings = VocabParallelEmbedding(
            config.vocab_size, config.hidden_size, weight_attr=init,
            overlap_chunks=config.tp_overlap_chunks)
        self.position_embeddings = Embedding(
            config.max_position_embeddings, config.hidden_size,
            weight_attr=init)
        self.dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, position_ids=None):
        if position_ids is None:
            S = input_ids.shape[-1]
            max_pos = self.position_embeddings._num_embeddings
            if S > max_pos:
                raise ValueError(
                    f"sequence length {S} exceeds max_position_embeddings "
                    f"{max_pos}")
            position_ids = Tensor._wrap(jnp.arange(S)[None, :])
        h = self.word_embeddings(input_ids) + \
            self.position_embeddings(position_ids)
        return self.dropout(mark_sharding(h, _act_spec()))


class GPTModel(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.embeddings = GPTEmbeddings(config)
        self.layers = LayerList(
            [GPTDecoderLayer(config) for _ in range(config.num_hidden_layers)])
        self.final_ln = LayerNorm(config.hidden_size,
                                  epsilon=config.layer_norm_epsilon)

    def forward(self, input_ids, position_ids=None, cache_ctx=None):
        if cache_ctx is not None and position_ids is None:
            if cache_ctx.mode != "prefill":
                # decode: each slot's single token sits at that slot's
                # own offset ([slots, 1]); verify: the speculative
                # window's k+1 tokens likewise ([slots, k+1])
                position_ids = cache_ctx.positions()
            else:
                # paged tail prefill: tokens sit past the cached prefix
                # (None for the contiguous layout — default 0..S-1)
                position_ids = cache_ctx.prefill_positions(
                    input_ids.shape[-1])
        h = self.embeddings(input_ids, position_ids)
        for i, layer in enumerate(self.layers):
            if cache_ctx is not None:
                cache_ctx.layer_idx = i
                h = layer(h, cache_ctx)
            elif self.config.recompute and self.training:
                h = recompute(layer, h)
            else:
                h = layer(h)
        return self.final_ln(h)


class GPTForCausalLM(Layer):
    """GPTModel + LM head (tied to the vocab-parallel embedding by
    default)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  bias_attr=False)
            set_param_spec(self.lm_head.weight, P(None, MODEL_AXIS))
        else:
            self.lm_head = None

    def forward(self, input_ids, position_ids=None, cache_ctx=None):
        h = self.gpt(input_ids, position_ids, cache_ctx=cache_ctx)
        if self.lm_head is not None:
            logits = self.lm_head(h)
        else:
            w = self.gpt.embeddings.word_embeddings.weight
            logits = h.matmul(w.t())
        return mark_sharding(logits, _act_spec(last=MODEL_AXIS))

    def compute_loss(self, input_ids, labels, loss_mask=None,
                     position_ids=None, ignore_index: int = -100):
        """Forward + causal-LM loss without materializing [B,S,V] logits.

        Uses ops.fused.fused_linear_cross_entropy (vocab-blockwise streamed
        CE — the memory fusion behind the reference's
        c_softmax_with_cross_entropy path) whenever the head weight is not
        vocab-sharded; under tensor parallelism it falls back to the
        vocab-parallel logits + ParallelCrossEntropy path.
        """
        from ..distributed import mesh as _mesh_mod
        from ..distributed.fleet.meta_parallel.tensor_parallel import (
            shard_batch,
        )
        from ..ops.fused import fused_linear_cross_entropy

        m = _mesh_mod.get_global_mesh()
        # same input placement the DataParallel wrapper's forward applies
        # (callers reach this method through the wrapper's __getattr__)
        input_ids = shard_batch(input_ids, m)
        labels = shard_batch(labels, m)
        if loss_mask is not None:
            loss_mask = shard_batch(loss_mask, m)
        mp = m.shape.get(MODEL_AXIS, 1) if m is not None else 1
        if mp > 1:
            # the criterion is built lazily, after apply_tp_overlap has
            # already stamped the model — read the root's stamp (or the
            # config) so the CE rides the chunked schedule too
            chunks = getattr(self, "_tp_overlap_chunks", 0) \
                or self.config.tp_overlap_chunks
            crit = GPTPretrainingCriterion(ignore_index=ignore_index,
                                           overlap_chunks=chunks)
            return crit(self.forward(input_ids, position_ids), labels,
                        loss_mask)
        h = self.gpt(input_ids, position_ids)
        if self.lm_head is not None:
            return fused_linear_cross_entropy(
                h, self.lm_head.weight, labels, loss_mask=loss_mask,
                ignore_index=ignore_index, transpose_weight=True)
        w = self.gpt.embeddings.word_embeddings.weight
        return fused_linear_cross_entropy(
            h, w, labels, loss_mask=loss_mask, ignore_index=ignore_index)


class _GPTHeadPipe(Layer):
    """Final LN + LM head for the pipelined model.  The tied embedding
    weight is referenced without sublayer registration (single-controller
    sharing — SharedLayerDesc semantics, pp_layers.py:77)."""

    def __init__(self, config: GPTConfig, word_embeddings=None):
        super().__init__()
        self.final_ln = LayerNorm(config.hidden_size,
                                  epsilon=config.layer_norm_epsilon)
        if word_embeddings is None:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  bias_attr=False)
            set_param_spec(self.lm_head.weight, P(None, MODEL_AXIS))
        else:
            self.lm_head = None
            object.__setattr__(self, "_tied_embeddings", word_embeddings)

    def forward(self, x):
        h = self.final_ln(x)
        if self.lm_head is not None:
            logits = self.lm_head(h)
        else:
            logits = h.matmul(self._tied_embeddings.weight.t())
        return mark_sharding(logits, _act_spec(last=MODEL_AXIS))


def GPTForCausalLMPipe(config: GPTConfig, topology=None,
                       num_stages: Optional[int] = None,
                       recompute_interval: int = 0,
                       num_virtual_pipeline_stages: Optional[int] = None):
    """Pipeline-parallel GPT (reference: the GPTForCausalLMPipe pattern of
    hybrid_parallel_pp_transformer.py) — a PipelineLayer whose uniform
    decoder stack compiles onto the "pipe" mesh axis.
    num_virtual_pipeline_stages > 1 selects the interleaved 1F1B schedule
    (reference pp_layers.py:162 interleaved segmentation)."""
    from ..distributed.fleet.meta_parallel.parallel_layers.pp_layers import (
        PipelineLayer,
    )
    emb = GPTEmbeddings(config)
    layers = [emb]
    layers += [GPTDecoderLayer(config)
               for _ in range(config.num_hidden_layers)]
    tied = emb.word_embeddings if config.tie_word_embeddings else None
    layers.append(_GPTHeadPipe(config, tied))
    crit = GPTPretrainingCriterion()
    return PipelineLayer(
        layers, num_stages=num_stages, topology=topology,
        loss_fn=lambda logits, labels: crit(logits, labels),
        recompute_interval=recompute_interval,
        num_virtual_pipeline_stages=num_virtual_pipeline_stages)


class GPTPretrainingCriterion(Layer):
    """Vocab-parallel causal-LM loss (reference:
    auto_parallel_gpt_model.py GPTPretrainingCriterion)."""

    def __init__(self, ignore_index: int = -100, overlap_chunks: int = 1):
        super().__init__()
        self.ce = ParallelCrossEntropy(ignore_index=ignore_index,
                                       overlap_chunks=overlap_chunks)
        self.ignore_index = ignore_index

    def forward(self, logits, labels, loss_mask=None):
        loss = self.ce(logits, labels)          # [B, S, 1]
        loss = loss.squeeze(-1)
        if loss_mask is not None:
            m = loss_mask.astype("float32")
            return (loss * m).sum() / m.sum().clip(min=1.0)
        denom = (labels != self.ignore_index).astype("float32").sum()
        return loss.sum() / denom.clip(min=1.0)
