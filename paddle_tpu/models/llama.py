"""Llama decoder-only transformer family (RMSNorm + SwiGLU + rotary, GQA).

Reference parity: BASELINE.md configs #3 (Llama-2 7B, bf16 AMP-O2 + fused
flash-attn/rotary kernels) and #5 (Llama-2 70B auto-parallel).  The
reference snapshot predates Llama, so this is capability-matching against
the baseline configs, built from the same TP building blocks as GPT
(mp_layers.py) — not a translation of any reference file.

TPU-native design decisions (shared with gpt.py):
- Q and fused-KV projections are ColumnParallelLinear with head-major
  output layout: the sharded dim lands on the heads axis after reshape, so
  GSPMD keeps heads on the "model" axis through rotary + attention with
  zero resharding.  GQA: n_kv_heads may be < n_heads; both are sharded
  over the model axis (mp_degree must divide n_kv_heads).
- Rotary embedding through ops.pallas.rotary_embedding (rotate-half
  convention); cos/sin cached per (max_seq, head_dim, theta).
- Attention via ops.pallas.flash_attention (Pallas on TPU, XLA oracle
  elsewhere); GQA expands kv heads by repeat before the kernel — the
  repeat is free under jit on the sharded heads axis.
- SwiGLU MLP: gate/up fused in ONE ColumnParallelLinear of width 2*ffn
  (output laid out [2, ffn] so the split stays on the sharded axis),
  silu(gate) * up, then RowParallelLinear down.
- Sequence dim carries the "sep" axis (context parallelism, SURVEY §5.7).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer_base import Layer
from ..nn.layer.common import Dropout, Linear
from ..nn.layer.container import LayerList
from ..nn.layer.norm import RMSNorm
from ..ops.pallas import flash_attention as _flash_attention
from ..ops.pallas import rotary_embedding as _rotary_embedding
from ..distributed.fleet.meta_parallel.parallel_layers.mp_layers import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
)
from ..distributed.fleet.utils.recompute import recompute
from ..distributed.sharding_spec import (
    BATCH_AXES, MODEL_AXIS, SEQ_AXIS, mark_sharding, set_param_spec,
)
from .gpt import GPTPretrainingCriterion


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: Optional[int] = None   # None → MHA
    intermediate_size: Optional[int] = None     # None → llama 8/3 rule
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    initializer_range: float = 0.02
    hidden_dropout_prob: float = 0.0
    tie_word_embeddings: bool = False
    recompute: bool = False
    # >1 enables chunked compute/collective overlap in every Megatron-TP
    # layer (distributed/fleet/meta_parallel/overlap.py); 1 = baseline
    tp_overlap_chunks: int = 1

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def n_kv_heads(self) -> int:
        return self.num_key_value_heads or self.num_attention_heads

    @property
    def ffn_size(self) -> int:
        if self.intermediate_size is not None:
            return self.intermediate_size
        # llama convention: 2/3 * 4h rounded up to a multiple of 256
        f = int(2 * 4 * self.hidden_size / 3)
        return 256 * ((f + 255) // 256)


def llama_tiny(**kw) -> LlamaConfig:
    kw.setdefault("vocab_size", 128)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_hidden_layers", 2)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("num_key_value_heads", 2)
    kw.setdefault("intermediate_size", 128)
    kw.setdefault("max_position_embeddings", 64)
    return LlamaConfig(**kw)


def llama2_7b(**kw) -> LlamaConfig:
    kw.setdefault("hidden_size", 4096)
    kw.setdefault("num_hidden_layers", 32)
    kw.setdefault("num_attention_heads", 32)
    kw.setdefault("intermediate_size", 11008)
    return LlamaConfig(**kw)


def llama2_13b(**kw) -> LlamaConfig:
    kw.setdefault("hidden_size", 5120)
    kw.setdefault("num_hidden_layers", 40)
    kw.setdefault("num_attention_heads", 40)
    kw.setdefault("intermediate_size", 13824)
    return LlamaConfig(**kw)


def llama2_70b(**kw) -> LlamaConfig:
    kw.setdefault("hidden_size", 8192)
    kw.setdefault("num_hidden_layers", 80)
    kw.setdefault("num_attention_heads", 64)
    kw.setdefault("num_key_value_heads", 8)
    kw.setdefault("intermediate_size", 28672)
    return LlamaConfig(**kw)


LLAMA_CONFIGS = {"tiny": llama_tiny, "llama2-7b": llama2_7b,
                 "llama2-13b": llama2_13b, "llama2-70b": llama2_70b}


def _act_spec(last=None):
    return P(BATCH_AXES, SEQ_AXIS, last)


def _rope_cache(seq_len: int, dim: int, theta: float):
    """cos/sin tables [S, D] for the rotate-half rotary convention.

    Pure numpy on purpose: the cache persists on the layer across traces,
    and a jnp value built inside a jit trace would be a leaked tracer."""
    inv_freq = 1.0 / (theta ** (np.arange(0, dim, 2, dtype=np.float64) / dim))
    t = np.arange(seq_len, dtype=np.float64)
    freqs = np.outer(t, inv_freq)                       # [S, D/2]
    emb = np.concatenate([freqs, freqs], axis=-1)       # [S, D]
    return (np.cos(emb).astype(np.float32), np.sin(emb).astype(np.float32))


class LlamaAttention(Layer):
    """Rotary causal self-attention with grouped-query KV."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.n_heads = config.num_attention_heads
        self.n_kv = config.n_kv_heads
        self.head_dim = config.head_dim
        h = config.hidden_size
        init = I.Normal(std=config.initializer_range)
        self.q_proj = ColumnParallelLinear(
            h, self.n_heads * self.head_dim, weight_attr=init,
            has_bias=False, gather_output=False,
            overlap_chunks=config.tp_overlap_chunks)
        # fused K+V, head-major [n_kv, 2*head_dim]
        self.kv_proj = ColumnParallelLinear(
            h, self.n_kv * 2 * self.head_dim, weight_attr=init,
            has_bias=False, gather_output=False,
            overlap_chunks=config.tp_overlap_chunks)
        self.o_proj = RowParallelLinear(
            h, h, weight_attr=init, has_bias=False, input_is_parallel=True,
            overlap_chunks=config.tp_overlap_chunks)
        self.rope_theta = config.rope_theta
        self.max_pos = config.max_position_embeddings
        self._rope = None  # built lazily at first forward

    def forward(self, x, cache_ctx=None):
        B, S, _ = x.shape
        q = self.q_proj(x).reshape([B, S, self.n_heads, self.head_dim])
        kv = self.kv_proj(x).reshape([B, S, self.n_kv, 2 * self.head_dim])
        q = mark_sharding(q, P(BATCH_AXES, SEQ_AXIS, MODEL_AXIS, None))
        kv = mark_sharding(kv, P(BATCH_AXES, SEQ_AXIS, MODEL_AXIS, None))
        k, v = kv.split(2, axis=-1)                     # [B,S,Hkv,D]

        if self._rope is None or self._rope[0].shape[0] < S:
            self._rope = _rope_cache(max(S, self.max_pos), self.head_dim,
                                     self.rope_theta)
        if cache_ctx is not None and cache_ctx.mode != "prefill":
            # position-offset rotary: gather the FULL tables at each
            # slot's current offset — decode's single query token (and
            # verify's k+1-token speculative window) is not at position 0
            cos = Tensor._wrap(jnp.asarray(self._rope[0]))
            sin = Tensor._wrap(jnp.asarray(self._rope[1]))
            q, k = _rotary_embedding(q, k, cos, sin,
                                     position_ids=cache_ctx.positions())
            # cache stores post-rotary K (and V) at kv-head granularity;
            # write + attend routed through the context (the paged cache
            # may run the Pallas flash-decoding kernel over its blocks;
            # verify mode routes to the W-token window attention)
            ctx = cache_ctx.decode_attention(q, k, v)
        else:
            pos = None if cache_ctx is None else \
                cache_ctx.prefill_positions(S)
            if pos is None:
                cos = Tensor._wrap(jnp.asarray(self._rope[0][:S]))
                sin = Tensor._wrap(jnp.asarray(self._rope[1][:S]))
                q, k = _rotary_embedding(q, k, cos, sin)
            else:
                # paged tail prefill: the bucket's tokens sit at absolute
                # offsets past the cached prefix — gather full tables
                cos = Tensor._wrap(jnp.asarray(self._rope[0]))
                sin = Tensor._wrap(jnp.asarray(self._rope[1]))
                q, k = _rotary_embedding(q, k, cos, sin, position_ids=pos)

            if cache_ctx is not None:                   # prefill
                # post-rotary K at kv-head granularity; attention routes
                # through the context (GQA expansion happens inside)
                cache_ctx.write_prefill(k, v)
                ctx = cache_ctx.prefill_attention(q, k, v)
            else:
                if self.n_kv != self.n_heads:
                    rep = self.n_heads // self.n_kv
                    k = k.unsqueeze(3) \
                         .expand([B, S, self.n_kv, rep, self.head_dim]) \
                         .reshape([B, S, self.n_heads, self.head_dim])
                    v = v.unsqueeze(3) \
                         .expand([B, S, self.n_kv, rep, self.head_dim]) \
                         .reshape([B, S, self.n_heads, self.head_dim])

                ctx = _flash_attention(q, k, v, is_causal=True,
                                       training=self.training)
        ctx = mark_sharding(ctx, P(BATCH_AXES, SEQ_AXIS, MODEL_AXIS, None))
        ctx = ctx.reshape([B, S, self.n_heads * self.head_dim])
        return self.o_proj(ctx)


class LlamaMLP(Layer):
    """SwiGLU: down(silu(gate(x)) * up(x)); gate/up fused column-parallel."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        init = I.Normal(std=config.initializer_range)
        self.ffn = config.ffn_size
        self.gate_up_proj = ColumnParallelLinear(
            config.hidden_size, 2 * config.ffn_size, weight_attr=init,
            has_bias=False, gather_output=False,
            overlap_chunks=config.tp_overlap_chunks)
        self.down_proj = RowParallelLinear(
            config.ffn_size, config.hidden_size, weight_attr=init,
            has_bias=False, input_is_parallel=True,
            overlap_chunks=config.tp_overlap_chunks)

    def forward(self, x):
        gu = self.gate_up_proj(x)
        gate, up = gu.split(2, axis=-1)
        return self.down_proj(F.silu(gate) * up)


class LlamaDecoderLayer(Layer):
    """Pre-RMSNorm block: x + attn(norm(x)); x + mlp(norm(x))."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.input_layernorm = RMSNorm(config.hidden_size,
                                       epsilon=config.rms_norm_eps)
        self.self_attn = LlamaAttention(config)
        self.post_attention_layernorm = RMSNorm(config.hidden_size,
                                                epsilon=config.rms_norm_eps)
        self.mlp = LlamaMLP(config)
        self.dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, x, cache_ctx=None):
        x = x + self.dropout(
            self.self_attn(self.input_layernorm(x), cache_ctx))
        x = x + self.dropout(self.mlp(self.post_attention_layernorm(x)))
        return mark_sharding(x, _act_spec())


class LlamaModel(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        init = I.Normal(std=config.initializer_range)
        self.embed_tokens = VocabParallelEmbedding(
            config.vocab_size, config.hidden_size, weight_attr=init,
            overlap_chunks=config.tp_overlap_chunks)
        self.layers = LayerList(
            [LlamaDecoderLayer(config)
             for _ in range(config.num_hidden_layers)])
        self.norm = RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)

    def forward(self, input_ids, cache_ctx=None):
        h = mark_sharding(self.embed_tokens(input_ids), _act_spec())
        for i, layer in enumerate(self.layers):
            if cache_ctx is not None:
                cache_ctx.layer_idx = i
                h = layer(h, cache_ctx)
            elif self.config.recompute and self.training:
                h = recompute(layer, h)
            else:
                h = layer(h)
        return self.norm(h)


class LlamaForCausalLM(Layer):
    """LlamaModel + LM head (untied by default, per llama convention)."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  bias_attr=False)
            set_param_spec(self.lm_head.weight, P(None, MODEL_AXIS))

    def forward(self, input_ids, cache_ctx=None):
        h = self.llama(input_ids, cache_ctx=cache_ctx)
        if self.lm_head is not None:
            logits = self.lm_head(h)
        else:
            logits = h.matmul(self.llama.embed_tokens.weight.t())
        return mark_sharding(logits, _act_spec(last=MODEL_AXIS))


class _LlamaHeadPipe(Layer):
    """Final RMSNorm + LM head for the pipelined model."""

    def __init__(self, config: LlamaConfig, embed_tokens=None):
        super().__init__()
        self.norm = RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        if embed_tokens is None:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  bias_attr=False)
            set_param_spec(self.lm_head.weight, P(None, MODEL_AXIS))
        else:
            self.lm_head = None
            object.__setattr__(self, "_tied_embeddings", embed_tokens)

    def forward(self, x):
        h = self.norm(x)
        if self.lm_head is not None:
            logits = self.lm_head(h)
        else:
            logits = h.matmul(self._tied_embeddings.weight.t())
        return mark_sharding(logits, _act_spec(last=MODEL_AXIS))


class _LlamaEmbPipe(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        init = I.Normal(std=config.initializer_range)
        self.embed_tokens = VocabParallelEmbedding(
            config.vocab_size, config.hidden_size, weight_attr=init)

    def forward(self, input_ids):
        return mark_sharding(self.embed_tokens(input_ids), _act_spec())


def LlamaForCausalLMPipe(config: LlamaConfig, topology=None,
                         num_stages: Optional[int] = None,
                         recompute_interval: int = 0):
    """Pipeline-parallel Llama (same PipelineLayer machinery as GPT)."""
    from ..distributed.fleet.meta_parallel.parallel_layers.pp_layers import (
        PipelineLayer,
    )
    emb = _LlamaEmbPipe(config)
    layers = [emb]
    layers += [LlamaDecoderLayer(config)
               for _ in range(config.num_hidden_layers)]
    tied = emb.embed_tokens if config.tie_word_embeddings else None
    layers.append(_LlamaHeadPipe(config, tied))
    crit = GPTPretrainingCriterion()
    return PipelineLayer(
        layers, num_stages=num_stages, topology=topology,
        loss_fn=lambda logits, labels: crit(logits, labels),
        recompute_interval=recompute_interval)


LlamaPretrainingCriterion = GPTPretrainingCriterion
