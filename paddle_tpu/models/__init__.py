"""Flagship model families (TPU-native, hybrid-parallel-ready).

The reference ships its large-model definitions in test/benchmark harnesses
(auto_parallel_gpt_model.py, hybrid_parallel_pp_transformer.py) and fused
transformer ops (operators/fused/).  Here they are first-class: every model
is built from the parallel layers in distributed.fleet.meta_parallel, so
the same definition runs single-chip or on any hybrid mesh.
"""
from .gpt import (
    GPTConfig, GPTModel, GPTForCausalLM, GPTForCausalLMPipe,
    GPTPretrainingCriterion, GPT_CONFIGS, gpt_tiny, gpt2_345m, gpt3_13b,
)
from .llama import (
    LlamaConfig, LlamaModel, LlamaForCausalLM, LlamaForCausalLMPipe,
    LlamaPretrainingCriterion, LLAMA_CONFIGS, llama_tiny, llama2_7b,
    llama2_13b, llama2_70b,
)
