"""Dygraph-to-static AST conversion of native python control flow.

Reference parity: dygraph_to_static/program_translator.py:239 (the
``@to_static`` source rewrite), ifelse_transformer.py, loop_transformer.py.
The reference rewrites ``if``/``while``/``for`` over Variables into
ConditionalBlock/While ops; here they are rewritten into calls to the
runtime converters below, which fall back to plain python when the
predicate is CONCRETE (the reference's dygraph fallback) and lower to
``static.cond`` / ``static.while_loop`` (→ ``lax.cond`` /
``lax.while_loop``) when it is a traced Tensor.

Mechanics (simplified versus the reference's multi-pass transformer
pipeline, but with the same variable-capture contract):

- each branch/loop body becomes a local function whose parameters are the
  names the body READS and whose returns are the names it ASSIGNS;
- possibly-unbound names are captured through ``ld`` (a try/except
  closure read) and flow as ``UndefinedVar`` sentinels that raise a clear
  message on first real use;
- statements containing ``return``/``break``/``continue``/``global``/
  ``nonlocal``/``del`` at conversion scope are left untouched: python
  semantics are preserved for concrete predicates, and a traced-tensor
  predicate keeps today's explicit error.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import List, Optional, Set

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["convert_function", "convert_ifelse", "convert_while",
           "convert_range_loop", "ld", "UndefinedVar"]


# ---------------------------------------------------------------------------
# runtime
# ---------------------------------------------------------------------------

class UndefinedVar:
    """A name that was unbound when captured.  Any real use raises with
    the variable name (reference: dygraph_to_static UndefinedVar)."""

    __slots__ = ("name",)

    def __init__(self, name=""):
        self.name = name

    def _raise(self):
        raise NameError(
            f"variable '{self.name}' is not defined on every path through "
            "converted control flow (assigned in only one branch, or read "
            "before the loop ever ran)")

    def __bool__(self):
        self._raise()

    def __array__(self, *a, **k):
        self._raise()

    def __getattr__(self, item):
        if item == "name":
            raise AttributeError(item)
        self._raise()

    def __call__(self, *a, **k):
        self._raise()

    # implicit dunder lookup bypasses __getattr__ — name the common ones
    def _binop(self, *a, **k):
        self._raise()

    __add__ = __radd__ = __sub__ = __rsub__ = __mul__ = __rmul__ = _binop
    __truediv__ = __rtruediv__ = __matmul__ = __rmatmul__ = _binop
    __lt__ = __le__ = __gt__ = __ge__ = __iter__ = __len__ = _binop
    __getitem__ = __neg__ = __abs__ = __float__ = __int__ = _binop

    def __repr__(self):
        return f"UndefinedVar({self.name!r})"


def ld(thunk, name=""):
    """Read a possibly-unbound outer variable."""
    try:
        return thunk()
    except (NameError, UnboundLocalError):
        return UndefinedVar(name)


def _is_traced(v) -> bool:
    if isinstance(v, Tensor):
        v = v._value()
    return isinstance(v, jax.core.Tracer)


def _layer_params(operands):
    """Parameters/buffers of any Layer operand (incl. `self`), listed so
    static.cond's tape vjp sees them — closure captures bypass the tape."""
    from ..nn.layer_base import Layer

    seen, ps = set(), []
    for o in operands:
        if isinstance(o, Layer):
            for t in (list(o.parameters())
                      + [b for _, b in o.named_buffers()]):
                if id(t) not in seen:
                    seen.add(id(t))
                    ps.append(t)
    return ps


_SKIP_CALL_MODULES = ("paddle_tpu", "jax", "numpy", "builtins",
                      "functools", "itertools", "operator", "np")


def convert_call(fn):
    """Recursively convert plain USER functions reached from converted
    code (reference: convert_call wrapping every call site,
    dygraph_to_static/convert_call_func.py).  Library code (paddle_tpu /
    jax / numpy / builtins) is never touched — it has no tensor-dependent
    python control flow by construction."""
    try:
        import inspect

        if inspect.isfunction(fn) or inspect.ismethod(fn):
            target = fn.__func__ if inspect.ismethod(fn) else fn
            mod = getattr(target, "__module__", "") or ""
            if getattr(target, _CONVERTED_MARK, False):
                return fn
            if mod.split(".")[0] in _SKIP_CALL_MODULES:
                return fn
            return convert_function(fn)
    except Exception:
        pass
    return fn


# short alias used by generated code at every call site
cvt = convert_call


def convert_ifelse(pred, true_fn, false_fn, operands=()):
    """``if pred: ... else: ...`` with assigned-name outputs."""
    from ..static.nn import cond as static_cond

    p = pred._value() if isinstance(pred, Tensor) else pred
    if isinstance(p, jax.core.Tracer):
        out = static_cond(pred, true_fn, false_fn, operands,
                          params=_layer_params(operands))
        return out if isinstance(out, tuple) else (out,)
    taken = true_fn if bool(
        pred.item() if isinstance(pred, Tensor) else pred) else false_fn
    out = taken(*operands)
    return out if isinstance(out, tuple) else (out,)


def _promote_loop_vars(vars_):
    """Python scalars in a TRACED loop must become Tensors, or their
    body updates would be silently dropped by lax.while_loop."""
    out = []
    for v in vars_:
        if isinstance(v, (bool, int, float)) and not isinstance(v, Tensor):
            out.append(Tensor._wrap(jnp.asarray(v)))
        else:
            out.append(v)
    return out


def convert_while(cond_fn, body_fn, init_vars):
    """``while cond: body`` over the body's assigned names."""
    from ..static.nn import while_loop

    init_vars = list(init_vars)
    if any(_is_traced(v) for v in init_vars):
        return tuple(while_loop(cond_fn, body_fn,
                                _promote_loop_vars(init_vars)))
    # Concrete init vars: evaluate the condition ONCE and reuse it as the
    # loop's first test, so conditions with side effects (iterator
    # consumption, counters) run exactly as many times as plain python
    # would run them.  The condition may still come back traced when it
    # reads a traced closure var — promote and lower in that case.
    test = cond_fn(*init_vars)
    if _is_traced(test):
        return tuple(while_loop(cond_fn, body_fn,
                                _promote_loop_vars(init_vars)))
    vars_ = init_vars
    while bool(test.item() if isinstance(test, Tensor) else test):
        res = body_fn(*vars_)
        vars_ = list(res) if isinstance(res, (tuple, list)) else [res]
        test = cond_fn(*vars_)
    return tuple(vars_)


def convert_range_loop(start, stop, step, body_fn, init_vars):
    """``for i in range(start, stop, step): body`` — body_fn(i, *vars) ->
    vars.  Concrete bounds run the plain python loop (still unrolls under
    an outer trace, matching previous behavior); traced bounds lower to a
    while_loop with the index as a carried Tensor."""
    from ..static.nn import while_loop

    bounds = [start, stop, step]
    if not any(_is_traced(b) for b in bounds):
        vars_ = tuple(init_vars)
        s0 = int(start.item() if isinstance(start, Tensor) else start)
        s1 = int(stop.item() if isinstance(stop, Tensor) else stop)
        st = int(step.item() if isinstance(step, Tensor) else step)
        for i in range(s0, s1, st):
            vars_ = body_fn(i, *vars_)
        return tuple(vars_)

    init = _promote_loop_vars([start] + list(init_vars))
    step_c = step if isinstance(step, Tensor) else Tensor._wrap(
        jnp.asarray(step))
    stop_c = stop if isinstance(stop, Tensor) else Tensor._wrap(
        jnp.asarray(stop))

    def _cond(i, *vars_):
        up = (step_c._value() if isinstance(step_c, Tensor) else step_c) > 0
        iv = i._value() if isinstance(i, Tensor) else i
        sv = stop_c._value()
        return Tensor._wrap(jnp.where(up, iv < sv, iv > sv))

    def _body(i, *vars_):
        new = body_fn(i, *vars_)
        new = new if isinstance(new, tuple) else (new,)
        nxt = Tensor._wrap(
            (i._value() if isinstance(i, Tensor) else i)
            + (step_c._value() if isinstance(step_c, Tensor) else step_c))
        return (nxt,) + tuple(new)

    out = while_loop(_cond, _body, init)
    return tuple(out[1:])


# ---------------------------------------------------------------------------
# AST analysis
# ---------------------------------------------------------------------------

_SCOPE_BARRIERS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
_BAIL_NODES = (ast.Return, ast.Break, ast.Continue, ast.Global,
               ast.Nonlocal, ast.Delete, ast.Yield, ast.YieldFrom,
               ast.Await)


def _walk_scope(node):
    """ast.walk that does not descend into nested function/class defs
    (their bodies are separate scopes), but does cover lambdas and
    comprehensions (their reads matter for capture)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, _SCOPE_BARRIERS):
            stack.extend(ast.iter_child_nodes(n))


def _walk_stmt(s):
    """The statement itself plus its same-scope subtree (if the statement
    IS a def, its body is a separate scope and is not entered)."""
    yield s
    if not isinstance(s, _SCOPE_BARRIERS):
        yield from _walk_scope(s)


def _nonname_store(n) -> bool:
    """Assignments into attributes/subscripts are object mutations whose
    effects would silently vanish inside a traced branch — bail."""
    tgts = []
    if isinstance(n, ast.Assign):
        tgts = n.targets
    elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
        tgts = [n.target]

    def bad(t):
        if isinstance(t, (ast.Attribute, ast.Subscript, ast.Starred)):
            return not isinstance(t, ast.Starred) or bad(t.value)
        if isinstance(t, (ast.Tuple, ast.List)):
            return any(bad(e) for e in t.elts)
        return False

    return any(bad(t) for t in tgts)


def _has_bail(stmts) -> bool:
    for s in stmts:
        for n in _walk_stmt(s):
            if _nonname_store(n):
                return True
            if isinstance(n, _BAIL_NODES):
                # break/continue inside a NESTED loop are that loop's
                # business, not ours
                if isinstance(n, (ast.Break, ast.Continue)):
                    if _inside_nested_loop(s, n):
                        continue
                return True
    return False


def _inside_nested_loop(root_stmt, node) -> bool:
    """True if `node` sits under a For/While that is itself inside
    root_stmt (so the break/continue does not escape the converted
    region)."""
    # collect all loop subtrees strictly inside root_stmt
    for n in _walk_scope(root_stmt):
        if isinstance(n, (ast.For, ast.While)):
            for m in [n] + list(_walk_scope(n)):
                if m is node:
                    return True
    return False


def _assigned_names(stmts) -> Set[str]:
    names: Set[str] = set()

    def targets_of(t):
        if isinstance(t, ast.Name):
            names.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                targets_of(e)
        elif isinstance(t, ast.Starred):
            targets_of(t.value)

    for s in stmts:
        for n in _walk_stmt(s):
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    targets_of(t)
            elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
                targets_of(n.target)
            elif isinstance(n, ast.For):
                targets_of(n.target)
            elif isinstance(n, ast.withitem) and n.optional_vars:
                targets_of(n.optional_vars)
            elif isinstance(n, ast.NamedExpr):
                targets_of(n.target)
            elif isinstance(n, _SCOPE_BARRIERS):
                names.add(n.name)
    # generated helpers are locals of their own region, and function/class
    # defs cannot cross a lax.cond boundary as outputs
    return {n for n in names if not n.startswith("__jst_")}


def _loaded_names(stmts) -> Set[str]:
    loads: Set[str] = set()
    for s in stmts:
        for n in _walk_stmt(s):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                loads.add(n.id)
    return {n for n in loads if not n.startswith("__jst_")}


# ---------------------------------------------------------------------------
# transformer
# ---------------------------------------------------------------------------

def _name(id_, ctx=None):
    return ast.Name(id=id_, ctx=ctx or ast.Load())


def _jst_attr(fn_name):
    return ast.Attribute(value=_name("_jst"), attr=fn_name, ctx=ast.Load())


def _ld_expr(var: str):
    """_jst.ld(lambda: var, 'var')"""
    lam = ast.Lambda(
        args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                           kw_defaults=[], defaults=[]),
        body=_name(var))
    return ast.Call(func=_jst_attr("ld"),
                    args=[lam, ast.Constant(var)], keywords=[])


def _branch_funcdef(fname: str, params: List[str], body: List[ast.stmt],
                    out_names: List[str]) -> ast.FunctionDef:
    ret = ast.Return(value=ast.Tuple(
        elts=[_ld_expr(n) for n in out_names], ctx=ast.Load()))
    return ast.FunctionDef(
        name=fname,
        args=ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=p) for p in params],
            kwonlyargs=[], kw_defaults=[], defaults=[]),
        body=(body or [ast.Pass()]) + [ret],
        decorator_list=[])


def _unpack_assign(out_names: List[str], value: ast.expr) -> ast.stmt:
    tgt = ast.Tuple(elts=[_name(n, ast.Store()) for n in out_names],
                    ctx=ast.Store())
    return ast.Assign(targets=[tgt], value=value)


class _CallSiteWrapper(ast.NodeTransformer):
    """foo(args) -> _jst.cvt(foo)(args) for plain-name/attribute callees,
    so user helper functions get converted recursively (reference
    convert_call).  Generated _jst.* calls are left alone."""

    def visit_Call(self, node: ast.Call):
        self.generic_visit(node)
        f = node.func
        if isinstance(f, ast.Name) and not f.id.startswith("__jst_"):
            pass
        elif isinstance(f, ast.Attribute):
            root = f
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) and root.id == "_jst":
                return node
        else:
            return node
        node.func = ast.Call(func=_jst_attr("cvt"), args=[f], keywords=[])
        return node


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self.changed = False
        self._uid = 0

    def _next(self, kind):
        self._uid += 1
        return f"__jst_{kind}_{self._uid}"

    # do not descend into nested defs — they are separate conversions
    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        return node

    def visit_Lambda(self, node):
        return node

    def visit_If(self, node: ast.If):
        self.generic_visit(node)   # innermost first
        if _has_bail(node.body) or _has_bail(node.orelse):
            return node
        assigned = sorted(_assigned_names(node.body)
                          | _assigned_names(node.orelse))
        if not assigned:
            # nothing flows out: conversion could only lose side-effect
            # semantics under tracing — keep the python if
            return node
        reads = sorted((_loaded_names(node.body)
                        | _loaded_names(node.orelse)
                        | _loaded_names([ast.Expr(node.test)])) - {"_jst"})
        tname = self._next("true")
        fname = self._next("false")
        true_def = _branch_funcdef(tname, reads, node.body, assigned)
        false_def = _branch_funcdef(fname, reads, node.orelse, assigned)
        call = ast.Call(
            func=_jst_attr("convert_ifelse"),
            args=[node.test, _name(tname), _name(fname),
                  ast.Tuple(elts=[_ld_expr(r) for r in reads],
                            ctx=ast.Load())],
            keywords=[])
        self.changed = True
        return [true_def, false_def, _unpack_assign(assigned, call)]

    def visit_While(self, node: ast.While):
        self.generic_visit(node)
        if node.orelse or _has_bail(node.body):
            return node
        assigned = sorted(_assigned_names(node.body))
        if not assigned:
            return node
        reads = sorted((_loaded_names(node.body)
                        | _loaded_names([ast.Expr(node.test)]))
                       - set(assigned) - {"_jst"})
        cname = self._next("cond")
        bname = self._next("body")
        params = assigned  # loop-carried; reads stay free (closures)
        cond_def = ast.FunctionDef(
            name=cname,
            args=ast.arguments(posonlyargs=[],
                               args=[ast.arg(arg=p) for p in params],
                               kwonlyargs=[], kw_defaults=[], defaults=[]),
            body=[ast.Return(value=node.test)],
            decorator_list=[])
        body_def = _branch_funcdef(bname, params, node.body, assigned)
        call = ast.Call(
            func=_jst_attr("convert_while"),
            args=[_name(cname), _name(bname),
                  ast.Tuple(elts=[_ld_expr(n) for n in assigned],
                            ctx=ast.Load())],
            keywords=[])
        self.changed = True
        return [cond_def, body_def, _unpack_assign(assigned, call)]

    def visit_For(self, node: ast.For):
        self.generic_visit(node)
        # only `for <name> in range(...)` without else
        if (node.orelse or _has_bail(node.body)
                or not isinstance(node.target, ast.Name)
                or not isinstance(node.iter, ast.Call)
                or not isinstance(node.iter.func, ast.Name)
                or node.iter.func.id != "range"
                or node.iter.keywords
                or not 1 <= len(node.iter.args) <= 3
                or any(isinstance(a, ast.Starred)
                       for a in node.iter.args)):
            return node
        assigned = sorted(_assigned_names(node.body) - {node.target.id})
        if not assigned:
            return node
        ra = node.iter.args
        if len(ra) == 1:
            start, stop, step = ast.Constant(0), ra[0], ast.Constant(1)
        elif len(ra) == 2:
            start, stop, step = ra[0], ra[1], ast.Constant(1)
        else:
            start, stop, step = ra
        bname = self._next("forbody")
        body_def = _branch_funcdef(
            bname, [node.target.id] + assigned, node.body, assigned)
        call = ast.Call(
            func=_jst_attr("convert_range_loop"),
            args=[start, stop, step, _name(bname),
                  ast.Tuple(elts=[_ld_expr(n) for n in assigned],
                            ctx=ast.Load())],
            keywords=[])
        self.changed = True
        return [body_def, _unpack_assign(assigned, call)]


# ---------------------------------------------------------------------------
# entry
# ---------------------------------------------------------------------------

class _LiveGlobals(dict):
    """exec/function globals that fall through to the original module's
    dict on miss — rebindings of module globals stay visible to the
    converted function.  (Closure cell VALUES are still snapshotted at
    conversion time: rebinding an enclosing local after decoration is not
    reflected — same as the reference's converted-function cache.)"""

    def __init__(self, base, extra):
        super().__init__(extra)
        self._base = base

    def __missing__(self, k):
        return self._base[k]


_CONVERTED_MARK = "__jst_converted__"


def convert_function(fn):
    """AST-convert python control flow in ``fn``; returns ``fn`` itself
    when nothing needs converting or the source is unavailable."""
    bound_self = None
    if inspect.ismethod(fn):
        bound_self = fn.__self__
        fn = fn.__func__
    if getattr(fn, _CONVERTED_MARK, False):
        return fn if bound_self is None else fn.__get__(bound_self)
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return fn if bound_self is None else fn.__get__(bound_self)
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn if bound_self is None else fn.__get__(bound_self)
    # only the to_static decorator itself may be stripped; any other
    # decorator would be silently dropped by recompilation — bail
    for dec in fdef.decorator_list:
        if "to_static" not in ast.unparse(dec):
            setattr(fn, _CONVERTED_MARK, True)
            return fn if bound_self is None else fn.__get__(bound_self)
    fdef.decorator_list = []
    tr = _ControlFlowTransformer()
    fdef.body = [x for stmt in fdef.body
                 for x in _as_list(tr.visit(stmt))]
    # call-site wrapping lets helpers reached from here convert too
    # (reference convert_call); only worth the indirection when this
    # function itself converts, or when it might CALL converting code
    _CallSiteWrapper().visit(fdef)
    if not tr.changed and not _has_user_calls(fdef):
        setattr(fn, _CONVERTED_MARK, True)
        return fn if bound_self is None else fn.__get__(bound_self)
    ast.fix_missing_locations(tree)
    from . import dy2static as _jst_mod

    # LIVE view of the module globals: a snapshot copy would silently pin
    # every later-rebound module global (config flags, the function's own
    # name for recursion) to its value at decoration time
    extras = {"_jst": _jst_mod}
    if fn.__closure__:
        for nm, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                extras[nm] = cell.cell_contents
            except ValueError:   # empty cell
                pass
    ns = _LiveGlobals(fn.__globals__, extras)
    code = compile(tree, filename=f"<dy2static {fn.__code__.co_filename}>",
                   mode="exec")
    exec(code, ns)
    new_fn = ns[fdef.name]
    functools.update_wrapper(new_fn, fn)
    setattr(new_fn, _CONVERTED_MARK, True)
    return new_fn if bound_self is None else new_fn.__get__(bound_self)


def _has_user_calls(fdef) -> bool:
    """Does the (wrapped) function contain any _jst.cvt call sites?"""
    for n in ast.walk(fdef):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Call) and \
                isinstance(n.func.func, ast.Attribute) and \
                n.func.func.attr == "cvt":
            return True
    return False


def _as_list(v):
    return v if isinstance(v, list) else [v]
