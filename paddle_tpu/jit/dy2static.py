"""Dygraph-to-static AST conversion of native python control flow.

Reference parity: dygraph_to_static/program_translator.py:239 (the
``@to_static`` source rewrite), ifelse_transformer.py, loop_transformer.py.
The reference rewrites ``if``/``while``/``for`` over Variables into
ConditionalBlock/While ops; here they are rewritten into calls to the
runtime converters below, which fall back to plain python when the
predicate is CONCRETE (the reference's dygraph fallback) and lower to
``static.cond`` / ``static.while_loop`` (→ ``lax.cond`` /
``lax.while_loop``) when it is a traced Tensor.

Mechanics (simplified versus the reference's multi-pass transformer
pipeline, but with the same variable-capture contract):

- each branch/loop body becomes a local function whose parameters are the
  names the body READS and whose returns are the names it ASSIGNS;
- possibly-unbound names are captured through ``ld`` (a try/except
  closure read) and flow as ``UndefinedVar`` sentinels that raise a clear
  message on first real use;
- ``return`` inside control flow is rewritten into a flag + value pair
  with guarded tails (reference return_transformer.py:136), ``break``/
  ``continue`` into loop flags folded into the loop condition (reference
  break_continue_transformer.py:89), and ``and``/``or``/``not`` into
  short-circuit-preserving converters that lower to logical ops on traced
  tensors (reference logical_transformer.py);
- statements that still cannot be converted (``yield``, ``global``,
  attribute stores inside branches, ...) are left untouched AND recorded:
  when a traced tensor later leaks into one, the error names the
  construct and the user's source line (reference
  dygraph_to_static/error.py).
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import List, Optional, Set

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["convert_function", "convert_ifelse", "convert_while",
           "convert_range_loop", "convert_logical_and",
           "convert_logical_or", "convert_logical_not", "ld",
           "UndefinedVar", "Dy2StaticError", "map_trace_error"]


class Dy2StaticError(RuntimeError):
    """A python construct could not be (or was not) converted to static
    control flow, and the failure is mapped back to user source
    (reference: dygraph_to_static/error.py, origin_info.py)."""


# conversion-time records of constructs the transformer deliberately left
# as plain python: {file, line, end, construct, reason}.  Consulted when a
# tracer leaks, to tell the user WHICH statement was the wall.
_BAIL_RECORDS: List[dict] = []
_BAIL_KEYS: set = set()
_MAX_BAIL_RECORDS = 512


def _record_bail(filename: str, node: ast.stmt, construct: str, reason: str):
    key = (filename, getattr(node, "lineno", 0), construct)
    if key in _BAIL_KEYS:
        return
    if len(_BAIL_RECORDS) >= _MAX_BAIL_RECORDS:
        dropped = _BAIL_RECORDS[:_MAX_BAIL_RECORDS // 2]
        del _BAIL_RECORDS[:_MAX_BAIL_RECORDS // 2]
        for r in dropped:
            _BAIL_KEYS.discard((r["file"], r["line"], r["construct"]))
    _BAIL_KEYS.add(key)
    _BAIL_RECORDS.append({
        "file": filename,
        "line": getattr(node, "lineno", 0),
        "end": getattr(node, "end_lineno", getattr(node, "lineno", 0)),
        "construct": construct,
        "reason": reason,
    })


def map_trace_error(exc):
    """Build a Dy2StaticError pointing at the user statement where a
    traced Tensor leaked into unconverted python control flow.  Returns
    None when no user frame can be identified (caller should re-raise the
    original)."""
    import traceback

    frames = traceback.extract_tb(exc.__traceback__)
    user = None
    for fr in frames:
        f = fr.filename
        if ("/paddle_tpu/" in f or "/jax/" in f or "/site-packages/" in f
                or f.startswith("<")):
            continue
        user = fr   # keep the deepest user frame
    if user is None:
        return None
    lines = [
        "tensor-dependent python control flow could not be compiled.",
        f"  at {user.filename}:{user.lineno}",
    ]
    if user.line:
        lines.append(f"    {user.line.strip()}")
    hits = [r for r in _BAIL_RECORDS
            if r["file"] == user.filename
            and r["line"] <= user.lineno <= r["end"]]
    for r in hits[-3:]:
        lines.append(
            f"  the `{r['construct']}` at {r['file']}:{r['line']} was left "
            f"as plain python because {r['reason']}; a traced Tensor "
            "reached it, which requires static conversion")
    if not hits:
        lines.append(
            "  a Tensor whose value is only known at run time was used "
            "where python needs a concrete bool/int (if/while/assert/"
            "index). Rewrite with paddle.static.nn.cond / while_loop, or "
            "move the data-dependent branch out of the @to_static "
            "function.")
    lines.append(f"  (original error: {type(exc).__name__}: "
                 f"{str(exc).splitlines()[0] if str(exc) else ''})")
    return Dy2StaticError("\n".join(lines))


# ---------------------------------------------------------------------------
# runtime
# ---------------------------------------------------------------------------

class UndefinedVar:
    """A name that was unbound when captured.  Any real use raises with
    the variable name (reference: dygraph_to_static UndefinedVar)."""

    __slots__ = ("name",)

    def __init__(self, name=""):
        self.name = name

    def _raise(self):
        raise NameError(
            f"variable '{self.name}' is not defined on every path through "
            "converted control flow (assigned in only one branch, or read "
            "before the loop ever ran)")

    def __bool__(self):
        self._raise()

    def __array__(self, *a, **k):
        self._raise()

    def __getattr__(self, item):
        if item == "name":
            raise AttributeError(item)
        self._raise()

    def __call__(self, *a, **k):
        self._raise()

    # implicit dunder lookup bypasses __getattr__ — name the common ones
    def _binop(self, *a, **k):
        self._raise()

    __add__ = __radd__ = __sub__ = __rsub__ = __mul__ = __rmul__ = _binop
    __truediv__ = __rtruediv__ = __matmul__ = __rmatmul__ = _binop
    __lt__ = __le__ = __gt__ = __ge__ = __iter__ = __len__ = _binop
    __getitem__ = __neg__ = __abs__ = __float__ = __int__ = _binop

    def __repr__(self):
        return f"UndefinedVar({self.name!r})"


def ld(thunk, name=""):
    """Read a possibly-unbound outer variable."""
    try:
        return thunk()
    except (NameError, UnboundLocalError):
        return UndefinedVar(name)


def _is_traced(v) -> bool:
    if isinstance(v, Tensor):
        v = v._value()
    return isinstance(v, jax.core.Tracer)


def _layer_params(operands):
    """Parameters/buffers of any Layer operand (incl. `self`), listed so
    static.cond's tape vjp sees them — closure captures bypass the tape."""
    from ..nn.layer_base import Layer

    seen, ps = set(), []
    for o in operands:
        if isinstance(o, Layer):
            for t in (list(o.parameters())
                      + [b for _, b in o.named_buffers()]):
                if id(t) not in seen:
                    seen.add(id(t))
                    ps.append(t)
    return ps


_SKIP_CALL_MODULES = ("paddle_tpu", "jax", "numpy", "builtins",
                      "functools", "itertools", "operator", "np")


def _traced_scalar(v):
    return (_is_traced_val(v)
            and tuple(getattr(v, "shape", (None,))) == ())


def _convert_minmax(builtin, fold):
    """``max(a, b, ...)``/``min`` with traced SCALAR tensor args: python
    would bool() a comparison of tracers — fold elementwise instead
    (exact for scalars; reference convert_call maps builtins too).
    Every other form — single-iterable, key=/default=, non-scalar or
    fully concrete args — keeps the builtin (eager semantics, loud
    errors included)."""
    def wrapped(*args, **kwargs):
        if (not kwargs and len(args) >= 2
                and any(_traced_scalar(a) for a in args)
                and all(_arrayable(a) for a in args)
                and all(tuple(getattr(a, "shape", ())) == ()
                        for a in args)):
            acc = args[0]
            for a in args[1:]:
                acc = _logical_binop(fold, acc, a)
            return acc
        return builtin(*args, **kwargs)
    return wrapped


def convert_call(fn):
    """Recursively convert plain USER functions reached from converted
    code (reference: convert_call wrapping every call site,
    dygraph_to_static/convert_call_func.py).  Library code (paddle_tpu /
    jax / numpy / builtins) is never touched — it has no tensor-dependent
    python control flow by construction.  Exceptions: ``max``/``min``,
    whose python semantics bool() tracer comparisons (mapped to exact
    scalar folds above)."""
    if fn is max:
        return _convert_minmax(max, jnp.maximum)
    if fn is min:
        return _convert_minmax(min, jnp.minimum)
    try:
        import inspect

        if inspect.isfunction(fn) or inspect.ismethod(fn):
            target = fn.__func__ if inspect.ismethod(fn) else fn
            mod = getattr(target, "__module__", "") or ""
            if getattr(target, _CONVERTED_MARK, False):
                return fn
            if mod.split(".")[0] in _SKIP_CALL_MODULES:
                return fn
            return convert_function(fn)
    except Exception:
        pass
    return fn


# short alias used by generated code at every call site
cvt = convert_call


def _is_traced_val(v):
    if isinstance(v, Tensor):
        v = v._value()
    return isinstance(v, jax.core.Tracer)


def _truthy(v):
    if isinstance(v, Tensor):
        return bool(v.item())
    return bool(v)


def convert_logical_and(x_fn, y_fn):
    """``x and y`` (reference logical_transformer.py convert_logical_and).
    Concrete x keeps python's exact short-circuit + value semantics;
    traced x evaluates both sides and lowers to logical_and."""
    x = x_fn()
    if _is_traced_val(x):
        y = y_fn()
        return _logical_binop(jnp.logical_and, x, y)
    if not _truthy(x):
        return x
    return y_fn()


def convert_logical_or(x_fn, y_fn):
    x = x_fn()
    if _is_traced_val(x):
        y = y_fn()
        return _logical_binop(jnp.logical_or, x, y)
    if _truthy(x):
        return x
    return y_fn()


def convert_logical_not(x):
    if _is_traced_val(x):
        arr = x._value() if isinstance(x, Tensor) else jnp.asarray(x)
        return Tensor._wrap(jnp.logical_not(arr))
    return not x


_CHAIN_CMP_OPS = {
    "Lt": lambda a, b: a < b, "LtE": lambda a, b: a <= b,
    "Gt": lambda a, b: a > b, "GtE": lambda a, b: a >= b,
    "Eq": lambda a, b: a == b, "NotEq": lambda a, b: a != b,
    "Is": lambda a, b: a is b, "IsNot": lambda a, b: a is not b,
    "In": lambda a, b: a in b, "NotIn": lambda a, b: a not in b,
}


def convert_chain_compare(left_fn, *pairs):
    """``a OP1 b OP2 c ...`` with python's exact evaluation contract:
    each operand evaluates AT MOST once, later operands are skipped after
    a concrete-false comparison (short-circuit), and the false comparison
    value itself is returned (python returns it, not ``False``).  Traced
    comparisons fold with logical_and — the same semantic extension the
    BoolOp converter applies."""
    val = left_fn()
    acc = None
    for op, rhs_fn in pairs:
        rhs = rhs_fn()
        cmp = _CHAIN_CMP_OPS[op](val, rhs)
        if acc is None:
            acc = cmp
        elif _is_traced_val(acc) or _is_traced_val(cmp):
            acc = _logical_binop(jnp.logical_and, acc, cmp)
        else:
            acc = cmp
        if not _is_traced_val(acc) and not _truthy(acc):
            return acc
        val = rhs
    return acc


def convert_ifexp(pred, t_fn, f_fn):
    """``a if pred else b`` (reference: the ifelse transformer also
    rewrites ternaries).  Concrete pred keeps python semantics exactly;
    traced pred lowers both arms through the same branch unification as
    statement `if`."""
    out = convert_ifelse(pred, lambda: (t_fn(),), lambda: (f_fn(),),
                         operands=(), names=("<ternary>",))
    return out[0]


def _logical_binop(op, x, y):
    xa = x._value() if isinstance(x, Tensor) else jnp.asarray(x)
    ya = y._value() if isinstance(y, Tensor) else jnp.asarray(y)
    return Tensor._wrap(op(xa, ya))


class _RetNone:
    """Singleton marking an EXPLICIT bare `return` / `return None` in a
    converted function — distinguishable from 'value never assigned'
    (plain None), which the branch unifier may placeholder-fill."""

    __slots__ = ()

    def __repr__(self):
        return "<bare return>"


RET_NONE = _RetNone()


def ret_unwrap(val):
    return None if isinstance(val, _RetNone) else val


def ret_value(flag, val):
    """Final return of a converted function that has a fall-through path
    (not every path returns): python semantics are `val if returned else
    None`.  A traced flag means the function would return a tensor on
    some runtime paths and None on others — not representable in one
    compiled program; raise an actionable error instead of silently
    returning a placeholder."""
    if _is_traced_val(flag):
        raise Dy2StaticError(
            "this function returns a value on some paths but falls off "
            "the end (implicit `return None`) on others, and the choice "
            "depends on a traced Tensor; a compiled program needs one "
            "return structure — add an explicit `return` to the "
            "fall-through path")
    return ret_unwrap(val) if _truthy(flag) else None


# generated flag/value variables (return flags, break/continue flags, loop
# indices) — the one name family for which a branch that does not bind the
# variable may be filled with a typed placeholder: reads are always
# guarded by the paired flag, so the placeholder value is never observed.
_GEN_PREFIX = "__jstf_"


def convert_ifelse(pred, true_fn, false_fn, operands=(), names=None,
                   guard=False):
    """``if pred: ... else: ...`` with assigned-name outputs.

    Concrete pred: run the taken branch as plain python.  Traced pred:
    probe both branches abstractly, unify their outputs per assigned name
    (placeholder zeros for generated flag/value vars missing on one side,
    dtype promotion for scalars, pass-through for equal non-tensor
    constants, a NAMED error for user vars bound in only one branch),
    then lower to lax.cond via static.nn.cond."""
    from ..static.nn import cond as static_cond

    p = pred._value() if isinstance(pred, Tensor) else pred
    if not isinstance(p, jax.core.Tracer):
        taken = true_fn if bool(
            pred.item() if isinstance(pred, Tensor) else pred) else false_fn
        out = taken(*operands)
        return out if isinstance(out, tuple) else (out,)

    try:
        # note: each branch runs twice at COMPILE time (abstract probe +
        # the real trace under static_cond) — python-visible side effects
        # in branches duplicate, same caveat as the reference's multi-pass
        # tracing.  Probe failures fall back to the direct lowering so
        # the real trace surfaces the error with full context.
        t_raw = _probe_branch(true_fn, operands)
        f_raw = _probe_branch(false_fn, operands)
    except Dy2StaticError:
        raise
    except Exception:
        out = static_cond(pred, true_fn, false_fn, operands,
                          params=_layer_params(operands))
        return out if isinstance(out, tuple) else (out,)
    n = len(t_raw)
    names = list(names) if names is not None else [f"<out {i}>"
                                                  for i in range(n)]
    plans = [_unify_slot(t_raw[i], f_raw[i], names[i], guard)
             for i in range(n)]
    tensor_ix = [i for i, pl in enumerate(plans) if pl[0] == "tree"]

    def _wrap(fn):
        def g(*ops):
            out = fn(*ops)
            outs = list(out) if isinstance(out, (tuple, list)) else [out]
            res = []
            for i in tensor_ix:
                _, treedef, leaf_specs = plans[i]
                v = outs[i]
                if _is_missing(v):
                    leaves = [jnp.zeros(sh, dt) for sh, dt in leaf_specs]
                else:
                    leaves = jax.tree_util.tree_leaves(
                        v, is_leaf=_is_leaf_obj)
                    leaves = [
                        _leaf_array(lv).astype(dt)
                        for lv, (sh, dt) in zip(leaves, leaf_specs)]
                    leaves = [jnp.broadcast_to(a, sh)
                              for a, (sh, dt) in zip(leaves, leaf_specs)]
                res.extend(Tensor._wrap(a) for a in leaves)
            return tuple(res)
        return g

    const_out = {i: pl[1] for i, pl in enumerate(plans) if pl[0] == "const"}
    if tensor_ix:
        outs = static_cond(pred, _wrap(true_fn), _wrap(false_fn), operands,
                           params=_layer_params(operands))
        outs = list(outs) if isinstance(outs, (tuple, list)) else [outs]
    else:
        outs = []
    # reassemble per-name values (unflatten pytree slots)
    full = []
    k = 0
    for i in range(n):
        if plans[i][0] == "tree":
            _, treedef, leaf_specs = plans[i]
            nleaf = len(leaf_specs)
            full.append(jax.tree_util.tree_unflatten(
                treedef, outs[k:k + nleaf]))
            k += nleaf
        else:
            full.append(const_out[i])
    return tuple(full)


def _is_missing(v):
    return v is None or isinstance(v, UndefinedVar)


def _is_leaf_obj(v):
    # Tensors are opaque to jax pytrees already, but be explicit so a
    # future pytree registration cannot change flattening here
    return isinstance(v, Tensor)


def _leaf_array(v):
    return v._value() if isinstance(v, Tensor) else jnp.asarray(v)


def _probe_branch(fn, operands):
    """Run a branch under eval_shape and capture its RAW python outputs
    (Tensors wrap abstract tracers — shape/dtype readable, values not)."""
    from ..core import autograd

    cap = {}

    def f(*arrs):
        it = iter(arrs)
        full = [Tensor._wrap(next(it)) if isinstance(o, Tensor) else o
                for o in operands]
        with autograd.no_grad():
            out = fn(*full)
        cap["outs"] = tuple(out) if isinstance(out, (tuple, list)) \
            else (out,)
        return jnp.zeros(())

    jax.eval_shape(
        f, *[o._value() for o in operands if isinstance(o, Tensor)])
    return cap["outs"]


def _unify_slot(t, f, name, guard=False):
    """Decide how one assigned name flows through a traced cond.

    Returns ("tree", treedef, [(shape, dtype), ...]) for values carried
    through lax.cond, or ("const", value) for values kept outside it.
    ``guard`` marks a return-flag tail guard: every variable first
    assigned there is dead on the flag-set path (the function returns
    immediately after), so missing-side placeholders are always safe."""
    # "missing" = genuinely UNBOUND (UndefinedVar from an ld miss).  An
    # explicit `None` binding is a VALUE for user variables — folding it
    # away would silently override `x = None` defaults on the untaken
    # path.  Only the generated return-value slots treat None as
    # missing (their None init is the machinery's own placeholder).
    def missing(v):
        if isinstance(v, UndefinedVar):
            return True
        # generated slots initialize with None as the machinery's own
        # placeholder; inside a return-flag guard every value is dead on
        # the flag path, so None is equally placeholder-able there
        return v is None and (guard or name.startswith(_GEN_PREFIX))

    t_missing, f_missing = missing(t), missing(f)
    if (t is None) != (f is None) and not (t_missing or f_missing):
        other = f if t is None else t
        if not _is_traced_val(other) and not isinstance(other, Tensor):
            # two concrete python values (None vs e.g. a string): a
            # traced condition cannot select between them
            raise Dy2StaticError(
                f"variable '{name}' is None on one path and a "
                f"non-tensor value ({type(other).__name__}) on the "
                "other of a converted `if` over a traced Tensor; a "
                "compiled branch cannot select between python objects "
                "— use tensor values on both paths")
        raise Dy2StaticError(
            f"variable '{name}' is None on one path of a converted "
            "`if` over a traced Tensor and a tensor on the other; "
            "assign a correctly-typed tensor default before the `if` "
            "instead of None")
    if isinstance(t, _RetNone) or isinstance(f, _RetNone):
        # bare return on one side: compatible with another bare return or
        # with "not returned yet" (the value stays None either way), but
        # NOT with a tensor — that would make the return structure depend
        # on a traced value
        other = f if isinstance(t, _RetNone) else t
        if isinstance(other, _RetNone) or _is_missing(other):
            return ("const", RET_NONE)
        raise Dy2StaticError(
            "this function returns a value on one path and bare "
            "`return`/None on another inside a traced `if`; a compiled "
            "program needs one return structure — return a tensor on "
            "every path")
    if t_missing and f_missing:
        return ("const", t if t is not None else f)
    if t_missing or f_missing:
        present = f if t_missing else t
        leaves, treedef = jax.tree_util.tree_flatten(
            present, is_leaf=_is_leaf_obj)
        # a fully CONCRETE value (python scalar, list of constants, any
        # object holding no trace-time tensors) bound in one branch only
        # passes through as a constant — branch-local temps just work;
        # python would only differ by NameError-ing on the untaken path
        if not any(_is_traced_val(lv) for lv in leaves):
            return ("const", present)
        if guard:
            if any(not _arrayable(lv) for lv in leaves):
                return ("const", present)
            return ("tree", treedef,
                    [_aval_of(lv) for lv in leaves])
        if name.startswith(_GEN_PREFIX) and \
                all(_arrayable(lv) for lv in leaves):
            return ("tree", treedef,
                    [_aval_of(lv) for lv in leaves])
        raise Dy2StaticError(
            f"variable '{name}' is assigned a traced value in only one "
            "branch of an `if` whose condition is a traced Tensor; under "
            "static conversion both branches must bind it — assign a "
            "default before the `if`")
    t_leaves, t_def = jax.tree_util.tree_flatten(t, is_leaf=_is_leaf_obj)
    f_leaves, f_def = jax.tree_util.tree_flatten(f, is_leaf=_is_leaf_obj)
    if t_def != f_def:
        raise Dy2StaticError(
            f"variable '{name}' has mismatched structures across the two "
            f"branches of a converted `if` ({t_def} vs {f_def}); both "
            "branches must produce the same nesting of values")
    if all(not _arrayable(lv) for lv in t_leaves + f_leaves):
        # plain python objects on both sides (strings, layers, ...):
        # identical values pass through, different values cannot be
        # selected at run time
        if _const_equal(t, f):
            return ("const", t)
        raise Dy2StaticError(
            f"variable '{name}' is bound to different non-tensor python "
            f"values in the two branches of a converted `if` "
            f"({t!r} vs {f!r}); a traced condition can only select "
            "tensor values")
    specs = []
    for name_i, (tl, fl) in enumerate(zip(t_leaves, f_leaves)):
        if not (_arrayable(tl) and _arrayable(fl)):
            raise Dy2StaticError(
                f"variable '{name}' mixes tensor and non-tensor values "
                "across the branches of a converted `if`; both branches "
                "must produce tensors (or equal python constants)")
        tsh, tdt = _aval_of(tl)
        fsh, fdt = _aval_of(fl)
        sh = _broadcast_shapes(tsh, fsh, name)
        specs.append((sh, jnp.promote_types(tdt, fdt)))
    return ("tree", t_def, specs)


def _arrayable(v):
    return isinstance(v, (Tensor, jax.Array)) or (
        isinstance(v, (bool, int, float)) or _np_scalar(v))


def _np_scalar(v):
    import numpy as _np
    return isinstance(v, (_np.ndarray, _np.generic))


def _aval_of(v):
    if isinstance(v, Tensor):
        a = v._value()
        return tuple(a.shape), a.dtype
    a = jnp.asarray(v) if not isinstance(v, jax.Array) else v
    return tuple(a.shape), a.dtype


def _broadcast_shapes(a, b, name):
    try:
        # jnp handles SYMBOLIC dims (shape-polymorphic jit.save export);
        # np.broadcast_shapes rejects _DimExpr entries
        return tuple(jnp.broadcast_shapes(tuple(a), tuple(b)))
    except Exception:
        raise Dy2StaticError(
            f"variable '{name}' has incompatible shapes across the two "
            f"branches of a converted `if` ({a} vs {b})")


def _const_equal(a, b):
    if a is b:
        return True
    try:
        return bool(a == b)
    except Exception:
        return False


def _promote_loop_vars(vars_):
    """Python scalars in a TRACED loop must become Tensors, or their
    body updates would be silently dropped by lax.while_loop."""
    out = []
    for v in vars_:
        if isinstance(v, (bool, int, float)) and not isinstance(v, Tensor):
            out.append(Tensor._wrap(jnp.asarray(v)))
        else:
            out.append(v)
    return out


def _check_loop_carry(names, vars_, probe):
    """A tensor-dependent loop carries a fixed structure: a var that is
    None/unbound at entry but becomes a Tensor inside the body would be
    silently dropped by lax.while_loop — catch it with a named error.
    EXCEPTIONS: the generated return-value slot (``__jstf_val_*``) is
    dead until its flag is set, and the flag-setting iteration always
    assigns it — fill it with a placeholder of the probed shape/dtype so
    early `return` inside a tensor-dependent loop compiles (the same
    dead-slot argument convert_ifelse applies to one-sided returns).
    The for-range shadow target (``__jstf_tgt_*``) likewise starts
    unbound when the loop target was never pre-bound; the range
    machinery already overshoot-corrects an unbound target after the
    loop, so a placeholder is equally unobservable.
    `probe` abstractly evaluates the body; probe failures are ignored
    (the real trace will surface them with context).  Returns ``vars_``,
    possibly with placeholders filled."""
    if names is None:
        return vars_
    missing = [i for i, v in enumerate(vars_) if _is_missing(v)]
    if not missing:
        return vars_
    try:
        outs = probe()
    except Exception:
        return vars_
    vars_ = list(vars_)
    for i in missing:
        if i < len(outs) and isinstance(outs[i], Tensor):
            nm = names[i]
            if nm.startswith((_GEN_PREFIX + "val", _GEN_PREFIX + "tgt")):
                a = outs[i]._value()     # abstract: shape/dtype readable
                vars_[i] = Tensor._wrap(jnp.zeros(a.shape, a.dtype))
                continue
            raise Dy2StaticError(
                f"loop variable '{nm}' enters a tensor-dependent loop "
                "unbound (or None) but is assigned a Tensor inside the "
                "body; initialize it with a correctly-shaped tensor "
                "before the loop so the compiled loop can carry it")
    return vars_


# abstract body probe: identical contract to the branch probe — one
# implementation serves both (defined with convert_ifelse below)
def _probe_body(body_fn, vars_):
    return _probe_branch(body_fn, vars_)


def convert_while(cond_fn, body_fn, init_vars, names=None):
    """``while cond: body`` over the body's assigned names."""
    from ..static.nn import while_loop

    def _lower(vars_):
        vars_ = _promote_loop_vars(vars_)
        vars_ = _check_loop_carry(
            names, vars_, lambda: _probe_body(body_fn, vars_))
        return tuple(while_loop(cond_fn, body_fn, vars_))

    vars_ = list(init_vars)
    if any(_is_traced(v) for v in vars_):
        return _lower(vars_)
    # Concrete state: run the python loop, evaluating the condition
    # exactly once per iteration (python's count — conditions with side
    # effects behave identically).  The CONDITION decides when to lower:
    # the moment it comes back traced (e.g. a break flag set inside a
    # tensor-dependent branch), hand the CURRENT state to the compiled
    # while_loop — completed iterations stay applied, lax runs the rest.
    # Body vars turning traced while the condition stays concrete is
    # plain eager-style unrolling and needs no lowering.
    while True:
        test = cond_fn(*vars_)
        if _is_traced(test):
            return _lower(vars_)
        if not _truthy(test):
            return tuple(vars_)
        res = body_fn(*vars_)
        vars_ = list(res) if isinstance(res, (tuple, list)) else [res]


def convert_range_loop(start, stop, step, body_fn, init_vars, names=None,
                       target_init=None):
    """``for i in range(start, stop, step): body`` — body_fn(i, *vars) ->
    vars.  Returns ``(final_target, *vars)``: python leaves the loop
    target bound to the last iterated value, and code after the loop may
    read it.  Concrete bounds run the plain python loop (still unrolls
    under an outer trace); traced bounds lower to a while_loop with the
    index as a carried Tensor.  Body reassignment of the target is local
    to the iteration (it does not alter the final value) — same contract
    as the carried-index lowering."""
    from ..static.nn import while_loop

    bounds = [start, stop, step]
    if any(_is_traced(b) for b in bounds):
        # probe with a TRACED index: in the lowered loop the index is a
        # carried Tensor, so anything assigned from it (the break-shadow
        # target in particular) comes out traced — a concrete probe
        # index would under-report that and leave the carry unfixable
        start_t = start if isinstance(start, Tensor) else Tensor._wrap(
            jnp.asarray(start))
        init_vars = _check_loop_carry(
            names, list(init_vars),
            lambda: _probe_body(lambda i0, *vs: body_fn(i0, *vs),
                                [start_t] + list(init_vars)))
    if not any(_is_traced(b) for b in bounds):
        vars_ = tuple(init_vars)
        tgt = target_init
        s0 = int(start.item() if isinstance(start, Tensor) else start)
        s1 = int(stop.item() if isinstance(stop, Tensor) else stop)
        st = int(step.item() if isinstance(step, Tensor) else step)
        for i in range(s0, s1, st):
            tgt = i
            vars_ = body_fn(i, *vars_)
        return (tgt,) + tuple(vars_)

    init = _promote_loop_vars([start] + list(init_vars))
    step_c = step if isinstance(step, Tensor) else Tensor._wrap(
        jnp.asarray(step))
    stop_c = stop if isinstance(stop, Tensor) else Tensor._wrap(
        jnp.asarray(stop))

    def _cond(i, *vars_):
        up = (step_c._value() if isinstance(step_c, Tensor) else step_c) > 0
        iv = i._value() if isinstance(i, Tensor) else i
        sv = stop_c._value()
        return Tensor._wrap(jnp.where(up, iv < sv, iv > sv))

    def _body(i, *vars_):
        new = body_fn(i, *vars_)
        new = new if isinstance(new, tuple) else (new,)
        nxt = Tensor._wrap(
            (i._value() if isinstance(i, Tensor) else i)
            + (step_c._value() if isinstance(step_c, Tensor) else step_c))
        return (nxt,) + tuple(new)

    out = while_loop(_cond, _body, init)
    # the carried index overshoots by one step; python's final target is
    # the last IN-range value — select the pre-loop binding when the loop
    # ran zero times (if that binding is not a number, the overshoot-
    # corrected value stands in: python would have left the name unbound)
    over = out[0]
    sa = step_c._value()
    st_a = start._value() if isinstance(start, Tensor) else jnp.asarray(start)
    sp_a = stop_c._value()
    ran = jnp.where(sa > 0, st_a < sp_a, st_a > sp_a)
    last = (over._value() if isinstance(over, Tensor) else
            jnp.asarray(over)) - sa
    if target_init is not None and not isinstance(target_init, UndefinedVar):
        try:
            ti = jnp.asarray(
                target_init._value() if isinstance(target_init, Tensor)
                else target_init).astype(last.dtype)
            last = jnp.where(ran, last, ti)
        except Exception:
            pass
    return (Tensor._wrap(last),) + tuple(out[1:])


# ---------------------------------------------------------------------------
# AST analysis
# ---------------------------------------------------------------------------

_SCOPE_BARRIERS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
_BAIL_NODES = (ast.Return, ast.Break, ast.Continue, ast.Global,
               ast.Nonlocal, ast.Delete, ast.Yield, ast.YieldFrom,
               ast.Await)


def _walk_scope(node):
    """ast.walk that does not descend into nested function/class defs
    (their bodies are separate scopes), but does cover lambdas and
    comprehensions (their reads matter for capture)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, _SCOPE_BARRIERS):
            stack.extend(ast.iter_child_nodes(n))


def _walk_stmt(s):
    """The statement itself plus its same-scope subtree (if the statement
    IS a def, its body is a separate scope and is not entered)."""
    yield s
    if not isinstance(s, _SCOPE_BARRIERS):
        yield from _walk_scope(s)


def _nonname_store(n) -> bool:
    """Assignments into attributes/subscripts are object mutations whose
    effects would silently vanish inside a traced branch — bail."""
    tgts = []
    if isinstance(n, ast.Assign):
        tgts = n.targets
    elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
        tgts = [n.target]

    def bad(t):
        if isinstance(t, (ast.Attribute, ast.Subscript, ast.Starred)):
            return not isinstance(t, ast.Starred) or bad(t.value)
        if isinstance(t, (ast.Tuple, ast.List)):
            return any(bad(e) for e in t.elts)
        return False

    return any(bad(t) for t in tgts)


_BAIL_KEYWORD = {
    ast.Return: "return", ast.Break: "break", ast.Continue: "continue",
    ast.Global: "global", ast.Nonlocal: "nonlocal", ast.Delete: "del",
    ast.Yield: "yield", ast.YieldFrom: "yield from", ast.Await: "await",
}


# container-mutation methods that cannot cross a compiled region.
# `add`/`sort`/`reverse` are deliberately absent: they collide with
# (out-of-place) Tensor methods and would false-positive on `t.add(y)`
_MUTATING_METHODS = frozenset({
    "append", "extend", "insert", "pop", "remove", "clear", "update",
    "setdefault", "discard", "popitem"})


def _mutation_receiver(n):
    """(root_name, dotted_receiver) when `n` is a mutating method call on
    a name or attribute chain (buf.append / self.log.append), else
    None."""
    if not (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
            and n.func.attr in _MUTATING_METHODS):
        return None
    parts = []
    root = n.func.value
    while isinstance(root, ast.Attribute):
        parts.append(root.attr)
        root = root.value
    if not isinstance(root, ast.Name):
        return None
    parts.append(root.id)
    return root.id, ".".join(reversed(parts))


def _bail_reason(stmts) -> Optional[str]:
    """Why this statement region cannot become a branch/loop-body
    function — None when it can."""
    assigned = _assigned_names(stmts)
    for s in stmts:
        for n in _walk_stmt(s):
            if _nonname_store(n):
                return ("it assigns into an attribute/subscript (object "
                        "mutation cannot cross a compiled branch)")
            # list.append(...) etc. on a container from OUTSIDE the
            # region (bare name or attribute chain like self.log): under
            # tracing the call would run trace-count times (once per
            # branch / once per loop), not run-count times — silently
            # wrong sizes.  A container CREATED in the region is
            # trace-local and fine.
            recv = _mutation_receiver(n)
            if recv is not None and recv[0] not in assigned:
                return (f"it mutates `{recv[1]}` in place via "
                        f".{n.func.attr}() — a python container cannot "
                        "carry through a compiled branch/loop; collect "
                        "into a Tensor instead")
            if isinstance(n, _BAIL_NODES):
                # break/continue inside a NESTED loop are that loop's
                # business, not ours
                if isinstance(n, (ast.Break, ast.Continue)):
                    if _inside_nested_loop(s, n):
                        continue
                kw = _BAIL_KEYWORD.get(type(n), type(n).__name__)
                return f"it contains `{kw}`"
    return None


def _has_bail(stmts) -> bool:
    return _bail_reason(stmts) is not None


def _inside_nested_loop(root_stmt, node) -> bool:
    """True if `node` sits under a For/While that is itself inside
    root_stmt (so the break/continue does not escape the converted
    region)."""
    # collect all loop subtrees strictly inside root_stmt
    for n in _walk_scope(root_stmt):
        if isinstance(n, (ast.For, ast.While)):
            for m in [n] + list(_walk_scope(n)):
                if m is node:
                    return True
    return False


def _assigned_names(stmts) -> Set[str]:
    names: Set[str] = set()

    def targets_of(t):
        if isinstance(t, ast.Name):
            names.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                targets_of(e)
        elif isinstance(t, ast.Starred):
            targets_of(t.value)

    for s in stmts:
        for n in _walk_stmt(s):
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    targets_of(t)
            elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
                targets_of(n.target)
            elif isinstance(n, ast.For):
                targets_of(n.target)
            elif isinstance(n, ast.withitem) and n.optional_vars:
                targets_of(n.optional_vars)
            elif isinstance(n, ast.NamedExpr):
                targets_of(n.target)
            elif isinstance(n, _SCOPE_BARRIERS):
                names.add(n.name)
    # generated helpers are locals of their own region, and function/class
    # defs cannot cross a lax.cond boundary as outputs
    return {n for n in names if not n.startswith("__jst_")}


def _is_converted_unpack(n) -> bool:
    """An Assign generated by an earlier (innermost-first) conversion:
    ``b, = _jst.convert_ifelse(...)`` / ``convert_while`` / range-loop."""
    return (isinstance(n, ast.Assign)
            and isinstance(n.value, ast.Call)
            and isinstance(n.value.func, ast.Attribute)
            and n.value.func.attr.startswith("convert_")
            and isinstance(n.value.func.value, ast.Name)
            and n.value.func.value.id == "_jst")


def _loaded_names(stmts) -> Set[str]:
    loads: Set[str] = set()
    for s in stmts:
        for n in _walk_stmt(s):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                loads.add(n.id)
            elif isinstance(n, ast.AugAssign) and \
                    isinstance(n.target, ast.Name):
                # `y += 2` reads y even though the AST marks the target
                # Store-only; missing it made the generated branch
                # function treat y as an uninitialized local
                # (UnboundLocalError at call time)
                loads.add(n.target.id)
            elif _is_converted_unpack(n):
                # outputs of an inner converted construct READ their
                # pre-value on the untaken/zero-trip side — but the read
                # sits inside the generated branch funcdefs, which are
                # scope barriers this walk rightly skips.  Count the
                # targets as reads so an enclosing conversion passes the
                # pre-value in as a parameter (else python shadows it
                # and the inner thunk sees an unbound local).
                loads.update(_assigned_names([n]))
    return {n for n in loads if not n.startswith("__jst_")}


# ---------------------------------------------------------------------------
# transformer
# ---------------------------------------------------------------------------

def _name(id_, ctx=None):
    return ast.Name(id=id_, ctx=ctx or ast.Load())


def _jst_attr(fn_name):
    return ast.Attribute(value=_name("_jst"), attr=fn_name, ctx=ast.Load())


def _ld_expr(var: str):
    """_jst.ld(lambda: var, 'var')"""
    lam = ast.Lambda(
        args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                           kw_defaults=[], defaults=[]),
        body=_name(var))
    return ast.Call(func=_jst_attr("ld"),
                    args=[lam, ast.Constant(var)], keywords=[])


def _branch_funcdef(fname: str, params: List[str], body: List[ast.stmt],
                    out_names: List[str]) -> ast.FunctionDef:
    ret = ast.Return(value=ast.Tuple(
        elts=[_ld_expr(n) for n in out_names], ctx=ast.Load()))
    return ast.FunctionDef(
        name=fname,
        args=ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=p) for p in params],
            kwonlyargs=[], kw_defaults=[], defaults=[]),
        body=(body or [ast.Pass()]) + [ret],
        decorator_list=[])


def _unpack_assign(out_names: List[str], value: ast.expr) -> ast.stmt:
    tgt = ast.Tuple(elts=[_name(n, ast.Store()) for n in out_names],
                    ctx=ast.Store())
    return ast.Assign(targets=[tgt], value=value)


def _assign(name: str, value: ast.expr) -> ast.stmt:
    return ast.Assign(targets=[_name(name, ast.Store())], value=value)


def _lambda0(body: ast.expr) -> ast.expr:
    return ast.Lambda(
        args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                           kw_defaults=[], defaults=[]),
        body=body)


def _not(e: ast.expr) -> ast.expr:
    return ast.UnaryOp(op=ast.Not(), operand=e)


def _contains_return(s) -> bool:
    return any(isinstance(n, ast.Return) for n in _walk_stmt(s))


def _always_returns(stmts) -> bool:
    """Conservative terminal-path analysis: True when every way out of
    this statement list is a `return` or `raise` (loops are assumed
    skippable, so they never count — EXCEPT ``while True`` without a
    break, which python can only leave by returning/raising)."""
    for s in stmts:
        if isinstance(s, (ast.Return, ast.Raise)):
            return True
        if isinstance(s, ast.If) and s.orelse:
            if _always_returns(s.body) and _always_returns(s.orelse):
                return True
        if (isinstance(s, ast.While) and isinstance(s.test, ast.Constant)
                and s.test.value and not s.orelse
                and not _owned_bc(s.body)[0]):
            return True
    return False


class _ReturnTransformer:
    """Rewrite `return` inside control flow into a flag + value pair with
    guarded tails (reference return_transformer.py:136,
    early_return_transformer.py).  Inside a loop the flag set is followed
    by `break` (consumed by _BreakContinueTransformer); after a nested
    construct that may have returned, `if flag: break` (in a loop) or an
    `if not flag:` tail guard (outside) keeps later statements from
    running."""

    def __init__(self, uid: int):
        self.flag = f"{_GEN_PREFIX}ret_{uid}"
        self.val = f"{_GEN_PREFIX}val_{uid}"
        self.applied = False

    def run(self, fdef):
        if not any(isinstance(s, (ast.If, ast.While, ast.For))
                   and _contains_return(s) for s in fdef.body):
            return
        always = _always_returns(fdef.body)
        self.applied = True
        body, _may = self._block(list(fdef.body), in_loop=False)
        if always:
            # every path returns → the flag is True at the end and the
            # value is always well-defined (unwrap a bare-return marker)
            tail = ast.Return(value=ast.Call(
                func=_jst_attr("ret_unwrap"), args=[_name(self.val)],
                keywords=[]))
        else:
            # fall-through possible → `val if flag else None`, with a
            # clear error when the flag itself is traced (mixed
            # tensor/None return structure cannot compile)
            tail = ast.Return(value=ast.Call(
                func=_jst_attr("ret_value"),
                args=[_name(self.flag), _name(self.val)], keywords=[]))
        fdef.body = [
            _assign(self.flag, ast.Constant(False)),
            _assign(self.val, ast.Constant(None)),
        ] + body + [tail]

    def _block(self, stmts, in_loop):
        out: List[ast.stmt] = []
        for i, s in enumerate(stmts):
            if isinstance(s, ast.Return):
                out.append(_assign(self.flag, ast.Constant(True)))
                # bare `return` / `return None` stores the RET_NONE
                # sentinel, NOT None — plain None means "never assigned"
                # to the branch unifier
                bare = s.value is None or (
                    isinstance(s.value, ast.Constant)
                    and s.value.value is None)
                out.append(_assign(
                    self.val,
                    _jst_attr("RET_NONE") if bare else s.value))
                if in_loop:
                    out.append(ast.Break())
                return out, True           # rest is unreachable
            if isinstance(s, (ast.If, ast.While, ast.For)) and \
                    _contains_return(s):
                s, smay = self._compound(s, in_loop)
                out.append(s)
                if smay:
                    rest, _ = self._block(list(stmts[i + 1:]), in_loop)
                    if in_loop:
                        # a set flag must also exit this (enclosing) loop
                        out.append(ast.If(test=_name(self.flag),
                                          body=[ast.Break()], orelse=[]))
                        out.extend(rest)
                    elif rest:
                        out.append(ast.If(test=_not(_name(self.flag)),
                                          body=rest, orelse=[]))
                    return out, True
                continue
            out.append(s)
        return out, False

    def _compound(self, s, in_loop):
        if isinstance(s, ast.If):
            b, m1 = self._block(list(s.body), in_loop)
            o, m2 = self._block(list(s.orelse), in_loop)
            s.body = b or [ast.Pass()]
            s.orelse = o
            return s, m1 or m2
        # While / For: returns in the body exit via the injected break
        b, m = self._block(list(s.body), in_loop=True)
        s.body = b or [ast.Pass()]
        if s.orelse:
            o, m2 = self._block(list(s.orelse), in_loop)
            s.orelse = o
            m = m or m2
        return s, m


def _owned_bc(body_stmts):
    """(has_break, has_continue) whose innermost enclosing loop is the
    loop owning `body_stmts`.  With/Try are not entered: a break inside
    them stays python (the region then bails, keeping the loop python —
    consistent either way)."""
    brk = cont = False

    def scan(stmts):
        nonlocal brk, cont
        for s in stmts:
            if isinstance(s, ast.Break):
                brk = True
            elif isinstance(s, ast.Continue):
                cont = True
            elif isinstance(s, ast.If):
                scan(s.body)
                scan(s.orelse)
            # For/While own their inner break/continue; With/Try/defs
            # are left alone on purpose
    scan(body_stmts)
    return brk, cont


class _BreakContinueTransformer(ast.NodeTransformer):
    """break → loop flag folded into the loop condition; continue → flag
    guarding the rest of the iteration (reference
    break_continue_transformer.py:89).  For-range loops containing either
    are rewritten into the equivalent while so the flag can live in the
    condition."""

    def __init__(self):
        self._uid = 0
        self.changed = False

    def _next(self):
        self._uid += 1
        return self._uid

    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        return node

    def visit_Lambda(self, node):
        return node

    def visit_While(self, node: ast.While):
        self.generic_visit(node)       # innermost loops first
        has_brk, has_cont = _owned_bc(node.body)
        if node.orelse or not (has_brk or has_cont):
            return node
        self.changed = True
        uid = self._next()
        brk = f"{_GEN_PREFIX}brk_{uid}"
        cont = f"{_GEN_PREFIX}cont_{uid}"
        body = self._block(list(node.body), brk, cont,
                           has_brk, has_cont) or [ast.Pass()]
        if has_cont:
            body = [_assign(cont, ast.Constant(False))] + body
        test = node.test
        if has_brk:
            test = ast.BoolOp(op=ast.And(),
                              values=[_not(_name(brk)), test])
        pre = []
        if has_brk:
            pre.append(_assign(brk, ast.Constant(False)))
        if has_cont:
            pre.append(_assign(cont, ast.Constant(False)))
        return pre + [ast.While(test=test, body=body, orelse=[])]

    def visit_For(self, node: ast.For):
        self.generic_visit(node)
        has_brk, has_cont = _owned_bc(node.body)
        if node.orelse or not (has_brk or has_cont):
            return node
        if (not isinstance(node.target, ast.Name)
                or not isinstance(node.iter, ast.Call)
                or not isinstance(node.iter.func, ast.Name)
                or node.iter.func.id != "range"):
            return node     # non-range for keeps python break/continue
        # Keep the `for range` form (so concrete bounds still unroll with
        # a python index) and guard the whole body with the break flag:
        # after a `break` the remaining iterations become no-ops, which
        # both the unrolled and the lax-lowered paths handle.
        self.changed = True
        uid = self._next()
        brk = f"{_GEN_PREFIX}brk_{uid}"
        cont = f"{_GEN_PREFIX}cont_{uid}"
        body = self._block(list(node.body), brk, cont,
                           has_brk, has_cont) or [ast.Pass()]
        if has_cont:
            body = [_assign(cont, ast.Constant(False))] + body
        pre = []
        post = []
        if has_brk:
            # after a break python's loop target stays at the breaking
            # iteration, but the flag-guarded loop keeps iterating as a
            # no-op — freeze the target in a shadow that only advances
            # while the loop is live, and restore it afterwards
            shadow = f"{_GEN_PREFIX}tgt_{uid}"
            tgt_name = (node.target.id
                        if isinstance(node.target, ast.Name) else None)
            if tgt_name is not None:
                body = [_assign(shadow, _name(tgt_name))] + body
                pre.append(_assign(shadow, _ld_expr(tgt_name)))
                post.append(_assign(tgt_name, _name(shadow)))
            body = [ast.If(test=_not(_name(brk)), body=body, orelse=[])]
            pre.append(_assign(brk, ast.Constant(False)))
        if has_cont:
            pre.append(_assign(cont, ast.Constant(False)))
        node.body = body
        return pre + [node] + post

    def _block(self, stmts, brk, cont, has_brk, has_cont):
        out: List[ast.stmt] = []
        for i, s in enumerate(stmts):
            if isinstance(s, ast.Break):
                out.append(_assign(brk, ast.Constant(True)))
                return out
            if isinstance(s, ast.Continue):
                out.append(_assign(cont, ast.Constant(True)))
                return out
            if isinstance(s, ast.If) and any(_owned_bc([s])):
                s.body = self._block(list(s.body), brk, cont,
                                     has_brk, has_cont) or [ast.Pass()]
                s.orelse = self._block(list(s.orelse), brk, cont,
                                       has_brk, has_cont)
                out.append(s)
                rest = self._block(list(stmts[i + 1:]), brk, cont,
                                   has_brk, has_cont)
                if rest:
                    flags = []
                    if has_brk:
                        flags.append(_name(brk))
                    if has_cont:
                        flags.append(_name(cont))
                    guard = flags[0] if len(flags) == 1 else \
                        ast.BoolOp(op=ast.Or(), values=flags)
                    out.append(ast.If(test=_not(guard), body=rest,
                                      orelse=[]))
                return out
            out.append(s)
        return out


class _LogicalTransformer(ast.NodeTransformer):
    """and/or/not → short-circuit-preserving converter calls that lower
    to logical ops on traced tensors (reference logical_transformer.py).
    Operand evaluation is wrapped in lambdas so the python short-circuit
    contract holds exactly for concrete values."""

    def __init__(self):
        self.changed = False

    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        return node

    def visit_Lambda(self, node):
        return node

    @staticmethod
    def _lambda_unsafe(*exprs) -> bool:
        # walrus bindings would become lambda-local (PEP 572),
        # yield/await cannot live in a lambda at all, and a container
        # mutation (buf.pop()) would execute trace-count times under a
        # traced predicate — keep python semantics (loud error when
        # traced) for such operands
        for e in exprs:
            for n in ast.walk(e):
                if isinstance(n, (ast.NamedExpr, ast.Yield,
                                  ast.YieldFrom, ast.Await)):
                    return True
                if _mutation_receiver(n) is not None:
                    return True
        return False

    def visit_BoolOp(self, node: ast.BoolOp):
        self.generic_visit(node)
        if self._lambda_unsafe(*node.values):
            return node
        fname = ("convert_logical_and" if isinstance(node.op, ast.And)
                 else "convert_logical_or")
        expr = node.values[-1]
        for v in reversed(node.values[:-1]):
            expr = ast.Call(func=_jst_attr(fname),
                            args=[_lambda0(v), _lambda0(expr)],
                            keywords=[])
        self.changed = True
        return expr

    def visit_UnaryOp(self, node: ast.UnaryOp):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            self.changed = True
            return ast.Call(func=_jst_attr("convert_logical_not"),
                            args=[node.operand], keywords=[])
        return node

    def visit_Compare(self, node: ast.Compare):
        """``a < b < c`` → ``_jst.convert_chain_compare(lambda: a,
        ("Lt", lambda: b), ("Lt", lambda: c))`` so a chained comparison
        over traced tensors converts like the explicit BoolOp would.
        The runtime helper evaluates each operand AT MOST once and
        short-circuits concrete-false comparisons, so python's chain
        contract holds exactly even for impure operands; only
        lambda-hostile operands (walrus/yield/mutation) stay python."""
        self.generic_visit(node)
        if len(node.ops) < 2:
            return node
        operands = [node.left] + node.comparators
        if self._lambda_unsafe(*operands):
            return node
        pair_args = [
            ast.Tuple(elts=[ast.Constant(type(op).__name__),
                            _lambda0(operands[i + 1])],
                      ctx=ast.Load())
            for i, op in enumerate(node.ops)]
        self.changed = True
        return ast.Call(func=_jst_attr("convert_chain_compare"),
                        args=[_lambda0(node.left)] + pair_args,
                        keywords=[])

    def visit_IfExp(self, node: ast.IfExp):
        self.generic_visit(node)
        if self._lambda_unsafe(node.body, node.orelse):
            return node
        self.changed = True
        return ast.Call(func=_jst_attr("convert_ifexp"),
                        args=[node.test, _lambda0(node.body),
                              _lambda0(node.orelse)], keywords=[])


class _CallSiteWrapper(ast.NodeTransformer):
    """foo(args) -> _jst.cvt(foo)(args) for plain-name/attribute callees,
    so user helper functions get converted recursively (reference
    convert_call).  Generated _jst.* calls are left alone."""

    def visit_Call(self, node: ast.Call):
        self.generic_visit(node)
        f = node.func
        if isinstance(f, ast.Name) and not f.id.startswith("__jst_"):
            pass
        elif isinstance(f, ast.Attribute):
            root = f
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) and root.id == "_jst":
                return node
        else:
            return node
        node.func = ast.Call(func=_jst_attr("cvt"), args=[f], keywords=[])
        return node


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self, filename: str = "<unknown>"):
        self.changed = False
        self._uid = 0
        self._filename = filename

    def _next(self, kind):
        self._uid += 1
        return f"__jst_{kind}_{self._uid}"

    def _bail(self, node, construct, reason):
        _record_bail(self._filename, node, construct, reason)
        return node

    # do not descend into nested defs — they are separate conversions
    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        return node

    def visit_Lambda(self, node):
        return node

    def visit_If(self, node: ast.If):
        self.generic_visit(node)   # innermost first
        r = _bail_reason(node.body) or _bail_reason(node.orelse)
        if r:
            return self._bail(node, "if", r)
        assigned = sorted(_assigned_names(node.body)
                          | _assigned_names(node.orelse))
        if not assigned:
            # nothing flows out: conversion could only lose side-effect
            # semantics under tracing — keep the python if
            return self._bail(
                node, "if",
                "no variable assignment flows out of it (side-effect-"
                "only branches stay python)")
        reads = sorted((_loaded_names(node.body)
                        | _loaded_names(node.orelse)
                        | _loaded_names([ast.Expr(node.test)])) - {"_jst"})
        tname = self._next("true")
        fname = self._next("false")
        true_def = _branch_funcdef(tname, reads, node.body, assigned)
        false_def = _branch_funcdef(fname, reads, node.orelse, assigned)
        # a return-flag tail guard (`if not __jstf_ret_N:`) may fill
        # one-sided assignments with placeholders: they are dead on the
        # flag-set path (the function returns right after)
        is_guard = any(
            isinstance(m, ast.Name)
            and m.id.startswith(_GEN_PREFIX + "ret")
            for m in ast.walk(node.test))
        kw = [ast.keyword(
            arg="names",
            value=ast.Tuple(elts=[ast.Constant(n) for n in assigned],
                            ctx=ast.Load()))]
        if is_guard:
            kw.append(ast.keyword(arg="guard", value=ast.Constant(True)))
        call = ast.Call(
            func=_jst_attr("convert_ifelse"),
            args=[node.test, _name(tname), _name(fname),
                  ast.Tuple(elts=[_ld_expr(r) for r in reads],
                            ctx=ast.Load())],
            keywords=kw)
        self.changed = True
        return [true_def, false_def, _unpack_assign(assigned, call)]

    def visit_While(self, node: ast.While):
        self.generic_visit(node)
        if node.orelse:
            return self._bail(node, "while", "it has an `else` clause")
        r = _bail_reason(node.body)
        if r:
            return self._bail(node, "while", r)
        assigned = sorted(_assigned_names(node.body))
        if not assigned:
            return self._bail(node, "while",
                              "no variable assignment flows out of it")
        reads = sorted((_loaded_names(node.body)
                        | _loaded_names([ast.Expr(node.test)]))
                       - set(assigned) - {"_jst"})
        cname = self._next("cond")
        bname = self._next("body")
        params = assigned  # loop-carried; reads stay free (closures)
        cond_def = ast.FunctionDef(
            name=cname,
            args=ast.arguments(posonlyargs=[],
                               args=[ast.arg(arg=p) for p in params],
                               kwonlyargs=[], kw_defaults=[], defaults=[]),
            body=[ast.Return(value=node.test)],
            decorator_list=[])
        body_def = _branch_funcdef(bname, params, node.body, assigned)
        call = ast.Call(
            func=_jst_attr("convert_while"),
            args=[_name(cname), _name(bname),
                  ast.Tuple(elts=[_ld_expr(n) for n in assigned],
                            ctx=ast.Load())],
            keywords=[ast.keyword(
                arg="names",
                value=ast.Tuple(elts=[ast.Constant(n) for n in assigned],
                                ctx=ast.Load()))])
        self.changed = True
        return [cond_def, body_def, _unpack_assign(assigned, call)]

    def visit_For(self, node: ast.For):
        self.generic_visit(node)
        # only `for <name> in range(...)` without else
        if not (isinstance(node.iter, ast.Call)
                and isinstance(node.iter.func, ast.Name)
                and node.iter.func.id == "range"):
            return self._bail(
                node, "for",
                "it iterates a non-range iterable (tensor-dependent "
                "iteration needs `for i in range(...)`)")
        if (node.orelse
                or not isinstance(node.target, ast.Name)
                or node.iter.keywords
                or not 1 <= len(node.iter.args) <= 3
                or any(isinstance(a, ast.Starred)
                       for a in node.iter.args)):
            return self._bail(node, "for",
                              "its range/target form is not convertible")
        r = _bail_reason(node.body)
        if r:
            return self._bail(node, "for", r)
        assigned = sorted(_assigned_names(node.body) - {node.target.id})
        if not assigned:
            return self._bail(node, "for",
                              "no variable assignment flows out of it")
        ra = node.iter.args
        if len(ra) == 1:
            start, stop, step = ast.Constant(0), ra[0], ast.Constant(1)
        elif len(ra) == 2:
            start, stop, step = ra[0], ra[1], ast.Constant(1)
        else:
            start, stop, step = ra
        bname = self._next("forbody")
        body_def = _branch_funcdef(
            bname, [node.target.id] + assigned, node.body, assigned)
        # the loop target is itself an output: python leaves it bound to
        # the last iterated value after the loop, and user code reads it
        call = ast.Call(
            func=_jst_attr("convert_range_loop"),
            args=[start, stop, step, _name(bname),
                  ast.Tuple(elts=[_ld_expr(n) for n in assigned],
                            ctx=ast.Load())],
            keywords=[
                ast.keyword(
                    arg="names",
                    value=ast.Tuple(
                        elts=[ast.Constant(n) for n in assigned],
                        ctx=ast.Load())),
                ast.keyword(arg="target_init",
                            value=_ld_expr(node.target.id)),
            ])
        self.changed = True
        return [body_def,
                _unpack_assign([node.target.id] + assigned, call)]


# ---------------------------------------------------------------------------
# entry
# ---------------------------------------------------------------------------

class _LiveGlobals(dict):
    """exec/function globals that fall through to the original module's
    dict on miss — rebindings of module globals stay visible to the
    converted function.  (Closure cell VALUES are still snapshotted at
    conversion time: rebinding an enclosing local after decoration is not
    reflected — same as the reference's converted-function cache.)"""

    def __init__(self, base, extra):
        super().__init__(extra)
        self._base = base

    def __missing__(self, k):
        return self._base[k]


_CONVERTED_MARK = "__jst_converted__"


def convert_function(fn):
    """AST-convert python control flow in ``fn``; returns ``fn`` itself
    when nothing needs converting or the source is unavailable."""
    bound_self = None
    if inspect.ismethod(fn):
        bound_self = fn.__self__
        fn = fn.__func__
    if getattr(fn, _CONVERTED_MARK, False):
        return fn if bound_self is None else fn.__get__(bound_self)
    try:
        src_lines, first_line = inspect.getsourcelines(fn)
        src = textwrap.dedent("".join(src_lines))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return fn if bound_self is None else fn.__get__(bound_self)
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn if bound_self is None else fn.__get__(bound_self)
    # only the to_static decorator itself may be stripped; any other
    # decorator would be silently dropped by recompilation — bail
    for dec in fdef.decorator_list:
        if "to_static" not in ast.unparse(dec):
            setattr(fn, _CONVERTED_MARK, True)
            return fn if bound_self is None else fn.__get__(bound_self)
    fdef.decorator_list = []
    filename = fn.__code__.co_filename
    # map node linenos to FILE linenos before any transform, so bail
    # records, tracebacks, and linecache all point at the user's source
    # (reference origin_info.py)
    ast.increment_lineno(tree, first_line - 1)
    # generators/coroutines: `return` means StopIteration(value) and
    # yield/await cannot cross generated function boundaries — leave the
    # return machinery off (break/continue flags and call wrapping are
    # still semantics-preserving for them)
    is_gen = isinstance(fdef, ast.AsyncFunctionDef) or any(
        isinstance(n, (ast.Yield, ast.YieldFrom, ast.Await))
        for n in _walk_scope(fdef))
    ret_tr = _ReturnTransformer(uid=abs(hash(fn.__qualname__)) % 9973)
    if not is_gen:
        ret_tr.run(fdef)
    bc_tr = _BreakContinueTransformer()
    fdef.body = [x for stmt in fdef.body
                 for x in _as_list(bc_tr.visit(stmt))]
    log_tr = _LogicalTransformer()
    fdef.body = [x for stmt in fdef.body
                 for x in _as_list(log_tr.visit(stmt))]
    tr = _ControlFlowTransformer(filename=filename)
    fdef.body = [x for stmt in fdef.body
                 for x in _as_list(tr.visit(stmt))]
    # call-site wrapping lets helpers reached from here convert too
    # (reference convert_call); only worth the indirection when this
    # function itself converts, or when it might CALL converting code
    _CallSiteWrapper().visit(fdef)
    changed = (tr.changed or ret_tr.applied or bc_tr.changed
               or log_tr.changed)
    if not changed and not _has_user_calls(fdef):
        setattr(fn, _CONVERTED_MARK, True)
        return fn if bound_self is None else fn.__get__(bound_self)
    ast.fix_missing_locations(tree)
    from . import dy2static as _jst_mod

    # LIVE view of the module globals: a snapshot copy would silently pin
    # every later-rebound module global (config flags, the function's own
    # name for recursion) to its value at decoration time
    extras = {"_jst": _jst_mod}
    if fn.__closure__:
        for nm, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                extras[nm] = cell.cell_contents
            except ValueError:   # empty cell
                pass
    ns = _LiveGlobals(fn.__globals__, extras)
    code = compile(tree, filename=filename, mode="exec")
    exec(code, ns)
    new_fn = ns[fdef.name]
    functools.update_wrapper(new_fn, fn)
    setattr(new_fn, _CONVERTED_MARK, True)
    return new_fn if bound_self is None else new_fn.__get__(bound_self)


def _has_user_calls(fdef) -> bool:
    """Does the (wrapped) function contain any _jst.cvt call sites?"""
    for n in ast.walk(fdef):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Call) and \
                isinstance(n.func.func, ast.Attribute) and \
                n.func.func.attr == "cvt":
            return True
    return False


def _as_list(v):
    return v if isinstance(v, list) else [v]
