"""paddle.jit: to_static, save/load (reference: fluid/dygraph/jit.py:163,637).

``to_static`` compiles an imperative function (model forward, or a whole
train step including backward and optimizer.step) into one cached XLA
program per input-spec — the reference's StaticFunction + ConcreteProgram
cache (program_translator.py:239,772) with jax.jit as the executor.
"""
from __future__ import annotations

import functools
import hashlib
import os
import time
import traceback
from typing import Any, Callable, List, Optional

import numpy as np
import jax
from ..core.jax_compat import jax_export
import jax.numpy as jnp

from ..core.tensor import Tensor, to_tensor
from ..core import dtype as dtype_mod
from .trace import CompiledProgram, _flatten_io, spec_of

# tracer-leak errors: a Tensor whose value exists only inside the trace
# was forced to a concrete python value (bool/int/array) by unconverted
# control flow — mapped back to user source via dy2static.map_trace_error
_TRACER_LEAK_ERRORS = tuple(
    e for e in (getattr(jax.errors, n, None)
                for n in ("TracerBoolConversionError",
                          "TracerArrayConversionError",
                          "TracerIntegerConversionError",
                          "ConcretizationTypeError"))
    if e is not None)


# -- executable-cache miss subscription (ISSUE 13) --------------------------
# Every StaticFunction program-cache miss is one trace + one XLA compile.
# Listeners (obs.CompileLedger) subscribe here to turn each miss into a
# ledger record — cache key, wall seconds, arg specs, attributed call
# site — so steady-state misses become NAMED anomalies instead of a
# mystery latency spike.  With no listener attached the miss path pays
# one falsy check and nothing else.

_compile_listeners: List[Callable[[dict], None]] = []


def subscribe_compiles(listener: Callable[[dict], None]) -> None:
    """Register ``listener(record)`` for every program-cache miss
    (see :class:`paddle_tpu.obs.compile_ledger.CompileLedger` — the
    canonical consumer).  Idempotent per listener object."""
    if listener not in _compile_listeners:
        _compile_listeners.append(listener)


def unsubscribe_compiles(listener: Callable[[dict], None]) -> None:
    try:
        _compile_listeners.remove(listener)
    except ValueError:
        pass


def _compile_call_site() -> str:
    """The innermost stack frame OUTSIDE the framework — who asked for
    this compile.  Only runs on a miss (compiles are seconds; a stack
    walk is microseconds)."""
    here = os.sep + "paddle_tpu" + os.sep
    for fr in reversed(traceback.extract_stack()):
        fn = fr.filename
        if here in fn or (os.sep + "jax" + os.sep) in fn:
            continue
        return f"{fn}:{fr.lineno}"
    return "<framework>"


def _arg_specs_str(leaves: List[Tensor]) -> str:
    return ",".join(f"{t.dtype}[{','.join(str(s) for s in t.shape)}]"
                    for t in leaves)


def _notify_compile(static_fn, key, leaves, seconds: float,
                    executed: bool) -> None:
    prog = static_fn._programs.get(key)
    rec = {
        "fn": getattr(static_fn._fn, "__qualname__",
                      getattr(static_fn._fn, "__name__", "<fn>")),
        "key": hashlib.sha1(repr(key).encode()).hexdigest()[:12],
        "arg_specs": _arg_specs_str(leaves),
        "seconds": round(seconds, 6),
        "site": _compile_call_site(),
        "cache_size": len(static_fn._programs),
        "state_inputs": len(prog.state_keys) if prog is not None else 0,
        # False = trace-only (get_concrete_program: eval_shape discovery,
        # no XLA executable built yet — jax.jit compiles lazily at the
        # first real call)
        "executed": executed,
    }
    for cb in list(_compile_listeners):
        try:
            cb(rec)
        except Exception as e:  # noqa: BLE001 — observers must never
            # break the compile path (or, from the notify-in-finally,
            # mask the first call's REAL exception — e.g. the
            # RESOURCE_EXHAUSTED the bench's OOM-halving matches on)
            import sys
            import traceback as _tb

            print(f"paddle_tpu.jit: compile listener {cb!r} raised "
                  f"{type(e).__name__}: {e} (ignored)", file=sys.stderr)
            _tb.print_exc(file=sys.stderr)


def _build_mapped(prog, leaves):
    """prog.build with tracer-leak errors mapped back to user source."""
    try:
        prog.build(leaves)
    except _TRACER_LEAK_ERRORS as e:
        from .dy2static import map_trace_error

        mapped = map_trace_error(e)
        if mapped is not None:
            raise mapped from e
        raise


class InputSpec:
    """Declarative input signature (reference: paddle.static.InputSpec)."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape)
        self.dtype = dtype_mod.convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"

    def _to_zero_tensor(self) -> Tensor:
        shape = [1 if (s is None or s < 0) else s for s in self.shape]
        return Tensor._wrap(jnp.zeros(shape, dtype=self.dtype),
                            stop_gradient=self.stop_gradient)


class StaticFunction:
    """Callable wrapper caching CompiledPrograms per input spec
    (reference: dygraph_to_static/program_translator.py:239)."""

    def __init__(self, fn, input_spec=None, build_strategy=None,
                 backend=None, donate=True):
        import os

        if not os.environ.get("PADDLE_TPU_NO_AST_CONVERT"):
            # reference program_translator.py:239 — rewrite python
            # if/while/for over tensors into cond/while_loop calls (no-op
            # on functions without convertible control flow)
            from .dy2static import convert_function

            fn = convert_function(fn)
        self._fn = fn
        self._input_spec = input_spec
        self._programs: dict = {}
        self._enabled = True
        self._donate = donate
        functools.update_wrapper(self, fn)

    @property
    def program_cache(self):
        return self._programs

    def last_program(self):
        """The most recently built CompiledProgram (for
        compiled_stats introspection)."""
        if not self._programs:
            raise RuntimeError("no program compiled yet — call the "
                               "function once first")
        return next(reversed(self._programs.values()))

    def _extra_key(self, args):
        """Mode bits that change the traced python path."""
        from ..core.autograd import is_grad_enabled
        from ..nn.layer_base import Layer

        bits = [is_grad_enabled()]
        owner = getattr(self._fn, "__self__", None)
        scan = []
        if isinstance(owner, Layer):
            scan.append(owner)
        for a in args:
            if isinstance(a, Layer):
                scan.append(a)
        for l in scan:
            bits.append(tuple(s.training for s in l.sublayers(include_self=True)))
        return tuple(bits)

    def __call__(self, *args, **kwargs):
        if not self._enabled or not ProgramTranslator.enable_to_static:
            return self._fn(*args, **kwargs)
        leaves: List[Tensor] = []
        args_tree = _flatten_io(list(args), leaves)
        n_args_leaves = len(leaves)
        kwargs_tree = _flatten_io(kwargs, leaves)
        key = (spec_of(args_tree, leaves), spec_of(kwargs_tree, leaves),
               self._extra_key(args))
        prog = self._programs.get(key)
        if prog is None:
            prog = CompiledProgram(self._fn, args_tree, kwargs_tree,
                                   donate=self._donate)
            # time trace + build + the FIRST call (jax.jit compiles
            # lazily, so the first execution pays the XLA compile —
            # that wall time is the ledger's whole point); one miss
            # path whether or not a listener is attached.  Notify in
            # finally: a first call that raises still CACHED the
            # program, and the retry will be a silent hit — skipping
            # the record would undercount that key's compile forever
            t0 = time.perf_counter()
            _build_mapped(prog, leaves)
            self._programs[key] = prog
            try:
                out = prog(leaves)
            finally:
                if _compile_listeners:
                    _notify_compile(self, key, leaves,
                                    time.perf_counter() - t0,
                                    executed=True)
            return out
        return prog(leaves)

    def concrete_program_specify_input_spec(self, input_spec=None):
        spec = input_spec or self._input_spec
        if spec is None:
            raise ValueError("input_spec required")
        tensors = [s._to_zero_tensor() if isinstance(s, InputSpec) else s
                   for s in spec]
        return self.get_concrete_program(*tensors)

    def get_concrete_program(self, *args, **kwargs):
        leaves: List[Tensor] = []
        args_tree = _flatten_io(list(args), leaves)
        kwargs_tree = _flatten_io(kwargs, leaves)
        key = (spec_of(args_tree, leaves), spec_of(kwargs_tree, leaves),
               self._extra_key(args))
        prog = self._programs.get(key)
        if prog is None:
            prog = CompiledProgram(self._fn, args_tree, kwargs_tree,
                                   donate=self._donate)
            t0 = time.perf_counter()
            _build_mapped(prog, leaves)
            self._programs[key] = prog
            if _compile_listeners:
                _notify_compile(self, key, leaves,
                                time.perf_counter() - t0, executed=False)
        return prog

    def rollback(self):
        self._enabled = False
        return self._fn


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, donate=True, **kwargs):
    """Decorator: compile a dygraph function to one XLA program
    (reference: @paddle.jit.to_static, fluid/dygraph/jit.py:163).

    donate=False disables buffer donation of rewritten state (params,
    optimizer moments): use it when eager code holds aliases of state
    arrays across compiled calls (e.g. an eager GradScaler.step snapshot
    around a compiled optimizer step) — donation would invalidate them.
    Costs a second in-flight copy of every donated buffer."""

    def _decorate(fn):
        from ..nn.layer_base import Layer

        if isinstance(fn, Layer):
            layer = fn
            static_fwd = StaticFunction(layer.forward, input_spec,
                                        donate=donate)
            layer.forward = static_fwd
            return layer
        return StaticFunction(fn, input_spec, donate=donate)

    if function is not None:
        return _decorate(function)
    return _decorate


declarative = to_static


def not_to_static(fn):
    fn._not_to_static = True
    return fn


# ---------------------------------------------------------------------------
# save / load (reference: jit.save fluid/dygraph/jit.py:637, TranslatedLayer
# fluid/dygraph/io.py:1137).  Deployment format: jax.export serialized
# StableHLO bytes + a params .pdparams — portable across processes and
# loadable without the original python model code.
# ---------------------------------------------------------------------------

def save(layer, path, input_spec=None, **configs):
    from ..nn.layer_base import Layer
    from ..framework.io import save as _fsave

    if isinstance(layer, Layer):
        fwd = layer.forward
        net = layer
    else:
        fwd = layer
        net = getattr(layer, "__self__", None)

    if input_spec is None and isinstance(fwd, StaticFunction):
        input_spec = fwd._input_spec
    if input_spec is None:
        raise ValueError("jit.save requires input_spec")

    in_tensors = [s._to_zero_tensor() if isinstance(s, InputSpec) else s
                  for s in input_spec]
    params = dict(net.named_parameters()) if net is not None else {}
    buffers = dict(net.named_buffers()) if net is not None else {}
    state = {**params, **buffers}
    names = sorted(state.keys())

    was_training = net.training if net is not None else False
    if net is not None:
        net.eval()

    raw_fn = fwd._fn if isinstance(fwd, StaticFunction) else fwd
    # AST-convert python control flow exactly like @to_static does —
    # exporting the raw forward would TracerBool on the first
    # tensor-dependent `if` that conversion handles.  Honors the same
    # kill-switch as StaticFunction.
    import os as _os

    if not _os.environ.get("PADDLE_TPU_NO_AST_CONVERT"):
        from .dy2static import convert_function

        raw_fn = convert_function(raw_fn)

    def pure(state_arrays, in_arrays):
        originals = [state[n]._data for n in names]
        for n, arr in zip(names, state_arrays):
            state[n]._data = arr
        try:
            outs = raw_fn(*[Tensor._wrap(a) for a in in_arrays])
            if isinstance(outs, (list, tuple)):
                return [o._value() for o in outs]
            return outs._value()
        finally:
            for n, orig in zip(names, originals):
                state[n]._data = orig

    state_arrays = [state[n]._value() for n in names]
    in_arrays = [t._value() for t in in_tensors]
    # None/-1 InputSpec dims export as SYMBOLIC dimensions (shared scope):
    # the served model accepts any size there (reference
    # save_inference_model's -1 dims; jax shape polymorphism)
    scope = jax_export.SymbolicScope()
    sym_iter = iter(f"_d{i}" for i in range(64))
    in_avals = []
    for spec_i, arr in zip(list(input_spec) + [None] * len(in_arrays),
                           in_arrays):
        declared = list(getattr(spec_i, "shape", arr.shape))
        if any(d is None or (isinstance(d, int) and d < 0)
               for d in declared):
            dims = ",".join(
                next(sym_iter) if (d is None or int(d) < 0) else str(int(d))
                for d in declared)
            shp = jax_export.symbolic_shape(dims, scope=scope)
            in_avals.append(jax.ShapeDtypeStruct(shp, arr.dtype))
        else:
            in_avals.append(jax.ShapeDtypeStruct(arr.shape, arr.dtype))
    exported = jax_export.export(jax.jit(pure))(
        jax.tree.map(lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                     state_arrays),
        in_avals,
    )
    blob = exported.serialize()
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path + ".pdmodel", "wb") as f:
        f.write(blob)
    _fsave({n: state[n] for n in names}, path + ".pdiparams")
    # signature sidecar: real input names (InputSpec.name, else xN) so the
    # serving surface (inference.Predictor) can expose named handles
    # instead of synthesizing them; old artifacts without it still load
    import json as _json

    in_names = [(getattr(s, "name", None) or f"x{i}")
                for i, s in enumerate(input_spec)]
    meta = {
        "format": 1,
        "input_names": in_names,
        "inputs": [
            {"name": name,
             "shape": [None if (d_ is None or (isinstance(d_, int) and d_ < 0))
                       else int(d_)
                       for d_ in getattr(s, "shape", list(arr.shape))],
             "dtype": str(np.dtype(arr.dtype))}
            for name, s, arr in zip(in_names, input_spec, in_arrays)],
    }
    with open(path + ".pdmeta.json", "w") as f:
        _json.dump(meta, f, indent=1)
    if net is not None and was_training:
        net.train()


class TranslatedLayer:
    """Inference-callable loaded from a jit.save artifact (reference:
    fluid/dygraph/io.py:1137)."""

    def __init__(self, exported, state):
        self._exported = exported
        self._names = sorted(state.keys())
        self._state = state

    def __call__(self, *inputs):
        in_arrays = [t._value() if isinstance(t, Tensor) else jnp.asarray(t)
                     for t in inputs]
        state_arrays = [self._state[n]._value() for n in self._names]
        out = self._exported.call(state_arrays, in_arrays)
        if isinstance(out, (list, tuple)):
            outs = [Tensor._wrap(o) for o in out]
            return outs[0] if len(outs) == 1 else outs
        return Tensor._wrap(out)

    forward = __call__

    def eval(self):
        return self

    def train(self):
        raise RuntimeError("TranslatedLayer is inference-only")

    def state_dict(self):
        return dict(self._state)


def load(path, **configs):
    from ..framework.io import load as _fload

    with open(path + ".pdmodel", "rb") as f:
        blob = f.read()
    exported = jax_export.deserialize(blob)
    state = _fload(path + ".pdiparams")
    return TranslatedLayer(exported, state)


# -- reference-parity shims -------------------------------------------------

class ProgramTranslator:
    """Reference dygraph_to_static ProgramTranslator (singleton toggling
    to_static globally). Here to_static is trace-based; the toggle makes
    decorated functions run eagerly when disabled."""

    _instance = None
    enable_to_static = True

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, enable_to_static: bool):
        type(self).enable_to_static = bool(enable_to_static)


def enable_to_static(enable: bool = True):
    """paddle.jit.enable_to_static parity."""
    ProgramTranslator.get_instance().enable(enable)


def set_code_level(level=100, also_to_stdout=False):
    """Reference dy2static debug knob: prints transformed code. The
    trace-based to_static has no AST transforms; accepted as a no-op."""


def set_verbosity(level=0, also_to_stdout=False):
    """Reference dy2static logging verbosity; accepted as a no-op (use
    standard logging on paddle_tpu.jit instead)."""


class TracedLayer:
    """Reference fluid dygraph TracedLayer (trace + save for inference).
    The modern path is jit.to_static + jit.save; `trace` compiles a
    wrapper around the layer (the layer itself is left untouched — its
    direct calls stay eager, like the reference) and returns
    (original_outputs, traced)."""

    def __init__(self, layer, inputs):
        self._layer = layer
        # compile a wrapper fn, NOT the layer: to_static(layer) would
        # replace the layer's own call path in place
        self._static = to_static(lambda *a, **k: layer(*a, **k))
        self._inputs = list(inputs)

    @staticmethod
    def trace(layer, inputs):
        outs = layer(*inputs)          # eager originals, pre-compile
        traced = TracedLayer(layer, inputs)
        return outs, traced

    def __call__(self, *args, **kwargs):
        return self._static(*args, **kwargs)

    def save_inference_model(self, path, feed=None, fetch=None, **kwargs):
        save(self._static, path, input_spec=self._inputs)
