"""Trace-based to_static: one imperative step → one XLA program.

Reference parity: dy2static (``StaticFunction``, program_translator.py:239;
``run_program`` op, run_program_op.cc:221) and the whole static-graph executor
stack (SURVEY.md §2.3) — which, TPU-native, collapse into ``jax.jit``
(SURVEY.md §7).  What remains ours is the *state lifting* machinery:

- The function under trace reads/writes framework Tensors that live outside
  it (parameters, optimizer accumulators, RNG state, BN running stats,
  ``.grad`` buffers).  A ``TraceHook`` installed on the Tensor payload
  accessors lifts every such external tensor into a program input, and turns
  every in-place write into a program output written back after the compiled
  call — the reference does the same by scoping ProgramDesc variables
  (run_program's scope handling).
- Discovery runs under ``jax.eval_shape`` (abstract, no FLOPs) iterated to a
  fixed point, then the program is compiled once per input-spec.
- Grad accumulation reads lift a zeros-initialized input, so cross-call grad
  accumulation and fresh-grad flows share one program structure.
- A traced function that performs an *internal* backward (train-step style)
  compiles to a single fwd+bwd+update program.  A pure-forward trace stays
  differentiable from outside: the compiled callable is dispatched through
  the autograd tape like any other op (reference: run_program grad node).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..core import tensor as tensor_mod
from ..core.tensor import Tensor
from ..core.autograd import is_grad_enabled
from ..core.dispatch import apply_op
from ..core.flags import get_flag


def _is_tracer(x):
    return isinstance(x, jax.core.Tracer)


class _StateKey:
    """Identity of a lifted (tensor, kind) slot; kind: 'data' | 'grad'."""

    __slots__ = ("tensor", "kind", "_zero_cache")

    def __init__(self, tensor, kind):
        self.tensor = tensor
        self.kind = kind
        self._zero_cache = None

    def current(self):
        """Concrete array to feed this slot right now (zeros for absent grad).

        The zeros buffer is cached: after ``clear_grad`` every parameter's
        grad slot is absent, and materializing ~2 eager arrays per parameter
        per step (zeros + dtype cast) was ~30% of the 345M step's wall time
        on host.  Grad-kind inputs are never donated, so reuse is safe."""
        if self.kind == "data":
            return self.tensor._data
        g = self.tensor._grad
        if g is None:
            z = self._zero_cache
            d = self.tensor._data
            if z is None or z.shape != d.shape or z.dtype != d.dtype \
                    or getattr(z, "is_deleted", lambda: False)():
                z = jnp.zeros(d.shape, d.dtype)
                self._zero_cache = z
            return z
        return g

    def apply(self, arr):
        if self.kind == "data":
            self.tensor._data = arr
            self.tensor._version += 1
        else:
            self.tensor._grad = arr

    def __hash__(self):
        return hash((id(self.tensor), self.kind))

    def __eq__(self, other):
        return self.tensor is other.tensor and self.kind == other.kind

    def __repr__(self):
        return f"<{self.kind}:{self.tensor.name or id(self.tensor)}>"


class TraceHook:
    """Installed as tensor_mod._trace_hook while a capture is active."""

    def __init__(self, known: Dict[_StateKey, Any]):
        self.env: Dict[_StateKey, Any] = dict(known)
        self.new_found: List[_StateKey] = []
        self.writes: Dict[_StateKey, Any] = {}
        self.grad_none: set = set()  # grads structurally absent this trace
        self.performed_backward = False  # any non-None grad write seen

    # Trace-local bookkeeping lives ON the tensor (owner-tagged slots), not
    # in id()-keyed sets: a GC'd trace-local tensor's id can be reused by a
    # brand-new external tensor, which an id set would misclassify as local.
    def mark_created(self, t):
        t._trace_born = self

    def unmark_created(self, t):
        t._trace_born = None

    def _is_local(self, t) -> bool:
        return t._trace_born is self or _is_tracer(t._data)

    def _local_grad(self, t):
        lg = t._trace_grad
        if lg is not None and lg[0] is self:
            return lg[1]
        return t._grad

    def _set_local_grad(self, t, arr):
        t._trace_grad = (self, arr)

    def read(self, t: Tensor):
        if self._is_local(t):
            return t._data
        key = _StateKey(t, "data")
        if key in self.writes:
            return self.writes[key]
        if key in self.env:
            return self.env[key]
        # unknown external: record for the next discovery round; use the
        # concrete value (a constant now — becomes an input on retrace)
        self.new_found.append(key)
        self.env[key] = t._data
        return t._data

    def write(self, t: Tensor, arr):
        if self._is_local(t):
            t._data = arr  # trace-local mutation
            return
        key = _StateKey(t, "data")
        if key not in self.env and key not in self.writes:
            self.new_found.append(key)  # written external never read
        self.writes[key] = arr

    def _grad_key_lookup(self, key):
        if key in self.writes:
            return self.writes[key], True
        if key in self.env:
            return self.env[key], True
        return None, False

    def read_grad(self, t: Tensor):
        """Structural read (Tensor.grad property): absent grad stays None."""
        if self._is_local(t):
            return self._local_grad(t)
        key = _StateKey(t, "grad")
        v, hit = self._grad_key_lookup(key)
        if hit:
            return v
        if key in self.grad_none:
            return None
        g = t._grad
        if g is None:
            self.grad_none.add(key)
            return None
        self.new_found.append(key)
        self.env[key] = g
        return g

    def read_grad_accum(self, t: Tensor):
        """Accumulation read: lift a zeros-backed input so fresh-grad and
        accumulate-grad calls share one program structure."""
        if self._is_local(t):
            return self._local_grad(t)
        key = _StateKey(t, "grad")
        v, hit = self._grad_key_lookup(key)
        if hit:
            return v
        self.new_found.append(key)
        g = t._grad
        init = g if g is not None else jnp.zeros(
            t._data.shape, t._data.dtype)
        self.env[key] = init
        return init

    def write_grad(self, t: Tensor, arr):
        if arr is not None:
            self.performed_backward = True
        if self._is_local(t):
            self._set_local_grad(t, arr)
            return
        key = _StateKey(t, "grad")
        if arr is None:
            self.grad_none.discard(key)
            self.writes[key] = None
            return
        self.grad_none.discard(key)
        if key not in self.env and key not in self.writes:
            self.new_found.append(key)
        self.writes[key] = arr


# -- pytree helpers over framework Tensors ----------------------------------

def _flatten_io(obj, leaves: List):
    if isinstance(obj, Tensor):
        leaves.append(obj)
        return ("T", len(leaves) - 1)
    if isinstance(obj, (list, tuple)):
        return ("tuple" if isinstance(obj, tuple) else "list",
                [_flatten_io(o, leaves) for o in obj])
    if isinstance(obj, dict):
        return ("dict", {k: _flatten_io(v, leaves) for k, v in obj.items()})
    return ("C", obj)


def _unflatten_io(tree, leaves: List):
    tag = tree[0]
    if tag == "T":
        return leaves[tree[1]]
    if tag == "C":
        return tree[1]
    if tag == "dict":
        return {k: _unflatten_io(v, leaves) for k, v in tree[1].items()}
    seq = [_unflatten_io(t, leaves) for t in tree[1]]
    return tuple(seq) if tag == "tuple" else seq


def _count_tensor_leaves(tree) -> int:
    tag = tree[0]
    if tag == "T":
        return 1
    if tag == "C":
        return 0
    if tag == "dict":
        return sum(_count_tensor_leaves(v) for v in tree[1].values())
    return sum(_count_tensor_leaves(t) for t in tree[1])


def spec_of(tree, leaves) -> tuple:
    """Hashable cache key for an arg pytree (reference: function_spec.py)."""

    def _spec(tree):
        tag = tree[0]
        if tag == "T":
            t = leaves[tree[1]]
            return ("T", tuple(t.shape), str(t.dtype), t.stop_gradient)
        if tag == "C":
            v = tree[1]
            try:
                hash(v)
                return ("C", v)
            except TypeError:
                return ("C", repr(v))
        if tag == "dict":
            return ("dict",
                    tuple(sorted((k, _spec(v)) for k, v in tree[1].items())))
        return (tag, tuple(_spec(t) for t in tree[1]))

    return _spec(tree)


class CompiledProgram:
    """One (input-spec → XLA executable) entry (reference: ConcreteProgram +
    cached InterpreterCore, executor_cache.cc)."""

    def __init__(self, fn, args_tree, kwargs_tree, donate=True):
        self.fn = fn
        self.args_tree = args_tree
        self.kwargs_tree = kwargs_tree
        self.donate = donate
        self.state_keys: List[_StateKey] = []
        self.write_keys: List[_StateKey] = []
        self.write_none_mask: List[bool] = []
        self.out_tree = None
        self.jitted = None
        self.has_internal_backward = False
        self._arg_sg: List[bool] = []

    def _run_traced(self, arg_arrays, state_arrays):
        """Trace body: returns (hook, out_tree, out_arrays)."""
        known = {k: a for k, a in zip(self.state_keys, state_arrays)}
        hook = TraceHook(known)
        arg_tensors = [
            Tensor._wrap(a, stop_gradient=sg)
            for a, sg in zip(arg_arrays, self._arg_sg)
        ]
        args = _unflatten_io(self.args_tree, arg_tensors)
        kwargs = _unflatten_io(self.kwargs_tree, arg_tensors)
        prev = tensor_mod._trace_hook
        tensor_mod._trace_hook = hook
        try:
            out = self.fn(*args, **kwargs)
            out_leaves: List[Tensor] = []
            out_tree = _flatten_io(out, out_leaves)
            out_arrays = [t._value() for t in out_leaves]
        finally:
            tensor_mod._trace_hook = prev
        return hook, out_tree, out_arrays

    def build(self, arg_tensors):
        self._arg_sg = [t.stop_gradient for t in arg_tensors]
        arg_arrays = [t._value() for t in arg_tensors]
        for _ in range(8):
            state_arrays = [k.current() for k in self.state_keys]
            box = {}

            def _probe(aa, sa):
                hook, out_tree, out_arrays = self._run_traced(aa, sa)
                box["hook"], box["out_tree"] = hook, out_tree
                return out_arrays

            jax.eval_shape(_probe, arg_arrays, state_arrays)
            hook = box["hook"]
            if not hook.new_found:
                self.out_tree = box["out_tree"]
                self.write_keys = list(hook.writes.keys())
                self.write_none_mask = [
                    hook.writes[k] is None for k in self.write_keys]
                self.has_internal_backward = hook.performed_backward
                break
            for k in hook.new_found:
                if k not in self.state_keys:
                    self.state_keys.append(k)
        else:
            raise RuntimeError("to_static: state discovery did not converge")

        # Buffer donation: data-kind state leaves that are rewritten every
        # call (params, optimizer moments, RNG state) alias their outputs,
        # so the executable updates them in place — without this, a train
        # step holds two copies of every parameter and moment (the
        # reference gets the same effect from inplace ops + buffer-share
        # passes).  Grad-kind leaves are NOT donated: `p.grad` hands out
        # aliases of the raw buffer and a later donated call would
        # invalidate them.  Caveat (shared with torch inplace optimizers):
        # a _value()/state_dict alias of a *parameter* captured before a
        # compiled train step is invalidated by that step's donation.
        replaced = {
            k for k, none in zip(self.write_keys, self.write_none_mask)
            if not none and k.kind == "data"}
        self._don_idx = [i for i, k in enumerate(self.state_keys)
                         if k in replaced]
        self._keep_idx = [i for i, k in enumerate(self.state_keys)
                          if k not in replaced]

        def program(aa, sd, sk):
            sa = [None] * len(self.state_keys)
            for j, i in enumerate(self._don_idx):
                sa[i] = sd[j]
            for j, i in enumerate(self._keep_idx):
                sa[i] = sk[j]
            hook, _, out_arrays = self._run_traced(aa, sa)
            write_arrays = []
            for k, none_at_build in zip(self.write_keys, self.write_none_mask):
                w = hook.writes.get(k)
                if w is None:
                    # None write (grad cleared) or unchanged: dummy scalar
                    write_arrays.append(jnp.zeros((), jnp.float32))
                else:
                    write_arrays.append(w)
            return tuple(out_arrays), tuple(write_arrays)

        # donating variant for the state-mutating fast path; non-donating
        # for the differentiable path (vjp residuals may alias state bufs)
        self.jitted = jax.jit(program)
        self.jitted_donate = jax.jit(program, donate_argnums=(1,))
        return self

    def _split_state(self, state_arrays):
        sd = [state_arrays[i] for i in self._don_idx]
        sk = [state_arrays[i] for i in self._keep_idx]
        return sd, sk

    def compiled_stats(self):
        """Compile-time introspection of the current program signature:
        XLA memory analysis + optimized HLO text (shares jax's executable
        cache with normal calls — cheap after the first run).  Powers the
        multichip gate's per-config stats (collective bytes, peak HBM)."""
        state_arrays = [k.current() for k in self.state_keys]
        sd, sk = self._split_state(state_arrays)
        run = self.jitted_donate if self.donate else self.jitted
        lowered = run.lower(self._last_arg_arrays, sd, sk)
        compiled = lowered.compile()
        out = {"hlo": compiled.as_text()}
        try:
            ma = compiled.memory_analysis()
            out["argument_bytes"] = int(ma.argument_size_in_bytes)
            out["output_bytes"] = int(ma.output_size_in_bytes)
            out["temp_bytes"] = int(ma.temp_size_in_bytes)
            out["alias_bytes"] = int(ma.alias_size_in_bytes)
            out["peak_bytes"] = int(ma.argument_size_in_bytes
                                    + ma.output_size_in_bytes
                                    + ma.temp_size_in_bytes
                                    - ma.alias_size_in_bytes)
        except Exception:
            pass
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            out["cost"] = {
                k.replace(" ", "_"): float(ca[k])
                for k in ("flops", "bytes accessed", "transcendentals")
                if k in ca}
        except Exception:
            pass
        return out

    def _writeback(self, write_arrays):
        for k, none_at_build, arr in zip(
                self.write_keys, self.write_none_mask, write_arrays):
            if none_at_build:
                k.apply(None) if k.kind == "grad" else None
            else:
                k.apply(arr)

    def __call__(self, arg_tensors):
        arg_arrays = [t._value() for t in arg_tensors]
        self._last_arg_arrays = arg_arrays
        state_arrays = [k.current() for k in self.state_keys]

        outer_diff = (
            not self.has_internal_backward
            and is_grad_enabled()
            and (any(not t.stop_gradient for t in arg_tensors)
                 or any(k.kind == "data" and not k.tensor.stop_gradient
                        for k in self.state_keys))
        )
        if not outer_diff:
            sd, sk = self._split_state(state_arrays)
            run = self.jitted_donate if self.donate else self.jitted
            out_arrays, write_arrays = run(arg_arrays, sd, sk)
            if get_flag("check_nan_inf"):
                from ..core import error_guard

                error_guard.raise_on_error()
            self._writeback(write_arrays)
            out_leaves = [Tensor._wrap(a) for a in out_arrays]
            return _unflatten_io(self.out_tree, out_leaves)

        # pure-forward program: dispatch through the tape so outer backward
        # flows into args and lifted parameters (reference: run_program grad)
        n_out = _count_tensor_leaves(self.out_tree)
        n_args = len(arg_tensors)
        state_wrappers = []
        for k, a in zip(self.state_keys, state_arrays):
            if k.kind == "data":
                state_wrappers.append(k.tensor)
            else:
                state_wrappers.append(Tensor._wrap(a, stop_gradient=True))

        def primal(*arrays):
            aa = list(arrays[:n_args])
            sa = list(arrays[n_args:])
            sd, sk = self._split_state(sa)
            out_arrays, write_arrays = self.jitted(aa, sd, sk)
            flat = tuple(out_arrays) + tuple(write_arrays)
            return flat[0] if len(flat) == 1 else flat

        res = apply_op("run_program", primal,
                       list(arg_tensors) + state_wrappers,
                       n_outs=n_out + len(self.write_keys))
        if get_flag("check_nan_inf"):
            from ..core import error_guard

            error_guard.raise_on_error()
        if not isinstance(res, tuple):
            res = (res,)
        out_leaves = list(res[:n_out])
        writes = [w._value() for w in res[n_out:]]
        self._writeback(writes)
        return _unflatten_io(self.out_tree, out_leaves)
