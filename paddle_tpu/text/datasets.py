"""paddle.text datasets (reference: python/paddle/text/datasets/*.py).

Same Dataset API and file formats as the reference; this environment has no
network egress, so ``download=True`` with no local file raises with
instructions instead of fetching — pass ``data_file`` pointing at a local
copy (the reference supports the same override).
"""
from __future__ import annotations

import gzip
import os
import re
import tarfile
from typing import List, Optional

import numpy as np

from ..io.dataset import Dataset

__all__ = ["UCIHousing", "Imdb", "Imikolov"]


def _require(data_file: Optional[str], name: str, url_hint: str) -> str:
    if data_file and os.path.exists(data_file):
        return data_file
    raise RuntimeError(
        f"{name}: no local data_file and downloads are unavailable in this "
        f"environment. Fetch {url_hint} manually and pass data_file=...")


class UCIHousing(Dataset):
    """Boston housing regression (reference: uci_housing.py — 13 features,
    80/20 train/test split, feature-wise max-min normalization)."""

    FEATURE_NUM = 14

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 download: bool = True):
        assert mode in ("train", "test")
        path = _require(data_file, "UCIHousing",
                        "https://archive.ics.uci.edu/ml/machine-learning-"
                        "databases/housing/housing.data")
        raw = np.loadtxt(path).astype(np.float32)
        raw = raw.reshape(-1, self.FEATURE_NUM)
        maxi, mini = raw.max(axis=0), raw.min(axis=0)
        avg = raw.mean(axis=0)
        span = np.where(maxi - mini == 0, 1.0, maxi - mini)
        feats = (raw - avg) / span
        raw = np.concatenate(
            [feats[:, :-1], raw[:, -1:]], axis=1)
        split = int(len(raw) * 0.8)
        self.data = raw[:split] if mode == "train" else raw[split:]

    def __len__(self):
        return len(self.data)

    def __getitem__(self, i):
        row = self.data[i]
        return row[:-1].astype(np.float32), row[-1:].astype(np.float32)


class Imdb(Dataset):
    """IMDB sentiment (reference: imdb.py — aclImdb tgz, word-frequency
    vocabulary with a cutoff of 150, <unk> id = len(vocab))."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 cutoff: int = 150, download: bool = True):
        assert mode in ("train", "test")
        path = _require(data_file, "Imdb",
                        "https://dataset.bj.bcebos.com/imdb%2FaclImdb_v1.tar.gz")
        # one decompression pass collects the vocab counts (train split)
        # and this mode's tokenized documents together — the tarball is
        # ~50k files and re-scanning it per purpose triples load time
        from collections import Counter

        pos_pat = re.compile(rf"aclImdb/{mode}/pos/.*\.txt$")
        neg_pat = re.compile(rf"aclImdb/{mode}/neg/.*\.txt$")
        train_pat = re.compile(r"aclImdb/train/(pos|neg)/.*\.txt$")
        freq: Counter = Counter()
        pos_docs: List[List[str]] = []
        neg_docs: List[List[str]] = []
        with tarfile.open(path) as tf:
            for m in tf.getmembers():
                name = m.name or ""
                is_pos = bool(pos_pat.match(name))
                is_neg = bool(neg_pat.match(name))
                is_train = bool(train_pat.match(name))
                if not (is_pos or is_neg or is_train):
                    continue
                words = tf.extractfile(m).read().decode("latin-1") \
                    .lower().replace("<br />", " ").split()
                if is_train:
                    freq.update(words)
                if is_pos:
                    pos_docs.append(words)
                elif is_neg:
                    neg_docs.append(words)
        freq.pop("<unk>", None)
        vocab = [w for w, c in freq.items() if c > cutoff]
        vocab.sort(key=lambda w: (-freq[w], w))
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        unk = len(self.word_idx)
        self.docs: List[np.ndarray] = []
        self.labels: List[int] = []
        for docs, label in ((pos_docs, 0), (neg_docs, 1)):
            for d in docs:
                self.docs.append(np.array(
                    [self.word_idx.get(w, unk) for w in d], dtype=np.int64))
                self.labels.append(label)

    def __len__(self):
        return len(self.docs)

    def __getitem__(self, i):
        # reference imdb.py:142 — label as a shape-(1,) array
        return self.docs[i], np.array([self.labels[i]], dtype=np.int64)


class Imikolov(Dataset):
    """PTB n-gram LM dataset (reference: imikolov.py — train/valid from the
    simple-examples tgz; n-gram or sequence data_type)."""

    def __init__(self, data_file: Optional[str] = None, data_type="NGRAM",
                 window_size: int = -1, mode: str = "train",
                 min_word_freq: int = 50, download: bool = True):
        assert mode in ("train", "test")
        assert data_type in ("NGRAM", "SEQ")
        path = _require(data_file, "Imikolov",
                        "https://dataset.bj.bcebos.com/imikolov%2F"
                        "simple-examples.tar.gz")
        self.window_size = window_size
        self.data_type = data_type
        # reference imikolov.py:143 — mode names the file directly
        # (ptb.test.txt for test; ptb.valid.txt only feeds the vocab)
        fname = f"./simple-examples/data/ptb.{mode}.txt"
        self.word_idx = self._build_vocab(path, min_word_freq)
        self.data = []
        with tarfile.open(path) as tf:
            f = tf.extractfile(fname)
            lines = f.read().decode("utf-8").splitlines()
        unk = self.word_idx["<unk>"]
        for ln in lines:
            words = ln.strip().split()
            ids = [self.word_idx["<s>"]] + \
                [self.word_idx.get(w, unk) for w in words] + \
                [self.word_idx["<e>"]]
            if data_type == "NGRAM":
                if window_size <= 0:
                    raise ValueError("NGRAM needs window_size > 0")
                # reference imikolov.py:153 — window_size ids per item
                for i in range(window_size, len(ids) + 1):
                    self.data.append(
                        np.array(ids[i - window_size:i], dtype=np.int64))
            else:
                src, tgt = ids[:-1], ids[1:]
                # reference imikolov.py:160 — drop over-long sequences
                if window_size > 0 and len(src) > window_size:
                    continue
                self.data.append((np.array(src, dtype=np.int64),
                                  np.array(tgt, dtype=np.int64)))

    def _build_vocab(self, path, min_word_freq):
        """Reference _build_work_dict: counts over train+valid with one
        <s>/<e> per line (so the markers get frequency-ranked ids), strict
        cutoff, <unk> appended last."""
        from collections import Counter

        freq = Counter()
        with tarfile.open(path) as tf:
            for split in ("train", "valid"):
                f = tf.extractfile(f"./simple-examples/data/ptb.{split}.txt")
                for ln in f.read().decode("utf-8").splitlines():
                    freq.update(ln.strip().split())
                    freq["<s>"] += 1
                    freq["<e>"] += 1
        freq.pop("<unk>", None)
        words = [w for w, c in freq.items() if c > min_word_freq]
        words.sort(key=lambda w: (-freq[w], w))
        idx = {w: i for i, w in enumerate(words)}
        idx["<unk>"] = len(idx)
        return idx

    def __len__(self):
        return len(self.data)

    def __getitem__(self, i):
        return self.data[i]


class _DownloadDataset(Dataset):
    """Base for corpora the reference fetches from its dataset server —
    this environment has no egress and the archive parsers are not
    implemented, so construction always raises with that reason (the
    honest alternative to returning an object whose __getitem__ would
    fail later)."""

    _NAME = "dataset"

    def __init__(self, *args, **kwargs):
        raise NotImplementedError(
            f"paddle.text.{self._NAME} downloads and parses its corpus "
            "from the dataset server, which needs network access this "
            "build does not have; load the data with paddle.io.Dataset "
            "over local files instead")


class Conll05st(_DownloadDataset):
    """CoNLL-2005 SRL (reference text/datasets/conll05.py)."""

    _NAME = "Conll05st"


class Movielens(_DownloadDataset):
    """MovieLens-1M ratings (reference text/datasets/movielens.py)."""

    _NAME = "Movielens"


class WMT14(_DownloadDataset):
    """WMT14 en-fr (reference text/datasets/wmt14.py)."""

    _NAME = "WMT14"


class WMT16(_DownloadDataset):
    """WMT16 en-de (reference text/datasets/wmt16.py)."""

    _NAME = "WMT16"
