"""Viterbi decoding (reference: python/paddle/text/viterbi_decode.py:24 →
phi viterbi_decode kernel).

TPU-native: the per-timestep max-product recursion is a `lax.scan` over the
sequence (compiler-friendly static shapes); variable lengths are handled by
freezing the alpha carry and using identity backpointers past each
sequence's end, so one compiled program serves every length in the batch.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from ..ops._helpers import nondiff

__all__ = ["viterbi_decode", "ViterbiDecoder"]


def _viterbi(pot, trans, lengths, include_bos_eos_tag):
    B, S, N = pot.shape
    lengths = lengths.astype(jnp.int32)
    start_idx, stop_idx = N - 1, N - 2
    alpha = pot[:, 0].astype(jnp.float32)
    if include_bos_eos_tag:
        alpha = alpha + trans[start_idx][None, :].astype(jnp.float32)

    transf = trans.astype(jnp.float32)

    def step(alpha, t):
        # [B, prev, next]
        scores = alpha[:, :, None] + transf[None]
        best_prev = jnp.argmax(scores, axis=1)                   # [B, N]
        best_score = jnp.max(scores, axis=1) + pot[:, t].astype(jnp.float32)
        active = (t < lengths)[:, None]
        new_alpha = jnp.where(active, best_score, alpha)
        bp = jnp.where(active, best_prev,
                       jnp.arange(N, dtype=best_prev.dtype)[None, :])
        return new_alpha, bp

    alpha, bps = jax.lax.scan(step, alpha, jnp.arange(1, S))     # bps [S-1,B,N]
    if include_bos_eos_tag:
        alpha = alpha + transf[:, stop_idx][None, :]
    scores = jnp.max(alpha, axis=1).astype(pot.dtype)
    last_tag = jnp.argmax(alpha, axis=1).astype(jnp.int32)       # [B]

    def back(tag, bp):
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        return prev, tag

    # reverse scan emits tag_t at slot t-1 (bps[k] holds t=k+1 pointers)
    # and its final carry is tag_0
    tag0, tags_rev = jax.lax.scan(back, last_tag, bps, reverse=True)
    path = jnp.concatenate([tag0[:, None],
                            jnp.swapaxes(tags_rev, 0, 1)], axis=1)  # [B, S]
    mask = jnp.arange(S)[None, :] < lengths[:, None]
    # int32, not the reference's int64: x64 is disabled framework-wide
    # (ids never exceed num_tags) and an int64 cast would only warn+truncate
    return scores, jnp.where(mask, path, 0).astype(jnp.int32)


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag: bool = True, name=None):
    """Highest-scoring tag sequence under emissions + transition matrix.

    Returns (scores [B], paths [B, max_len]); with concrete lengths the
    path is truncated to the batch max length like the reference kernel.
    """
    scores, path = nondiff(
        "viterbi_decode",
        lambda p, t, l: _viterbi(p, t, l, include_bos_eos_tag),
        [potentials, transition_params, lengths], n_outs=2)
    larr = lengths._value() if isinstance(lengths, Tensor) else lengths
    if not isinstance(larr, jax.core.Tracer):
        max_len = int(np.max(np.asarray(larr))) if np.size(
            np.asarray(larr)) else 0
        path = path[:, :max_len]
    return scores, path


class ViterbiDecoder(Layer):
    """Layer wrapper (reference: viterbi_decode.py:92)."""

    def __init__(self, transitions, include_bos_eos_tag: bool = True,
                 name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
