"""paddle.text parity (reference: python/paddle/text)."""
from .datasets import (  # noqa: F401
    Imdb, Imikolov, UCIHousing, Conll05st, Movielens, WMT14, WMT16,
)
from .viterbi_decode import ViterbiDecoder, viterbi_decode  # noqa: F401

__all__ = ["UCIHousing", "Imdb", "Imikolov", "viterbi_decode",
           "Conll05st", "Movielens", "WMT14", "WMT16",
           "ViterbiDecoder"]
