"""paddle.text parity (reference: python/paddle/text)."""
from .datasets import Imdb, Imikolov, UCIHousing  # noqa: F401
from .viterbi_decode import ViterbiDecoder, viterbi_decode  # noqa: F401

__all__ = ["UCIHousing", "Imdb", "Imikolov", "viterbi_decode",
           "ViterbiDecoder"]
