"""paddle.incubate parity namespace (reference: python/paddle/incubate).

Hosts pre-stable APIs: fused ops and the MoE/expert-parallel stack.  On TPU
most of the reference's incubate fused CUDA ops are XLA fusions of the plain
nn composition; the ones with a real memory/layout win live in ops.fused.
"""
from ..ops.fused import fused_linear_cross_entropy  # noqa: F401
from . import distributed  # noqa: F401
from .. import sparse  # noqa: F401 — 2.3-era import path paddle.incubate.sparse
from . import asp  # noqa: F401
from . import autograd  # noqa: F401
from . import operators  # noqa: F401
from . import tensor  # noqa: F401
from . import optimizer  # noqa: F401
from .operators import (  # noqa: F401
    graph_send_recv, graph_khop_sampler, graph_sample_neighbors,
    graph_reindex, softmax_mask_fuse, softmax_mask_fuse_upper_triangle)
from .tensor import segment_sum, segment_mean, segment_max, segment_min  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401

__all__ = [
    "fused_linear_cross_entropy", "distributed", "sparse", "asp",
    "autograd",
    "LookAhead", "ModelAverage",
    "softmax_mask_fuse_upper_triangle", "softmax_mask_fuse",
    "graph_send_recv", "graph_khop_sampler", "graph_sample_neighbors",
    "graph_reindex",
    "segment_sum", "segment_mean", "segment_max", "segment_min",
]
