"""paddle.incubate parity namespace (reference: python/paddle/incubate).

Hosts pre-stable APIs: fused ops and the MoE/expert-parallel stack.  On TPU
most of the reference's incubate fused CUDA ops are XLA fusions of the plain
nn composition; the ones with a real memory/layout win live in ops.fused.
"""
from ..ops.fused import fused_linear_cross_entropy  # noqa: F401
from . import distributed  # noqa: F401
from .. import sparse  # noqa: F401 — 2.3-era import path paddle.incubate.sparse
from . import asp  # noqa: F401
from . import autograd  # noqa: F401

__all__ = ["fused_linear_cross_entropy", "distributed", "sparse", "asp",
           "autograd"]
