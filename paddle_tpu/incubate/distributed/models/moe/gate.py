"""MoE gate networks (reference: python/paddle/incubate/distributed/models/
moe/gate/{base_gate,naive_gate,gshard_gate,switch_gate}.py).

Each gate maps tokens [N, H] → (top-k combine weights [N, k], expert ids
[N, k]) and records a load-balancing auxiliary loss in ``self.loss``
(reference: BaseGate.set_loss / get_loss).  All math is framework ops, so
the aux loss is differentiable through the gate projection.
"""
from __future__ import annotations

import numpy as np

from .....nn import functional as F
from .....nn.initializer import Normal
from .....nn.layer.common import Linear
from .....nn.layer_base import Layer
from ..... import ops


class BaseGate(Layer):
    def __init__(self, num_expert: int, top_k: int):
        super().__init__()
        self.num_expert = num_expert
        self.top_k = top_k
        self.loss = None

    def set_loss(self, loss):
        self.loss = loss

    def get_loss(self):
        return self.loss


class NaiveGate(BaseGate):
    """Plain softmax-top-k routing, no auxiliary loss (reference:
    naive_gate.py:29)."""

    def __init__(self, d_model: int, num_expert: int, top_k: int = 2):
        super().__init__(num_expert, top_k)
        self.gate = Linear(d_model, num_expert,
                           weight_attr=Normal(std=0.02))

    def _scores(self, x):
        return F.softmax(self.gate(x).astype("float32"), axis=-1)

    def forward(self, x):
        scores = self._scores(x)
        val, idx = ops.topk(scores, self.top_k, axis=-1)
        self.set_loss(None)
        return val, idx


def _aux_load_balance(scores, top1_idx, num_expert):
    """GShard/Switch load-balancing loss: E * Σ_e mean_prob_e * frac_e,
    where frac_e is the fraction of tokens whose first choice is e."""
    me = scores.mean(axis=0)                                  # [E]
    assigned = ops.one_hot(top1_idx.astype("int64"),
                           num_expert).astype("float32")      # [N, E]
    ce = assigned.mean(axis=0)                                # [E]
    return (me * ce).sum() * num_expert


class GShardGate(NaiveGate):
    """Top-2 gate with load-balance aux loss, train/eval capacity factors
    and GShard's random second-expert routing (reference: gshard_gate.py:30;
    GShard paper §3.2: the 2nd expert is used with probability proportional
    to its gate weight — tokens whose 2nd weight is small route top-1 only,
    which decorrelates overflow)."""

    def __init__(self, d_model: int, num_expert: int, top_k: int = 2,
                 capacity=(1.2, 2.4), random_routing: bool = True):
        if top_k != 2:
            raise ValueError("GShardGate works with top_k=2")
        super().__init__(d_model, num_expert, top_k)
        self.capacity = tuple(capacity)
        self.random_routing = random_routing

    def capacity_factor(self, training: bool) -> float:
        return self.capacity[0] if training else self.capacity[1]

    def forward(self, x):
        scores = self._scores(x)
        val, idx = ops.topk(scores, 2, axis=-1)
        self.set_loss(_aux_load_balance(scores, idx[:, 0], self.num_expert))
        if self.random_routing and self.training:
            # keep the 2nd expert with prob min(1, 2*w2): zero its combine
            # weight otherwise (capacity dispatch then drops the slot)
            u = ops.rand_like(val[:, 1:2])
            keep2 = (2.0 * val[:, 1:2] > u).astype(val.dtype)
            val = ops.concat([val[:, 0:1], val[:, 1:2] * keep2], axis=-1)
        return val, idx


class SwitchGate(NaiveGate):
    """Top-1 switch routing with aux loss and train/eval capacity factors
    (reference: switch_gate.py:30)."""

    def __init__(self, d_model: int, num_expert: int, top_k: int = 1,
                 capacity=(1.2, 2.4)):
        if top_k != 1:
            raise ValueError("SwitchGate is top-1")
        super().__init__(d_model, num_expert, top_k)
        self.capacity = tuple(capacity)

    def capacity_factor(self, training: bool) -> float:
        return self.capacity[0] if training else self.capacity[1]

    def forward(self, x):
        scores = self._scores(x)
        val, idx = ops.topk(scores, 1, axis=-1)
        self.set_loss(_aux_load_balance(scores, idx[:, 0], self.num_expert))
        return val, idx
