"""MoELayer — mixture-of-experts with expert parallelism.

Reference parity: python/paddle/incubate/distributed/models/moe/
moe_layer.py:384 (MoELayer over a moe_group; dispatch via the
global_scatter/global_gather C++ collectives, moe_layer.py:96-245,
paddle/fluid/operators/collective/global_scatter_op.cc:108).

TPU-native design: the reference's count-exchange + ragged NCCL alltoall
becomes a STATIC-shape capacity dispatch (the GShard construction — XLA
needs static shapes, and fixed expert capacity is also what bounds memory):

1. top-k expert choice per token (gate), positions within each expert's
   queue by a priority-ordered cumulative count (first choices of all
   tokens outrank second choices — GShard's priority rule);
2. tokens scatter into a [E, C, H] buffer; tokens over capacity drop
   (their combine weight contributes zero, like the reference's capacity
   clamp in prune_gate_by_capacity);
3. the buffer is sharding-constrained so the expert dim E lies on the
   expert-parallel mesh axes — GSPMD emits the batch→expert all-to-all
   that global_scatter performed explicitly;
4. stacked experts run under jax.vmap over the expert dim (one MXU batch);
5. outputs gather back by the same slots and combine with gate weights.

The whole dispatch-compute-combine is one differentiable tape op; gradients
flow to tokens, gate weights (through the combine weights and aux loss) and
every expert parameter.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .....core import autograd
from .....core.dispatch import apply_op
from .....core.tensor import Tensor
from .....nn.layer_base import Layer
from .....nn.layer.container import LayerList
from .....distributed import mesh as mesh_mod
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate

__all__ = ["MoELayer"]


def _expert_leaves(layer: Layer) -> List[Tensor]:
    leaves = [p for _, p in sorted(layer.named_parameters())]
    leaves += [b for _, b in sorted(layer.named_buffers())]
    return leaves


def _apply_template(template: Layer, leaf_arrays, x_arr):
    """Run template.forward over raw arrays by payload swap (same mechanism
    as the pipeline schedule's stage body)."""
    leaves = _expert_leaves(template)
    saved = [(t, t._data) for t in leaves]
    try:
        for t, a in zip(leaves, leaf_arrays):
            t._data = a
        with autograd.no_grad():
            out = template(Tensor._wrap(x_arr))
    finally:
        for t, a in saved:
            t._data = a
    return out._value() if isinstance(out, Tensor) else out


def _ep_axes(mesh, num_expert: int):
    """Mesh axes to lay the expert dim over: a dedicated 'expert' axis if
    the mesh has one, else the DP axes (DeepSpeed-style EP=DP placement)."""
    if mesh is None:
        return None
    for cand in (("expert",), ("data", "sharding"), ("data",)):
        n = 1
        for a in cand:
            n *= mesh.shape.get(a, 1)
        kept = tuple(a for a in cand if mesh.shape.get(a, 1) > 1)
        if kept and num_expert % n == 0:
            return kept
    return None


class MoELayer(Layer):
    """See module docstring.  API mirrors reference moe_layer.py:384.

    Args:
        d_model: token width.
        experts: list/LayerList of structurally-identical expert Layers
            (the total expert count across the expert-parallel group).
        gate: dict config ({"type": "gshard"|"switch"|"naive",
            "top_k": int}) or a BaseGate instance.
        moe_group / mp_group: accepted for API parity; on TPU the expert
            placement is the mesh annotation from _ep_axes, not a process
            group.
        capacity_factor: per-expert queue size multiplier
            (C = ceil(top_k * N / E * capacity_factor)).
    """

    def __init__(self, d_model: int, experts, gate=None, moe_group=None,
                 mp_group=None, capacity_factor: Optional[float] = None,
                 **kwargs):
        super().__init__()
        self.d_model = d_model
        self.experts = experts if isinstance(experts, LayerList) \
            else LayerList(list(experts))
        self.num_expert = len(self.experts)
        self.capacity_factor = (float(capacity_factor)
                                if capacity_factor is not None else None)
        if gate is None:
            gate = {"type": "gshard", "top_k": 2}
        if isinstance(gate, dict):
            kind = gate.get("type", "gshard")
            top_k = gate.get("top_k", 2)
            if kind == "naive":
                gate = NaiveGate(d_model, self.num_expert, top_k=top_k)
            elif kind == "gshard":
                gate = GShardGate(d_model, self.num_expert, top_k=2)
            elif kind == "switch":
                gate = SwitchGate(d_model, self.num_expert, top_k=1)
            else:
                raise ValueError(f"unknown gate type {kind!r}")
        if not isinstance(gate, BaseGate):
            raise TypeError("gate must be a BaseGate or config dict")
        self.gate = gate
        self.top_k = gate.top_k
        # stacked-leaf template for vmapped expert compute
        self._template = self.experts[0]
        from ..... distributed.fleet.meta_parallel.pp_schedule import (
            structure_signature,
        )
        sig0 = structure_signature(self._template)
        for e in self.experts:
            if structure_signature(e) != sig0:
                raise ValueError("experts must be structurally identical")

    @property
    def l_aux(self):
        return self.gate.get_loss()

    def forward(self, x):
        orig_shape = x.shape
        H = orig_shape[-1]
        x2 = x.reshape([-1, H])
        N = x2.shape[0]
        E, K = self.num_expert, self.top_k
        # explicit capacity_factor wins; else the gate's train/eval pair
        # (reference: gates carry (train_cap, eval_cap)); else 1.2
        cf = self.capacity_factor
        if cf is None:
            cf = (self.gate.capacity_factor(self.training)
                  if hasattr(self.gate, "capacity_factor") else 1.2)
        C = int(np.ceil(K * N / E * cf))
        val, idx = self.gate(x2)                       # [N,K] f32 / int
        mesh = mesh_mod.get_global_mesh()
        ep = _ep_axes(mesh, E)
        per_leaf = [_expert_leaves(e) for e in self.experts]
        n_leaf = len(per_leaf[0])
        flat = [t for leaves in per_leaf for t in leaves]

        def primal(x_arr, val_arr, idx_arr, *leaf_arrays):
            # ---- positions by GShard priority: all 1st choices first ----
            idx_f = idx_arr.astype(jnp.int32).T.reshape(-1)        # [K*N]
            onehot = (idx_f[:, None] == jnp.arange(E)[None, :])
            pos_f = (jnp.cumsum(onehot.astype(jnp.int32), axis=0)
                     * onehot).sum(-1) - 1                          # [K*N]
            keep = pos_f < C
            slot = jnp.where(keep, idx_f * C + pos_f, E * C)       # drop→trash
            tok = jnp.tile(jnp.arange(N), K)
            # ---- scatter tokens into the expert buffer ------------------
            buf = jnp.zeros((E * C + 1, H), x_arr.dtype)
            buf = buf.at[slot].add(x_arr[tok])
            ebuf = buf[:E * C].reshape(E, C, H)
            if ep is not None:
                ebuf = jax.lax.with_sharding_constraint(
                    ebuf, NamedSharding(mesh, P(ep, None, None)))

            # ---- vmapped stacked experts --------------------------------
            stacked = []
            for j in range(n_leaf):
                s = jnp.stack([leaf_arrays[i * n_leaf + j]
                               for i in range(E)], axis=0)
                if ep is not None:
                    s = jax.lax.with_sharding_constraint(
                        s, NamedSharding(
                            mesh, P(*( (ep,) + (None,) * (s.ndim - 1)))))
                stacked.append(s)
            eout = jax.vmap(
                lambda leaves_e, xe: _apply_template(
                    self._template, leaves_e, xe))(tuple(stacked), ebuf)

            # ---- gather back + combine ----------------------------------
            flat_out = jnp.concatenate(
                [eout.reshape(E * C, H),
                 jnp.zeros((1, H), eout.dtype)], axis=0)
            y_f = flat_out[slot]                                    # [K*N,H]
            w_f = (val_arr.astype(jnp.float32).T.reshape(-1)
                   * keep.astype(jnp.float32))
            y = (y_f.astype(jnp.float32) * w_f[:, None]) \
                .reshape(K, N, H).sum(0)
            return y.astype(x_arr.dtype)

        out = apply_op("moe_dispatch_combine", primal, [x2, val, idx] + flat)
        return out.reshape(orig_shape)
