"""Incubate operators: graph learning + fused transformer softmax.

Reference: python/paddle/incubate/operators/{graph_send_recv.py,
graph_khop_sampler.py, graph_sample_neighbors.py, graph_reindex.py,
softmax_mask_fuse.py, softmax_mask_fuse_upper_triangle.py}.

TPU-native split:
- ``graph_send_recv`` and the fused softmaxes are device ops — scatter
  segments and masked softmax both lower to single XLA fusions (the
  reference needs hand-written CUDA for each).
- The samplers (`graph_khop_sampler`, `graph_sample_neighbors`,
  `graph_reindex`) are *host-side*: their output shapes are data-dependent
  (number of sampled edges), which XLA cannot compile.  In a TPU pipeline
  they belong on the host next to the DataLoader — sample/reindex on CPU,
  feed the static-shape subgraph to the device (same place the reference
  runs them when no GPU is present, graph_khop_sampler_op.h).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops._helpers import op, unwrap, wrap

__all__ = [
    "graph_send_recv", "graph_khop_sampler", "graph_sample_neighbors",
    "graph_reindex", "softmax_mask_fuse", "softmax_mask_fuse_upper_triangle",
]


# ---------------------------------------------------------------------------
# device ops
# ---------------------------------------------------------------------------

def graph_send_recv(x, src_index, dst_index, pool_type="sum", out_size=None,
                    name=None):
    """Gather ``x[src_index]`` then scatter-reduce into rows ``dst_index``.

    Rows receiving no message are 0 (all pool types), matching the
    reference kernel's zero-initialised output
    (paddle/phi/kernels/cpu/graph_send_recv_kernel.cc).
    """
    if pool_type not in ("sum", "mean", "max", "min"):
        raise ValueError(
            "pool_type should be `sum`, `mean`, `max` or `min`, "
            "but received %s" % pool_type)
    if out_size is None:
        n = int(unwrap(x).shape[0])
    else:
        n = int(out_size) if not isinstance(out_size, Tensor) \
            else int(out_size.item())

    def primal(xa, src, dst):
        src = src.astype(jnp.int32).reshape(-1)
        dst = dst.astype(jnp.int32).reshape(-1)
        msgs = xa[src]
        out_shape = (n,) + xa.shape[1:]
        if pool_type == "sum":
            return jnp.zeros(out_shape, xa.dtype).at[dst].add(msgs)
        cnt = jnp.zeros((n,), jnp.float32).at[dst].add(1.0)
        cnt = cnt.reshape((n,) + (1,) * (xa.ndim - 1))
        if pool_type == "mean":
            s = jnp.zeros(out_shape, xa.dtype).at[dst].add(msgs)
            return s / jnp.maximum(cnt, 1.0).astype(xa.dtype)
        if pool_type == "max":
            m = jnp.full(out_shape, -jnp.inf, xa.dtype).at[dst].max(msgs)
        else:
            m = jnp.full(out_shape, jnp.inf, xa.dtype).at[dst].min(msgs)
        return jnp.where(cnt > 0, m, jnp.zeros_like(m))

    return op(f"graph_send_recv_{pool_type}", primal, [x, src_index, dst_index])


def _softmax_f32(y, dtype):
    y = y - y.max(axis=-1, keepdims=True)
    e = jnp.exp(y)
    return (e / e.sum(axis=-1, keepdims=True)).astype(dtype)


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) computed in f32, returned in x's dtype.

    Reference: fused_softmax_mask op
    (paddle/fluid/operators/fused_softmax_mask_op.cu); on TPU the
    add+softmax pair is one XLA fusion, so the composition IS the kernel.
    """
    def primal(xa, ma):
        return _softmax_f32(
            xa.astype(jnp.float32) + ma.astype(jnp.float32), xa.dtype)

    return op("softmax_mask_fuse", primal, [x, mask])


def softmax_mask_fuse_upper_triangle(x, name=None):
    """Causal (upper-triangle-masked) softmax over the last two dims.

    Positions col > row get -10000 before the softmax, matching the
    reference kernel
    (paddle/fluid/operators/fused_softmax_mask_upper_triangle_op.cu).
    """
    def primal(xa):
        s_q, s_k = xa.shape[-2], xa.shape[-1]
        causal = jnp.tril(jnp.ones((s_q, s_k), bool))
        y = jnp.where(causal, xa.astype(jnp.float32), -10000.0)
        return _softmax_f32(y, xa.dtype)

    return op("softmax_mask_fuse_upper_triangle", primal, [x])


# ---------------------------------------------------------------------------
# host-side samplers
# ---------------------------------------------------------------------------

def _np1d(t, dtype=np.int64):
    return np.asarray(unwrap(t)).reshape(-1).astype(dtype)


def _reindex_np(x, neighbors):
    """Order-preserving relabel: x first, then new neighbor ids by first
    appearance.  Returns (mapped_neighbors, out_nodes)."""
    out_nodes = list(x)
    table = {int(v): i for i, v in enumerate(x)}
    mapped = np.empty(len(neighbors), np.int64)
    for i, v in enumerate(neighbors):
        v = int(v)
        j = table.get(v)
        if j is None:
            j = len(out_nodes)
            table[v] = j
            out_nodes.append(v)
        mapped[i] = j
    return mapped, np.asarray(out_nodes, np.int64)


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    """Relabel sampled subgraph node ids from 0 (host-side).

    Returns (reindex_src, reindex_dst, out_nodes): edges dst[i]->src over
    the new ids, input nodes occupying ids [0, len(x)).
    """
    xs = _np1d(x)
    nb = _np1d(neighbors)
    ct = _np1d(count)
    mapped, out_nodes = _reindex_np(xs, nb)
    dst = np.repeat(np.arange(len(xs), dtype=np.int64), ct)
    return wrap(jnp.asarray(mapped)), wrap(jnp.asarray(dst)), \
        wrap(jnp.asarray(out_nodes))


def _sample_one_hop(row, colptr, nodes, sample_size, eids, rng):
    """CSC one-hop: neighbors of n are row[colptr[n]:colptr[n+1]]."""
    out_nb, out_ct, out_eids = [], [], []
    for n in nodes:
        beg, end = int(colptr[n]), int(colptr[n + 1])
        deg = end - beg
        if sample_size < 0 or deg <= sample_size:
            idx = np.arange(beg, end)
        else:
            idx = beg + rng.choice(deg, size=sample_size, replace=False)
        out_nb.append(row[idx])
        out_ct.append(len(idx))
        if eids is not None:
            out_eids.append(eids[idx])
    nb = np.concatenate(out_nb) if out_nb else np.empty(0, np.int64)
    es = (np.concatenate(out_eids) if out_eids else np.empty(0, np.int64)) \
        if eids is not None else None
    return nb, np.asarray(out_ct, np.int64), es


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None):
    """Uniformly sample up to ``sample_size`` neighbors per input node
    (host-side; -1 = all).  Returns (out_neighbors, out_count[, out_eids]).
    """
    if return_eids and eids is None:
        raise ValueError("`eids` should not be None if `return_eids` is True.")
    r = _np1d(row)
    cp = _np1d(colptr)
    nodes = _np1d(input_nodes)
    ea = _np1d(eids) if (eids is not None and return_eids) else None
    rng = np.random.default_rng()
    nb, ct, es = _sample_one_hop(r, cp, nodes, int(sample_size), ea, rng)
    outs = (wrap(jnp.asarray(nb)), wrap(jnp.asarray(ct)))
    if return_eids:
        return outs + (wrap(jnp.asarray(es)),)
    return outs


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """Multi-layer neighbor sampling + subgraph reindex (host-side).

    Returns (edge_src, edge_dst, sample_index, reindex_nodes[, edge_eids]),
    edge columns shaped [E, 1] like the reference kernel
    (paddle/fluid/operators/graph_khop_sampler_op.h).
    """
    if return_eids and sorted_eids is None:
        raise ValueError(
            "`sorted_eids` should not be None if `return_eids` is True.")
    r = _np1d(row)
    cp = _np1d(colptr)
    seeds = _np1d(input_nodes)
    ea = _np1d(sorted_eids) if (sorted_eids is not None and return_eids) \
        else None
    rng = np.random.default_rng()

    frontier = seeds
    all_src, all_dst, all_eids = [], [], []
    for size in list(sample_sizes):
        nb, ct, es = _sample_one_hop(r, cp, frontier, int(size), ea, rng)
        all_src.append(nb)
        all_dst.append(np.repeat(frontier, ct))
        if es is not None:
            all_eids.append(es)
        # next layer samples neighbors of the newly discovered nodes
        frontier = np.unique(nb)
    src = np.concatenate(all_src) if all_src else np.empty(0, np.int64)
    dst = np.concatenate(all_dst) if all_dst else np.empty(0, np.int64)

    # subgraph reindex: seeds first, then sampled nodes by first appearance
    mapped_src, out_nodes = _reindex_np(seeds, src)
    table = {int(v): i for i, v in enumerate(out_nodes)}
    mapped_dst = np.asarray([table[int(v)] for v in dst], np.int64)
    reindex_nodes = np.arange(len(seeds), dtype=np.int64)

    outs = (
        wrap(jnp.asarray(mapped_src.reshape(-1, 1))),
        wrap(jnp.asarray(mapped_dst.reshape(-1, 1))),
        wrap(jnp.asarray(out_nodes)),
        wrap(jnp.asarray(reindex_nodes)),
    )
    if return_eids:
        es = np.concatenate(all_eids) if all_eids else np.empty(0, np.int64)
        return outs + (wrap(jnp.asarray(es.reshape(-1, 1))),)
    return outs
