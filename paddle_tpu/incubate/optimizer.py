"""Incubate optimizers: LookAhead, ModelAverage.

Reference: python/paddle/incubate/optimizer/lookahead.py and
modelaverage.py (+ the average_accumulates kernel,
paddle/fluid/operators/average_accumulates_op.h).

TPU-native design: both keep their state in persistent Tensors and express
the every-k-step / window-reset conditions as ``jnp.where`` over a
step-counter tensor rather than host control flow, so `step()` inside a
``to_static`` train step compiles into the same XLA program as the inner
optimizer update (the reference reaches the same shape via conditional
blocks in ProgramDesc).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.autograd import no_grad
from ..optimizer.optimizer import Optimizer

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead(Optimizer):
    r"""Lookahead (https://arxiv.org/abs/1907.08610): the inner optimizer
    updates fast params every step; every ``k`` steps the slow params move
    ``alpha`` of the way to the fast params and the fast params snap back:

        slow = slow + alpha * (fast - slow);  fast = slow

    Reference: python/paddle/incubate/optimizer/lookahead.py:26.
    """

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if not isinstance(inner_optimizer, Optimizer):
            raise TypeError(
                "inner optimizer should be an Optimizer, but got "
                f"{type(inner_optimizer)}")
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha should be in [0, 1], but got %s" % alpha)
        if not (isinstance(k, int) and k > 0):
            raise ValueError("k should be a positive integer, but got %s" % k)
        super().__init__(
            learning_rate=alpha,
            parameters=inner_optimizer._parameter_list, name=name)
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k

    def _step_counter(self) -> Tensor:
        from ..core import tensor as tensor_mod

        accs = self._accumulators.setdefault("@lookahead", {})
        if "k_step" not in accs:
            accs["k_step"] = tensor_mod.external_tensor(
                lambda: jnp.zeros((), jnp.int32))
        return accs["k_step"]

    @no_grad()
    def step(self):
        self.inner_optimizer.step()
        ctr = self._step_counter()
        step = ctr._value() + 1
        ctr._set_data(step)
        sync = (step % self.k) == 0
        for p in self._parameter_list or []:
            if not getattr(p, "trainable", True):
                continue
            # copy=True: astype on an f32 param would alias the param's
            # buffer and break donation under jit (same buffer donated
            # twice as two state entries)
            slow = self._get_accumulator(
                "slow", p, dtype=jnp.float32,
                init_from=lambda p=p: jnp.array(
                    p._data, dtype=jnp.float32, copy=True))
            # read/write the FAST weights through the INNER optimizer's
            # master accumulator: under AMP-O2 a private master here would
            # freeze at its init value and desync from the inner updates
            fast32 = self.inner_optimizer._master_value(p)
            slow_new = jnp.where(
                sync, slow._value() + self.alpha * (fast32 - slow._value()),
                slow._value())
            slow._set_data(slow_new)
            self.inner_optimizer._apply_master(
                p, jnp.where(sync, slow_new, fast32))

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        return None, None

    def clear_grad(self, set_to_zero=False):
        self.inner_optimizer.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def state_dict(self):
        sd = super().state_dict()
        for k, v in self.inner_optimizer.state_dict().items():
            sd[f"inner/{k}"] = v
        return sd

    def set_state_dict(self, state_dict):
        inner = {k[len("inner/"):]: v for k, v in state_dict.items()
                 if k.startswith("inner/")}
        outer = {k: v for k, v in state_dict.items()
                 if not k.startswith("inner/")}
        self.inner_optimizer.set_state_dict(inner)
        super().set_state_dict(outer)


class ModelAverage(Optimizer):
    r"""Maintain a running average of parameters over a trailing window and
    swap it in for evaluation via ``apply()`` / ``restore()``.

    The window length tracks
    ``min(max_average_window, num_updates * average_window_rate)`` with a
    floor of ``min_average_window``; the three-bucket sum scheme
    (sum_1 current, sum_2 precision-rollover every 16384 updates, sum_3
    last discarded window) follows the reference kernel exactly
    (average_accumulates_op.h:42-108).
    """

    _MAX_NUM_ACCUMULATES = 16384

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        super().__init__(learning_rate=0.0, parameters=parameters, name=name)
        self.average_window = float(average_window_rate)
        self.min_average_window = int(min_average_window)
        self.max_average_window = int(max_average_window)
        self._restore_vals = {}

    def _counter(self, name) -> Tensor:
        from ..core import tensor as tensor_mod

        accs = self._accumulators.setdefault("@model_average", {})
        if name not in accs:
            accs[name] = tensor_mod.external_tensor(
                lambda: jnp.zeros((), jnp.int32))
        return accs[name]

    def _sums(self, p):
        return tuple(
            self._get_accumulator(n, p, dtype=jnp.float32)
            for n in ("sum_1", "sum_2", "sum_3"))

    @no_grad()
    def step(self):
        nu_t = self._counter("num_updates")
        na_t = self._counter("num_accumulates")
        ona_t = self._counter("old_num_accumulates")
        num_updates = nu_t._value() + 1
        num_accumulates = na_t._value() + 1

        rollover = (num_updates % self._MAX_NUM_ACCUMULATES) == 0
        window = jnp.minimum(
            jnp.asarray(self.max_average_window, jnp.float32),
            num_updates.astype(jnp.float32) * self.average_window)
        discard = (num_accumulates >= self.min_average_window) \
            & (num_accumulates.astype(jnp.float32) >= window)

        for p in self._parameter_list or []:
            if not getattr(p, "trainable", True):
                continue
            s1, s2, s3 = self._sums(p)
            # accumulate the CURRENT param value (the main optimizer owns
            # any master copy; a private master here would freeze)
            v1 = s1._value() + p._value().astype(jnp.float32)
            v2, v3 = s2._value(), s3._value()
            # precision rollover: fold sum_1 into sum_2
            v2 = jnp.where(rollover, v2 + v1, v2)
            v1 = jnp.where(rollover, jnp.zeros_like(v1), v1)
            # window overflow: current window becomes the "old" sum
            v3 = jnp.where(discard, v1 + v2, v3)
            v1 = jnp.where(discard, jnp.zeros_like(v1), v1)
            v2 = jnp.where(discard, jnp.zeros_like(v2), v2)
            s1._set_data(v1)
            s2._set_data(v2)
            s3._set_data(v3)

        ona_t._set_data(jnp.where(discard, num_accumulates, ona_t._value()))
        na_t._set_data(jnp.where(discard, jnp.zeros_like(num_accumulates),
                                 num_accumulates))
        nu_t._set_data(num_updates)

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        self.step()
        return None, None

    def _average_value(self, p):
        s1, s2, s3 = self._sums(p)
        total = self._counter("num_accumulates")._value() \
            + self._counter("old_num_accumulates")._value()
        denom = jnp.maximum(total, 1).astype(jnp.float32)
        return (s1._value() + s2._value() + s3._value()) / denom

    @no_grad()
    def apply(self, executor=None, need_restore=True):
        """Swap the averaged values into the parameters (eval-time)."""
        for p in self._parameter_list or []:
            if not getattr(p, "trainable", True):
                continue
            self._restore_vals[self._param_key(p)] = p._value()
            self._apply(p, self._average_value(p))
        self._need_restore = need_restore
        return _ApplyCtx(self)

    @no_grad()
    def restore(self, executor=None):
        """Undo ``apply()``: put the training values back."""
        for p in self._parameter_list or []:
            key = self._param_key(p)
            if key in self._restore_vals:
                p._set_data(self._restore_vals.pop(key))


class _ApplyCtx:
    """`with model_average.apply(): ...` restores on exit if requested."""

    def __init__(self, ma):
        self._ma = ma

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if getattr(self._ma, "_need_restore", True):
            self._ma.restore()
        return False
