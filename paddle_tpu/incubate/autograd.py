"""paddle.incubate.autograd — functional differentiation API (reference
`python/paddle/autograd/functional.py:22,79,165,255` jvp/vjp/Jacobian/
Hessian, re-exported under incubate.autograd).

TPU-native: direct jax transform wrappers over the Tensor facade —
forward-mode via jax.jvp (the reference builds double-backward graphs to
emulate it), reverse via jax.vjp, Jacobian via jax.jacfwd (vmapped for
the batched contract), Hessian via jax.hessian."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops._helpers import unwrap, wrap

__all__ = ["vjp", "jvp", "Jacobian", "Hessian"]


def _as_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _pack(arrays):
    out = [wrap(a) for a in arrays]
    return out[0] if len(out) == 1 else tuple(out)


def _pure(func):
    """Wrap a Tensor->Tensor(s) function as arrays->arrays (structure
    preserved)."""

    def f(*arrays):
        outs = func(*[wrap(a) for a in arrays])
        if isinstance(outs, (list, tuple)):
            return tuple(unwrap(o) for o in outs)
        return unwrap(outs)

    return f


def _pure_flat(func):
    """arrays -> one flat vector (multi-output funcs concatenate)."""
    f = _pure(func)

    def flat(*arrays):
        out = f(*arrays)
        outs = out if isinstance(out, tuple) else (out,)
        return jnp.concatenate([jnp.ravel(o) for o in outs])

    return flat


def vjp(func, xs, v=None):
    """Returns (outputs, input-gradients) for cotangent v (defaults to
    ones like the reference)."""
    xs_l = _as_list(xs)
    arrays = [unwrap(x) for x in xs_l]
    f = _pure(func)
    outs, pullback = jax.vjp(f, *arrays)
    if v is None:
        cot = jax.tree.map(jnp.ones_like, outs)
    else:
        cot = tuple(unwrap(c) for c in _as_list(v))
        if not isinstance(outs, tuple):
            cot = cot[0]
    grads = pullback(cot)
    outs_t = outs if isinstance(outs, tuple) else (outs,)
    return _pack(list(outs_t)), _pack(list(grads))


def jvp(func, xs, v=None):
    """Forward-mode: returns (outputs, jvp) for tangent v (defaults to
    ones)."""
    xs_l = _as_list(xs)
    arrays = [unwrap(x) for x in xs_l]
    f = _pure(func)
    if v is None:
        tangents = tuple(jnp.ones_like(a) for a in arrays)
    else:
        tangents = tuple(unwrap(t) for t in _as_list(v))
    outs, tangents_out = jax.jvp(f, tuple(arrays), tangents)
    outs_t = outs if isinstance(outs, tuple) else (outs,)
    tan_t = tangents_out if isinstance(tangents_out, tuple) \
        else (tangents_out,)
    return _pack(list(outs_t)), _pack(list(tan_t))


class Jacobian:
    """Lazy Jacobian (reference functional.py:165).

    Unbatched: flattened [out_size, total_in_size] (multi-output funcs
    concatenate their flattened outputs; multi-input columns concatenate
    in input order).  Batched (`is_batched=True`, single input
    [B, ...]): per-sample [B, out_size, in_size] via vmap(jacfwd) — O(B)
    work, no cross-batch blocks."""

    def __init__(self, func, xs, is_batched=False):
        self._func = func
        self._xs = _as_list(xs)
        self._is_batched = is_batched
        self._mat = None

    def _compute(self):
        if self._mat is not None:
            return self._mat
        arrays = [unwrap(x) for x in self._xs]
        flat = _pure_flat(self._func)

        if self._is_batched:
            if len(arrays) > 1:
                raise NotImplementedError(
                    "batched Jacobian supports a single input tensor "
                    "[B, ...]; pass inputs concatenated")
            x = arrays[0]

            def per_sample(xb):
                return flat(xb[None])

            jac = jax.vmap(jax.jacfwd(per_sample))(x)   # [B, out, *in]
            self._mat = jac.reshape(x.shape[0], jac.shape[1], -1)
            return self._mat

        jacs = jax.jacfwd(flat, argnums=tuple(range(len(arrays))))(
            *arrays)
        jacs = jacs if isinstance(jacs, tuple) else (jacs,)
        rows = jacs[0].shape[0]
        self._mat = jnp.concatenate(
            [j.reshape(rows, -1) for j in jacs], axis=1)
        return self._mat

    @property
    def shape(self):
        if self._mat is not None:
            return list(self._mat.shape)
        # sizes via eval_shape: zero FLOPs (lazy contract of the
        # reference API)
        import jax as _jax

        arrays = [unwrap(x) for x in self._xs]
        flat = _pure_flat(self._func)
        if self._is_batched:
            B = arrays[0].shape[0]
            out = _jax.eval_shape(flat, arrays[0][:1])
            return [B, int(out.shape[0]), int(arrays[0][0].size)]
        out = _jax.eval_shape(flat, *arrays)
        return [int(out.shape[0]), int(sum(a.size for a in arrays))]

    def __getitem__(self, idx):
        return wrap(self._compute()[idx])

    def numpy(self):
        import numpy as np

        return np.asarray(self._compute())


class Hessian:
    """Lazy Hessian of a scalar function (reference functional.py:255):
    [in_size, in_size] (symmetric); batched (`is_batched=True`, single
    input [B, n], per-sample scalar outputs): [B, n, n]."""

    def __init__(self, func, xs, is_batched=False):
        self._func = func
        self._xs = _as_list(xs)
        self._is_batched = is_batched
        self._mat = None

    def _compute(self):
        if self._mat is not None:
            return self._mat
        arrays = [unwrap(x) for x in self._xs]
        flat = _pure_flat(self._func)

        if self._is_batched:
            if len(arrays) > 1:
                raise NotImplementedError(
                    "batched Hessian supports a single input tensor "
                    "[B, n]")
            x = arrays[0]

            def per_sample(xb):
                out = flat(xb[None])
                if out.size != 1:
                    raise ValueError(
                        "batched Hessian requires one scalar per sample")
                return out.reshape(())

            h = jax.vmap(jax.hessian(per_sample))(x)    # [B, *in, *in]
            n = int(x[0].size)
            self._mat = h.reshape(x.shape[0], n, n)
            return self._mat

        def scalar_f(*a):
            out = flat(*a)
            if out.size != 1:
                raise ValueError("Hessian requires a scalar function")
            return out.reshape(())

        if len(arrays) == 1:
            h = jax.hessian(scalar_f)(arrays[0])
            n = arrays[0].size
            self._mat = h.reshape(n, n)
        else:
            h = jax.hessian(scalar_f,
                            argnums=tuple(range(len(arrays))))(*arrays)
            sizes = [a.size for a in arrays]
            blocks = []
            for i in range(len(arrays)):
                row = [jnp.reshape(h[i][j], (sizes[i], sizes[j]))
                       for j in range(len(arrays))]
                blocks.append(jnp.concatenate(row, axis=1))
            self._mat = jnp.concatenate(blocks, axis=0)
        return self._mat

    @property
    def shape(self):
        if self._mat is not None:
            return list(self._mat.shape)
        arrays = [unwrap(x) for x in self._xs]
        if self._is_batched:
            B = arrays[0].shape[0]
            n = int(arrays[0][0].size)
            return [B, n, n]
        n = int(sum(a.size for a in arrays))
        return [n, n]

    def __getitem__(self, idx):
        return wrap(self._compute()[idx])

    def numpy(self):
        import numpy as np

        return np.asarray(self._compute())


# -- primitive-mode toggles (reference: incubate/autograd/primx.py
# enable_prim/disable_prim — a CINN-era whole-graph primitive lowering).
# On TPU, jax's jaxpr primitives ARE the primitive IR and XLA lowers
# them always; the toggle is honored as state (some reference code
# branches on prim_enabled()) but changes nothing about lowering.

_prim_enabled = False


def enable_prim():
    global _prim_enabled
    _prim_enabled = True


def disable_prim():
    global _prim_enabled
    _prim_enabled = False


def prim_enabled() -> bool:
    return _prim_enabled


def prim2orig(block=None):
    """Reference: rewrite primitive ops back to original ops in a static
    block.  There is no separate primitive block here (jaxprs lower
    directly), so this is an intentional no-op."""
    return None
