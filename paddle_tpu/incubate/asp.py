"""paddle.incubate.asp — automatic structured (2:4) sparsity (reference
`python/paddle/incubate/asp/__init__.py` →
`fluid/contrib/sparsity/asp.py`: prune_model, decorate,
calculate_density, set/reset_excluded_layers).

TPU note: the reference prunes for Ampere sparse-tensor-core speedups;
the MXU has no 2:4 fast path, so here ASP is a *model-compression*
capability — masks are computed once (magnitude-based best-2-of-4) and
the decorated optimizer re-applies them after every step so pruned
weights stay exactly zero through training."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = [
    'calculate_density', 'decorate', 'prune_model',
    'set_excluded_layers', 'reset_excluded_layers',
]

_excluded_layers = set()
_masks = {}          # param name -> jnp bool mask


def set_excluded_layers(param_names, main_program=None):
    """Exclude parameters (by name) from pruning."""
    for n in param_names:
        _excluded_layers.add(n)


def reset_excluded_layers(main_program=None):
    _excluded_layers.clear()


def calculate_density(x):
    """Fraction of nonzero entries."""
    arr = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    return float((arr != 0).sum()) / max(arr.size, 1)


def _best_2in4_mask(w: np.ndarray) -> np.ndarray:
    """2:4 mask along the last axis: keep the 2 largest |w| of every
    contiguous group of 4 (reference sparsity/utils get_mask_2d_best /
    create_mask with MaskAlgo.MASK_1D)."""
    orig_shape = w.shape
    n = w.shape[-1]
    pad = (-n) % 4
    if pad:
        w = np.concatenate(
            [w, np.zeros(w.shape[:-1] + (pad,), w.dtype)], axis=-1)
    g = np.abs(w).reshape(-1, 4)
    order = np.argsort(-g, axis=1)          # descending |w|
    mask = np.zeros_like(g, dtype=bool)
    rows = np.arange(g.shape[0])[:, None]
    mask[rows, order[:, :2]] = True
    mask = mask.reshape(w.shape)
    if pad:
        mask = mask[..., :n]
    return mask.reshape(orig_shape)


def _supported_layer(layer):
    from .. import nn

    types = [nn.Linear]
    for name in ("Conv1D", "Conv2D", "Conv3D"):
        cls = getattr(nn, name, None)
        if cls is not None:
            types.append(cls)
    return isinstance(layer, tuple(types))


def _prunable(layer, p):
    """Prune weight matrices of FC/conv layers with a sparsifiable last
    dim (reference supported-layers check — embeddings, norms and biases
    are never pruned)."""
    if p.name in _excluded_layers:
        return False
    if not _supported_layer(layer):
        return False
    if p.ndim < 2:         # biases and norm scales
        return False
    return p.shape[-1] >= 4


def prune_model(model, n=2, m=4, mask_algo='mask_1d', with_mask=True):
    """Compute and apply n:m masks to every prunable parameter of
    `model`; returns {param_name: mask Tensor}."""
    if (n, m) != (2, 4):
        raise NotImplementedError("only 2:4 sparsity is supported")
    out = {}
    for layer in model.sublayers(include_self=True):
        for pname, p in layer.named_parameters(include_sublayers=False):
            if not _prunable(layer, p):
                continue
            w = np.asarray(p.numpy(), np.float32)
            mask = _best_2in4_mask(w)
            key = p.name or f"param_{id(p)}"
            _masks[key] = jnp.asarray(mask)
            p._set_data(p._value() * jnp.asarray(mask, p._value().dtype))
            out[key] = Tensor._wrap(jnp.asarray(mask))
    return out


class OptimizerWithSparsityGuarantee:
    """Wraps an optimizer so masks survive updates (reference
    `asp.py OptimizerWithSparsityGuarantee`)."""

    def __init__(self, optimizer):
        self._inner_opt = optimizer

    def step(self):
        self._inner_opt.step()
        for p in self._inner_opt._parameter_list or []:
            key = p.name or f"param_{id(p)}"
            mask = _masks.get(key)
            if mask is not None:
                arr = p._value()
                p._set_data(arr * mask.astype(arr.dtype))
                # keep the f32 master consistent too (AMP-O2)
                accs = self._inner_opt._accumulators.get(
                    self._inner_opt._param_key(p), {})
                mw = accs.get("master_weight")
                if mw is not None:
                    mw._set_data(mw._value()
                                 * mask.astype(mw._value().dtype))

    def clear_grad(self, *a, **k):
        return self._inner_opt.clear_grad(*a, **k)

    clear_gradients = clear_grad

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)

    def __getattr__(self, name):
        return getattr(self._inner_opt, name)


def decorate(optimizer):
    return OptimizerWithSparsityGuarantee(optimizer)
