"""paddle.hub — load models/entrypoints from a local hubconf.py (reference
`python/paddle/hub.py` → `python/paddle/hapi/hub.py`).

TPU build: the local-dir source is fully supported; github/gitee sources
need network egress and raise a clear error instead (this environment is
air-gapped, and the reference's download path is just a fetch in front of
the same hubconf protocol)."""
from __future__ import annotations

import importlib.util
import os

__all__ = ['list', 'help', 'load']

_HUB_CONF = "hubconf.py"
_cache = {}


def _load_hubconf(repo_dir, force_reload=False):
    path = os.path.join(repo_dir, _HUB_CONF)
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no {_HUB_CONF} in {repo_dir}")
    key = os.path.abspath(path)
    if not force_reload and key in _cache:
        return _cache[key]
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    _cache[key] = mod
    return mod


def _check_source(source):
    if source not in ("local", "github", "gitee"):
        raise ValueError(
            f"unknown source {source!r}: expected local/github/gitee")
    if source != "local":
        raise RuntimeError(
            "github/gitee hub sources need network access, which this "
            "TPU build does not have; clone the repo and use "
            "source='local'")


def list(repo_dir, source="local", force_reload=False):  # noqa: A001
    """List callable entrypoints defined by repo_dir/hubconf.py."""
    _check_source(source)
    mod = _load_hubconf(repo_dir, force_reload)
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):  # noqa: A001
    """Docstring of an entrypoint."""
    _check_source(source)
    mod = _load_hubconf(repo_dir, force_reload)
    if not hasattr(mod, model):
        raise RuntimeError(f"entrypoint {model!r} not found in hubconf")
    return getattr(mod, model).__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    """Call an entrypoint and return its result (usually a Layer)."""
    _check_source(source)
    mod = _load_hubconf(repo_dir, force_reload)
    if not hasattr(mod, model):
        raise RuntimeError(f"entrypoint {model!r} not found in hubconf")
    return getattr(mod, model)(**kwargs)
