"""paddle.amp — automatic mixed precision (reference: python/paddle/amp)."""
from .auto_cast import auto_cast, amp_guard, decorate, WHITE_LIST, BLACK_LIST
from .grad_scaler import GradScaler, AmpScaler

__all__ = ["auto_cast", "amp_guard", "decorate", "GradScaler", "AmpScaler"]
