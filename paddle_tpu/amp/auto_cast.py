"""AMP autocast: per-op dtype policy.

Reference parity: paddle.amp.auto_cast (python/paddle/amp/auto_cast.py:21)
with the op allow/deny lists of fluid/dygraph/amp/auto_cast.py and the C++
eager hook (eager/amp_auto_cast.h).

TPU-native design: the default low dtype is **bfloat16** — TPU MXUs eat
bf16 natively and its f32-range exponent makes loss scaling optional
(float16 honored for parity).  The policy is applied at op dispatch via the
`_amp_cast_hook` in core.dispatch (the same interception point the
reference generates into every dygraph function): white-list ops cast
inputs down (MXU-bound matmuls/convs), black-list ops cast up to f32
(softmax/norm/loss numerics), everything else runs in whatever dtype
arrives (O1).  O2 additionally casts params at decorate() time.
"""
from __future__ import annotations

import contextlib
from typing import Iterable, Optional, Set

import jax.numpy as jnp

from ..core import dispatch as dispatch_mod
from ..core import dtype as dtype_mod
from ..core.tensor import Tensor

# MXU-bound ops: cast to the low dtype (reference: white list
# fluid/dygraph/amp/auto_cast.py WHITE_LIST — matmul/conv/mul)
WHITE_LIST: Set[str] = {
    "matmul", "mm", "bmm", "mv", "linear", "einsum", "inner", "outer",
    "tensordot", "multi_dot",
    "conv1d", "conv2d", "conv3d",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose",
    "flash_attention",
    # embedding output sets the residual stream's dtype: bf16 keeps the
    # whole transformer block (LN included, see below) in bf16
    "embedding",
    # chunked TP-overlap forwards must cast like their GSPMD twins
    # (linear / embedding) so chunks>1 stays AMP-transparent
    "tp_overlap_column_linear", "tp_overlap_row_linear",
    "tp_overlap_vocab_embedding",
}

# numerically sensitive ops: force f32 (reference: BLACK_LIST —
# softmax/CE/norms/exp/log/pow...).  The norm family is NOT listed: our
# layer_norm/rms_norm/batch_norm kernels are dtype-preserving with f32
# internal statistics (TPU-native AMP), so f32 promotion would only
# force a full-f32 residual stream and cast traffic around every matmul.
BLACK_LIST: Set[str] = {
    "softmax", "log_softmax", "cross_entropy", "parallel_cross_entropy",
    "tp_overlap_cross_entropy",
    "bce_with_logits", "binary_cross_entropy", "nll_loss", "kl_div",
    "ctc_loss",
    "mean", "sum", "var", "std",
    "cumsum", "logcumsumexp", "prod", "square_error_cost",
}

_LOW = {"bfloat16": jnp.bfloat16, "float16": jnp.float16}


class AmpState:
    def __init__(self, enable: bool, dtype: str, level: str,
                 white: Set[str], black: Set[str]):
        self.enable = enable
        self.dtype = _LOW[dtype]
        self.level = level
        self.white = white
        self.black = black


_state: Optional[AmpState] = None


def amp_state() -> Optional[AmpState]:
    return _state


def _is_float(arr) -> bool:
    return arr is not None and hasattr(arr, "dtype") and \
        jnp.issubdtype(arr.dtype, jnp.floating)


def _cast_args(args, target):
    out = []
    for a in args:
        if isinstance(a, Tensor) and _is_float(a._value()) \
                and a._value().dtype != target:
            from ..ops._helpers import op as run_op
            out.append(run_op("cast", lambda x: x.astype(target), [a]))
        else:
            out.append(a)
    return out


def _hook(name: str, tensor_args):
    s = _state
    if s is None or not s.enable or name == "cast":
        # "cast" passes through or the hook's own casts would recurse
        return tensor_args
    if name in s.white:
        return _cast_args(tensor_args, s.dtype)
    if name in s.black:
        return _cast_args(tensor_args, jnp.float32)
    if s.level == "O2":
        # pure-low-precision: run gray ops in the low dtype too
        return _cast_args(tensor_args, s.dtype)
    return tensor_args


@contextlib.contextmanager
def auto_cast(enable: bool = True, custom_white_list: Optional[Iterable[str]] = None,
              custom_black_list: Optional[Iterable[str]] = None,
              level: str = "O1", dtype: str = "bfloat16"):
    """Context manager (reference: amp/auto_cast.py:21)."""
    global _state
    if level not in ("O0", "O1", "O2"):
        raise ValueError(f"level must be O0/O1/O2, got {level}")
    if dtype not in _LOW:
        raise ValueError(f"dtype must be bfloat16/float16, got {dtype}")
    cw, cb = set(custom_white_list or ()), set(custom_black_list or ())
    if cw & cb:
        raise ValueError(f"ops in both custom lists: {sorted(cw & cb)}")
    white = (set(WHITE_LIST) | cw) - cb
    black = (set(BLACK_LIST) | cb) - cw
    prev_state, prev_hook = _state, dispatch_mod._amp_cast_hook
    _state = AmpState(enable and level != "O0", dtype, level, white, black)
    dispatch_mod._amp_cast_hook = _hook
    try:
        yield
    finally:
        _state, dispatch_mod._amp_cast_hook = prev_state, prev_hook


amp_guard = auto_cast  # legacy alias (fluid/dygraph/amp/auto_cast.py)


def decorate(models, optimizers=None, level: str = "O2", dtype: str = "bfloat16",
             master_weight=None, save_dtype=None):
    """O2 model preparation (reference: amp/auto_cast.py:81 `decorate`):
    cast float params to the low dtype; optimizers keep f32 master state
    (our optimizer accumulators are f32 already — multi_precision default).
    """
    if level not in ("O0", "O1", "O2"):
        raise ValueError(f"level must be O0/O1/O2, got {level}")
    if level in ("O0", "O1"):
        return (models, optimizers) if optimizers is not None else models
    target = _LOW[dtype]
    model_list = models if isinstance(models, (list, tuple)) else [models]
    for m in model_list:
        for p in m.parameters():
            arr = p._value()
            if _is_float(arr) and arr.dtype == jnp.float32:
                p._set_data(arr.astype(target))
    if optimizers is not None:
        return models, optimizers
    return models
