"""Dynamic loss scaling.

Reference parity: paddle.amp.GradScaler (amp/grad_scaler.py:26) →
AmpScaler (fluid/dygraph/amp/loss_scaler.py:40) built on the
check_finite_and_unscale + update_loss_scaling ops.

TPU-native design: scaling is optional under bf16 (f32 exponent range) but
fully supported for f16 parity.  The skip-on-inf control flow is expressed
as `jnp.where` selects over persistent state tensors (scale / good & bad
step counters / param & accumulator snapshots), never python branches, so
one compiled train step handles both the apply and the skip path — the
exact role of the reference's update_loss_scaling op, which the executor
also runs unconditionally.

Sentry interplay (docs/RESILIENCE.md "Divergence sentry & rollback"): a
``found_inf`` overflow skip is ROUTINE dynamic-loss-scale behavior, not
a divergence — feed :attr:`found_inf` to
``DivergenceSentry.observe(..., found_inf=...)`` so a backoff neither
rolls training back nor perturbs the anomaly counters.  ``state_dict``
/ ``load_state_dict`` ride the ``pack_state`` ``@scaler`` entry
(ResilientLoop / hapi checkpoints and the memory snapshot ring), so a
post-rollback or post-relaunch AMP run resumes with the live loss
scale bitwise intact.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core import tensor as tensor_mod
from ..core.tensor import Tensor
from ..ops._helpers import op as run_op


class GradScaler:
    def __init__(self, enable: bool = True, init_loss_scaling: float = 2.0 ** 15,
                 incr_ratio: float = 2.0, decr_ratio: float = 0.5,
                 incr_every_n_steps: int = 1000,
                 decr_every_n_nan_or_inf: int = 2,
                 use_dynamic_loss_scaling: bool = True):
        self._enable = enable
        self._use_dynamic = use_dynamic_loss_scaling and enable
        self._incr_ratio = float(incr_ratio)
        self._decr_ratio = float(decr_ratio)
        self._incr_every_n_steps = int(incr_every_n_steps)
        self._decr_every_n_nan_or_inf = int(decr_every_n_nan_or_inf)
        self._scale_t = tensor_mod.external_tensor(
            jnp.float32(init_loss_scaling if enable else 1.0))
        self._good_t = tensor_mod.external_tensor(jnp.int32(0))
        self._bad_t = tensor_mod.external_tensor(jnp.int32(0))
        self._found_inf = None  # jax bool scalar from the last step()
        self._unscaled = False

    # -- public API (reference surface) ------------------------------------

    def is_enable(self) -> bool:
        return self._enable

    def is_use_dynamic_loss_scaling(self) -> bool:
        return self._use_dynamic

    def get_loss_scaling(self) -> float:
        return float(jax.device_get(self._scale_t._data))

    def set_init_loss_scaling(self, v: float):
        self._scale_t._data = jnp.float32(v)

    @property
    def found_inf(self):
        """The overflow latch from the last ``unscale_`` (a jax bool
        scalar, possibly traced; None before the first unscale or after
        ``update``).  Hand it to ``DivergenceSentry.observe`` so an AMP
        skip is classified as routine, never as an anomaly."""
        return self._found_inf

    @property
    def scale_tensor(self):
        """The live loss-scale state tensor — read it inside a compiled
        step (e.g. the sentry's per-step report lane) without a host
        pull."""
        return self._scale_t

    def scale(self, var: Tensor) -> Tensor:
        if not self._enable:
            return var
        scale_t = self._scale_t
        return run_op("amp_scale", lambda a, s: a * s.astype(a.dtype),
                      [var, scale_t])

    def unscale_(self, optimizer):
        """Divide grads by the scale and latch found_inf
        (reference: check_finite_and_unscale op)."""
        if not self._enable:
            self._found_inf = jnp.bool_(False)
            return
        if self._unscaled:
            raise RuntimeError(
                "unscale_() has already been called on this optimizer since "
                "the last update()")
        inv = 1.0 / self._scale_t._value().astype(jnp.float32)
        found = jnp.bool_(False)
        for p in optimizer._parameter_list or []:
            g = p.grad
            if g is None:
                continue
            garr = g._value()
            un = (garr.astype(jnp.float32) * inv).astype(garr.dtype)
            found = found | ~jnp.all(jnp.isfinite(un.astype(jnp.float32)))
            p.grad = un
        self._found_inf = found
        self._unscaled = True

    def step(self, optimizer):
        """unscale → snapshot → inner step → where-select rollback."""
        if not self._enable:
            optimizer.step()
            return
        if not self._unscaled:
            self.unscale_(optimizer)
        found = self._found_inf
        params = [p for p in (optimizer._parameter_list or [])
                  if getattr(p, "trainable", True)]
        old_params = {id(p): p._value() for p in params}
        old_accs = {}
        for key, accs in optimizer._accumulators.items():
            for name, t in accs.items():
                old_accs[(key, name)] = t._value()
        optimizer.step()
        for p in params:
            new = p._value()
            p._set_data(jnp.where(found, old_params[id(p)], new))
        for key, accs in optimizer._accumulators.items():
            for name, t in accs.items():
                new = t._value()
                if (key, name) in old_accs:
                    old = old_accs[(key, name)]
                else:
                    # accumulator born this step: roll back to its init
                    # (derived accumulators re-run their init thunk, e.g.
                    # master weights from the already-rolled-back param)
                    init = optimizer._acc_inits.get((key, name), 0.0)
                    if callable(init):
                        old = init()
                    else:
                        old = jnp.full(new.shape, init, new.dtype)
                t._set_data(jnp.where(found, old, new))
        self._unscaled = False

    def update(self):
        """Dynamic scale bookkeeping (reference: update_loss_scaling op)."""
        if not self._use_dynamic or self._found_inf is None:
            return
        found = self._found_inf
        good = self._good_t._value()
        bad = self._bad_t._value()
        scale = self._scale_t._value()
        good = jnp.where(found, 0, good + 1)
        bad = jnp.where(found, bad + 1, 0)
        decr = bad >= self._decr_every_n_nan_or_inf
        scale = jnp.where(decr, jnp.maximum(scale * self._decr_ratio, 1.0),
                          scale)
        bad = jnp.where(decr, 0, bad)
        incr = good >= self._incr_every_n_steps
        scale = jnp.where(incr, scale * self._incr_ratio, scale)
        good = jnp.where(incr, 0, good)
        self._good_t._set_data(good)
        self._bad_t._set_data(bad)
        self._scale_t._set_data(scale)
        self._found_inf = None

    def minimize(self, optimizer, scaled_loss, *args, **kwargs):
        """reference: scaler.minimize = step + update (backward already run
        by the caller on the scaled loss)."""
        self.step(optimizer)
        self.update()

    def state_dict(self):
        return {
            "scale": self._scale_t._data,
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every_n_steps,
            "decr_every_n_nan_or_inf": self._decr_every_n_nan_or_inf,
            "incr_count": self._good_t._data,
            "decr_count": self._bad_t._data,
            "use_dynamic_loss_scaling": self._use_dynamic,
        }

    def load_state_dict(self, sd):
        # leaves may arrive as framework Tensors (disk generation load),
        # jax/numpy arrays (memory snapshot ring), or python scalars —
        # all legal resume sources
        from ..core.tensor import _to_jax_array as _arr

        self._scale_t._data = jnp.float32(_arr(sd["scale"]))
        self._good_t._data = jnp.int32(_arr(sd.get("incr_count", 0)))
        self._bad_t._data = jnp.int32(_arr(sd.get("decr_count", 0)))
        self._incr_ratio = float(sd.get("incr_ratio", self._incr_ratio))
        self._decr_ratio = float(sd.get("decr_ratio", self._decr_ratio))
        self._incr_every_n_steps = int(
            sd.get("incr_every_n_steps", self._incr_every_n_steps))
        self._decr_every_n_nan_or_inf = int(
            sd.get("decr_every_n_nan_or_inf", self._decr_every_n_nan_or_inf))
        if "use_dynamic_loss_scaling" in sd:
            self._use_dynamic = bool(sd["use_dynamic_loss_scaling"]) and self._enable


AmpScaler = GradScaler  # legacy alias (fluid/dygraph/amp/loss_scaler.py:40)
