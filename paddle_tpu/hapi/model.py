"""paddle.Model — the Keras-like high-level API (reference:
python/paddle/hapi/model.py:915; fit at :1574).

TPU-native design: one adapter (no dynamic/static split — jax.jit *is* the
static path and is applied under ``Model.prepare(..., jit=True)`` or
``paddle.jit.to_static`` on the network).
"""
from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor, to_tensor
from ..metric import Metric
from . import callbacks as cbks_mod


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._loss = None
        self._optimizer = None
        self._metrics = []
        self.stop_training = False
        self._amp_level = None

    # -- configuration -----------------------------------------------------

    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is None:
            self._metrics = []
        elif isinstance(metrics, Metric):
            self._metrics = [metrics]
        else:
            self._metrics = list(metrics)
        if isinstance(amp_configs, str):
            self._amp_level = amp_configs
        elif isinstance(amp_configs, dict):
            self._amp_level = amp_configs.get("level", "O1")
        return self

    # -- single-batch paths --------------------------------------------------

    def _compute_loss(self, outputs, labels):
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        lbls = labels if isinstance(labels, (list, tuple)) else [labels]
        if callable(self._loss):
            return self._loss(*outs, *lbls)
        raise ValueError("Model.prepare(loss=...) required for training")

    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        ins = [x if isinstance(x, Tensor) else to_tensor(x) for x in ins]

        def _run():
            outputs = self.network(*ins)
            loss = self._compute_loss(outputs, labels)
            loss.backward()
            if update:
                self._optimizer.step()
                self._optimizer.clear_grad()
            return outputs, loss

        if self._amp_level in ("O1", "O2"):
            from .. import amp as amp_mod

            with amp_mod.auto_cast(level=self._amp_level):
                outputs = self.network(*ins)
            loss = self._compute_loss(outputs, labels)
            loss.backward()
            if update:
                self._optimizer.step()
                self._optimizer.clear_grad()
        else:
            outputs, loss = _run()
        metrics = [float(np.asarray(loss.numpy()))]
        for m in self._metrics:
            pre = m.compute(outputs if not isinstance(outputs, (list, tuple))
                            else outputs[0],
                            labels if not isinstance(labels, (list, tuple))
                            else labels[0])
            if isinstance(pre, tuple):
                m.update(*pre)
            else:
                m.update(pre)
        return metrics

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        ins = [x if isinstance(x, Tensor) else to_tensor(x) for x in ins]
        from ..core.autograd import no_grad

        with no_grad():
            outputs = self.network(*ins)
            loss_val = None
            if self._loss is not None and labels is not None:
                loss_val = float(np.asarray(
                    self._compute_loss(outputs, labels).numpy()))
        for m in self._metrics:
            pre = m.compute(outputs if not isinstance(outputs, (list, tuple))
                            else outputs[0],
                            labels if not isinstance(labels, (list, tuple))
                            else labels[0])
            if isinstance(pre, tuple):
                m.update(*pre)
            else:
                m.update(pre)
        return [loss_val] if loss_val is not None else []

    def predict_batch(self, inputs):
        """Run one inference batch; returns a LIST of numpy arrays, one
        per network output (reference `hapi/model.py:811-820`
        predict_batch returns `[to_numpy(o) for o in to_list(outputs)]`
        — a list even for a single output)."""
        self.network.eval()
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        ins = [x if isinstance(x, Tensor) else to_tensor(x) for x in ins]
        from ..core.autograd import no_grad

        with no_grad():
            out = self.network(*ins)
        outs = out if isinstance(out, (list, tuple)) else [out]
        return [np.asarray(o.numpy()) if isinstance(o, Tensor)
                else np.asarray(o) for o in outs]

    # -- loops ----------------------------------------------------------------

    @staticmethod
    def _unpack(batch):
        if isinstance(batch, (list, tuple)):
            if len(batch) >= 2:
                return batch[0], batch[1]
            return batch[0], None
        return batch, None

    def _make_loader(self, data, batch_size, shuffle):
        from ..io import DataLoader, Dataset

        if data is None:
            return None
        if isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle)

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None, save_steps=None,
            keep_last=3, resume=False):
        train_loader = self._make_loader(train_data, batch_size, shuffle)
        eval_loader = self._make_loader(eval_data, batch_size, False)

        self._resumed_step = 0
        if save_dir and resume:
            self._resumed_step = self.resume_from(
                cbks_mod.ModelCheckpoint.steps_root(save_dir))
        cbs = [cbks_mod.ProgBarLogger(log_freq, verbose=verbose)]
        if save_dir:
            cbs.append(cbks_mod.ModelCheckpoint(save_freq, save_dir,
                                                save_steps=save_steps,
                                                keep_last=keep_last))
        if callbacks:
            cbs.extend(callbacks)
        cbk_list = cbks_mod.CallbackList(cbs)
        cbk_list.set_model(self)
        try:
            steps = len(train_loader)
        except TypeError:
            steps = None
        cbk_list.set_params({
            "epochs": epochs, "steps": steps, "verbose": verbose,
            "batch_size": batch_size, "metrics": self._metrics_name(),
        })
        self.stop_training = False
        cbk_list.on_train_begin()
        for epoch in range(epochs):
            if self.stop_training:
                break
            cbk_list.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            step_count = 0
            for step, batch in enumerate(train_loader):
                cbk_list.on_train_batch_begin(step)
                x, y = self._unpack(batch)
                update = ((step + 1) % accumulate_grad_batches == 0)
                outs = self.train_batch(x, y, update=update)
                logs = {"loss": outs[0]}
                for m in self._metrics:
                    logs[_name_str(m.name())] = _fmt_metric(m.accumulate())
                cbk_list.on_train_batch_end(step, logs)
                step_count += 1
                if num_iters is not None and step_count >= num_iters:
                    break
            cbk_list.on_epoch_end(epoch, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, batch_size=batch_size,
                                          verbose=0, _callbacks=cbk_list)
        cbk_list.on_train_end()
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None,
                 _callbacks=None):
        loader = self._make_loader(eval_data, batch_size, False)
        cbk_list = _callbacks or cbks_mod.CallbackList(
            [cbks_mod.ProgBarLogger(log_freq, verbose=verbose)])
        if _callbacks is None:
            cbk_list.set_model(self)
            cbk_list.set_params({"verbose": verbose})
        for m in self._metrics:
            m.reset()
        cbk_list.on_eval_begin()
        losses = []
        for step, batch in enumerate(loader):
            cbk_list.on_eval_batch_begin(step)
            x, y = self._unpack(batch)
            outs = self.eval_batch(x, y)
            if outs:
                losses.append(outs[0])
            cbk_list.on_eval_batch_end(step)
        logs = {}
        if losses:
            logs["loss"] = float(np.mean(losses))
        for m in self._metrics:
            logs[_name_str(m.name())] = _fmt_metric(m.accumulate())
        cbk_list.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        """Reference contract (`hapi/model.py:2005-2017`): returns a list
        with ONE entry per network output; each entry is the list of
        per-batch arrays, or one vstacked array when ``stack_outputs``."""
        loader = self._make_loader(test_data, batch_size, False)
        outputs = []
        for batch in loader:
            x, _ = self._unpack(batch)
            outputs.append(self.predict_batch(x))
        outputs = [list(outs) for outs in zip(*outputs)]   # [output][batch]
        if stack_outputs:
            outputs = [np.vstack(outs) for outs in outputs]
        return outputs

    # -- fault tolerance -------------------------------------------------------

    def _ft_user_state(self):
        state = {"model": self.network.state_dict()}
        if self._optimizer is not None:
            state["opt"] = self._optimizer.state_dict()
        return state

    def _ft_restore(self, user_state):
        self.network.set_state_dict(user_state["model"])
        if self._optimizer is not None and "opt" in user_state:
            self._optimizer.set_state_dict(user_state["opt"])

    def _ft_state_dict(self, step):
        """Generation payload via the shared ResilientLoop schema, so
        fit-produced step checkpoints and ResilientLoop ones share one
        resume contract (docs/RESILIENCE.md)."""
        from ..distributed.fault_tolerance import pack_state

        return pack_state(self._ft_user_state(), step)

    def resume_from(self, ckpt_root):
        """Restore params/optimizer/RNG from the newest VALID step
        generation under ``ckpt_root`` (corrupt/torn generations are
        skipped).  Returns the restored global step (0 = fresh start).

        Note: fit-level resume restores state and continues generation
        numbering; it does not fast-forward the data iterator to the
        exact batch — for bitwise step-exact resume drive training with
        ``distributed.fault_tolerance.ResilientLoop``.
        """
        from ..distributed.fault_tolerance import ResilientLoop

        loop = ResilientLoop(ckpt_root, state_fn=self._ft_user_state,
                             restore_fn=self._ft_restore, verbose=False)
        return loop.resume()

    # -- persistence -----------------------------------------------------------

    def save(self, path, training=True):
        from ..framework.io import save as _save

        _save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            _save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as _load

        self.network.set_state_dict(_load(path + ".pdparams"))
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(opt_path):
            self._optimizer.set_state_dict(_load(opt_path))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .summary import summary as _summary

        return _summary(self.network, input_size, dtypes=dtype)

    def _metrics_name(self):
        names = ["loss"]
        for m in self._metrics:
            n = m.name()
            names.extend(n if isinstance(n, (list, tuple)) else [n])
        return names


def _name_str(n):
    return n if isinstance(n, str) else n[0]


def _fmt_metric(v):
    if isinstance(v, (list, tuple)):
        return float(v[0])
    return float(v)
