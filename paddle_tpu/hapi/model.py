"""paddle.Model — the Keras-like high-level API (reference:
python/paddle/hapi/model.py:915; fit at :1574).

TPU-native design: one adapter (no dynamic/static split — jax.jit *is* the
static path and is applied under ``Model.prepare(..., jit=True)`` or
``paddle.jit.to_static`` on the network).
"""
from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor, to_tensor
from ..metric import Metric
from ..obs.train import NULL_TIMELINE, resolve_timeline
from . import callbacks as cbks_mod


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._loss = None
        self._optimizer = None
        self._metrics = []
        self.stop_training = False
        self._amp_level = None
        self._scaler = None
        #: the report train_batch pulled for its sentry (one poll per
        #: batch); fit's rollback policy reads it instead of polling a
        #: second time
        self._last_sentry_report = None
        # fit-level observatory surface (profiler.train_stats): live
        # objects during fit, sentry frozen to bare counters after
        # (holding the sentry would pin its snapshot ring)
        self._fit_timeline = None
        self._fit_sentry = None
        self._fit_sentry_counters = None
        self._obs_registered = False

    # -- configuration -----------------------------------------------------

    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is None:
            self._metrics = []
        elif isinstance(metrics, Metric):
            self._metrics = [metrics]
        else:
            self._metrics = list(metrics)
        if isinstance(amp_configs, str):
            self._amp_level = amp_configs
        elif isinstance(amp_configs, dict):
            self._amp_level = amp_configs.get("level", "O1")
            # reference amp_configs carries loss-scaling knobs; here a
            # prepared GradScaler rides along so fit's AMP path uses
            # dynamic loss scaling AND its state joins every checkpoint
            # tier (docs/RESILIENCE.md "Divergence sentry & rollback")
            self._scaler = amp_configs.get("scaler", self._scaler)
        return self

    # -- single-batch paths --------------------------------------------------

    def _compute_loss(self, outputs, labels):
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        lbls = labels if isinstance(labels, (list, tuple)) else [labels]
        if callable(self._loss):
            return self._loss(*outs, *lbls)
        raise ValueError("Model.prepare(loss=...) required for training")

    def train_batch(self, inputs, labels=None, update=True, sentry=None,
                    timeline=None):
        timeline = timeline if timeline is not None else NULL_TIMELINE
        self.network.train()
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        ins = [x if isinstance(x, Tensor) else to_tensor(x) for x in ins]
        scaler = self._scaler if (self._scaler is not None
                                  and self._scaler.is_enable()) else None

        def _observe(loss, grads_ready, found_inf=None):
            # in-graph sentry latch: runs between backward and the
            # optimizer step so the grad norm is the raw global norm,
            # and an AMP found_inf skip is classified as routine
            if sentry is None:
                return
            grad_norm = None
            if grads_ready and self._optimizer is not None:
                from ..distributed.fault_tolerance import global_grad_norm

                grad_norm = global_grad_norm(
                    self._optimizer._parameter_list or [])
            sentry.observe(loss, grad_norm=grad_norm, found_inf=found_inf,
                           scale=None if scaler is None
                           else scaler.scale_tensor)

        def _run():
            outputs = self.network(*ins)
            loss = self._compute_loss(outputs, labels)
            loss.backward()
            _observe(loss, grads_ready=update)
            if update:
                self._optimizer.step()
                self._optimizer.clear_grad()
            return outputs, loss

        # step_dispatch covers building + dispatching the (possibly
        # compiled) step — everything up to the first host pull; the
        # device_wait phases below time the pulls themselves, so a
        # timeline separates "host built the step" from "host waited
        # on the device" per batch
        with timeline.phase("step_dispatch"):
            if self._amp_level in ("O1", "O2"):
                from .. import amp as amp_mod

                with amp_mod.auto_cast(level=self._amp_level):
                    outputs = self.network(*ins)
                loss = self._compute_loss(outputs, labels)
                if scaler is not None:
                    scaler.scale(loss).backward()
                    if update:
                        scaler.unscale_(self._optimizer)
                        _observe(loss, grads_ready=True,
                                 found_inf=scaler.found_inf)
                        scaler.step(self._optimizer)
                        scaler.update()
                        self._optimizer.clear_grad()
                    else:
                        _observe(loss, grads_ready=False)
                else:
                    loss.backward()
                    _observe(loss, grads_ready=update)
                    if update:
                        self._optimizer.step()
                        self._optimizer.clear_grad()
            else:
                outputs, loss = _run()
        self._last_sentry_report = None
        if sentry is not None:
            # poll HERE (still the one pull per batch — fit reads
            # _last_sentry_report instead of polling again) so an
            # anomalous batch never reaches the metric accumulators:
            # a rolled-back batch must leave no trace in them either
            with timeline.phase("device_wait"):
                self._last_sentry_report = sentry.poll()
            if self._last_sentry_report.anomalous:
                # the polled report already holds the loss host-side —
                # no second device pull on the rollback path
                return [self._last_sentry_report.loss]
        with timeline.phase("device_wait"):
            metrics = [float(np.asarray(loss.numpy()))]
        for m in self._metrics:
            pre = m.compute(outputs if not isinstance(outputs, (list, tuple))
                            else outputs[0],
                            labels if not isinstance(labels, (list, tuple))
                            else labels[0])
            if isinstance(pre, tuple):
                m.update(*pre)
            else:
                m.update(pre)
        return metrics

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        ins = [x if isinstance(x, Tensor) else to_tensor(x) for x in ins]
        from ..core.autograd import no_grad

        with no_grad():
            outputs = self.network(*ins)
            loss_val = None
            if self._loss is not None and labels is not None:
                loss_val = float(np.asarray(
                    self._compute_loss(outputs, labels).numpy()))
        for m in self._metrics:
            pre = m.compute(outputs if not isinstance(outputs, (list, tuple))
                            else outputs[0],
                            labels if not isinstance(labels, (list, tuple))
                            else labels[0])
            if isinstance(pre, tuple):
                m.update(*pre)
            else:
                m.update(pre)
        return [loss_val] if loss_val is not None else []

    def predict_batch(self, inputs):
        """Run one inference batch; returns a LIST of numpy arrays, one
        per network output (reference `hapi/model.py:811-820`
        predict_batch returns `[to_numpy(o) for o in to_list(outputs)]`
        — a list even for a single output)."""
        self.network.eval()
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        ins = [x if isinstance(x, Tensor) else to_tensor(x) for x in ins]
        from ..core.autograd import no_grad

        with no_grad():
            out = self.network(*ins)
        outs = out if isinstance(out, (list, tuple)) else [out]
        return [np.asarray(o.numpy()) if isinstance(o, Tensor)
                else np.asarray(o) for o in outs]

    # -- loops ----------------------------------------------------------------

    @staticmethod
    def _unpack(batch):
        if isinstance(batch, (list, tuple)):
            if len(batch) >= 2:
                return batch[0], batch[1]
            return batch[0], None
        return batch, None

    def _make_loader(self, data, batch_size, shuffle):
        from ..io import DataLoader, Dataset

        if data is None:
            return None
        if isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle)

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None, save_steps=None,
            keep_last=3, resume=False, sentry=None, timeline=None):
        """Train the prepared model (reference `hapi/model.py:1574`).

        ``timeline`` (an ``obs.StepTimeline``) arms the training step
        observatory: one span per batch attempt with ``data_fetch`` /
        ``step_dispatch`` / ``device_wait`` / ``snapshot_capture``
        phases, sentry rollbacks ended ``rolled_back`` and linked to
        the batch that resumed — export with ``obs.chrome_trace`` /
        ``obs.jsonl_lines`` and certify with ``obs.validate_timeline``.
        Pure host-side timing: no new compile keys, no device pulls
        (defaults to the no-op ``NULL_TIMELINE``; or set
        ``PADDLE_TPU_TRAIN_TRACE=1``).

        ``sentry`` (a ``distributed.fault_tolerance.DivergenceSentry``)
        arms divergence rollback: each batch is checked by the in-graph
        anomaly latch (one small host pull); on anomaly fit restores the
        newest memory snapshot (weights, optimizer, RNG, GradScaler) and
        continues with the NEXT batch — the offending window is skipped,
        not replayed (fit's loaders are not step-replayable; drive
        training with ``ResilientLoop`` for bitwise replay semantics).
        After ``max_rollbacks`` consecutive failures a
        ``SentryEscalation`` fail-stops the fit with the flight-recorder
        dump attached and any ``save_dir`` checkpoints intact.
        """
        train_loader = self._make_loader(train_data, batch_size, shuffle)
        eval_loader = self._make_loader(eval_data, batch_size, False)

        self._resumed_step = 0
        if save_dir and resume:
            self._resumed_step = self.resume_from(
                cbks_mod.ModelCheckpoint.steps_root(save_dir))
        cbs = [cbks_mod.ProgBarLogger(log_freq, verbose=verbose)]
        if save_dir:
            cbs.append(cbks_mod.ModelCheckpoint(save_freq, save_dir,
                                                save_steps=save_steps,
                                                keep_last=keep_last))
        if callbacks:
            cbs.extend(callbacks)
        cbk_list = cbks_mod.CallbackList(cbs)
        cbk_list.set_model(self)
        try:
            steps = len(train_loader)
        except TypeError:
            steps = None
        cbk_list.set_params({
            "epochs": epochs, "steps": steps, "verbose": verbose,
            "batch_size": batch_size, "metrics": self._metrics_name(),
        })
        # same arming contract as ResilientLoop: explicit timeline=,
        # else PADDLE_TPU_TRAIN_TRACE=1, else the no-op
        tl = resolve_timeline(timeline)
        # an armed fit joins profiler.train_stats() / the metrics
        # exposition like a ResilientLoop does (register once per
        # Model; the snapshot reads whatever the LAST ARMED fit set —
        # a later unarmed fit must not wipe it mid-scrape).  A sentry
        # alone is enough to register: its rollback counters must be
        # scrapable even when step timing is off
        if tl.enabled or sentry is not None:
            self._fit_timeline = tl if tl.enabled else None
            self._fit_sentry = sentry
            if not self._obs_registered:
                from .. import profiler as _profiler

                _profiler._register_train_stats(self)
                self._obs_registered = True
        flight = None
        gstep = int(self._resumed_step or 0)
        if sentry is not None:
            from ..obs.flight import FlightRecorder

            flight = FlightRecorder(name="training")
            # seed a rollback target (a background snapshot_capture
            # phase — no batch attempt is open yet)
            self._sentry_snapshot(sentry, gstep, timeline=tl)
        self.stop_training = False
        cbk_list.on_train_begin()
        try:
            self._fit_epochs(epochs, train_loader, eval_loader, cbk_list,
                             sentry, tl, flight, gstep, batch_size,
                             eval_freq, accumulate_grad_batches,
                             num_iters)
        finally:
            # the scrape surface only needs the sentry's COUNTERS; a
            # live reference would pin its snapshot ring (several full
            # model+optimizer state copies) for the Model's lifetime
            if sentry is not None and self._fit_sentry is sentry:
                self._fit_sentry = None
                self._fit_sentry_counters = dict(sentry.counters())
        cbk_list.on_train_end()
        return self

    def _fit_epochs(self, epochs, train_loader, eval_loader, cbk_list,
                    sentry, tl, flight, gstep, batch_size, eval_freq,
                    accumulate_grad_batches, num_iters):
        for epoch in range(epochs):
            if self.stop_training:
                break
            cbk_list.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            step_count = 0
            batches = enumerate(train_loader)
            while True:
                # one span per batch attempt; the fetch itself is the
                # data_fetch phase (a starved input pipeline becomes
                # visible as exactly that)
                tl.begin_step(gstep)
                with tl.phase("data_fetch"):
                    try:
                        step, batch = next(batches)
                    except StopIteration:
                        tl.abandon_step()   # nothing ran this attempt
                        break
                if sentry is not None and sentry.should_skip(gstep):
                    # skip only bypasses the batch itself: the boundary
                    # still flows through the flight ring and the
                    # snapshot cadence (a cadence landing exactly on a
                    # skipped step must not shrink the rollback window)
                    sentry.note_skip(gstep)
                    tl.on_skip(gstep)
                    flight.record(step=gstep, skipped=1)
                    gstep += 1
                    if gstep % sentry.snapshot_every == 0:
                        self._sentry_snapshot(sentry, gstep, timeline=tl)
                    tl.end_step("skipped")
                    step_count += 1
                    if num_iters is not None and step_count >= num_iters:
                        break
                    continue
                cbk_list.on_train_batch_begin(step)
                x, y = self._unpack(batch)
                update = ((step + 1) % accumulate_grad_batches == 0)
                outs = self.train_batch(x, y, update=update, sentry=sentry,
                                        timeline=tl)
                if sentry is not None:
                    report = self._last_sentry_report
                    flight.record(step=gstep, anomaly=report.code,
                                  loss=report.loss,
                                  grad_norm=report.grad_norm,
                                  scale=report.scale)
                    if report.anomalous:
                        self._sentry_rollback(sentry, gstep, report,
                                              cbk_list, flight,
                                              timeline=tl)
                        gstep += 1
                        step_count += 1
                        if num_iters is not None \
                                and step_count >= num_iters:
                            break
                        continue
                    sentry.note_clean(gstep)
                logs = {"loss": outs[0]}
                for m in self._metrics:
                    logs[_name_str(m.name())] = _fmt_metric(m.accumulate())
                cbk_list.on_train_batch_end(step, logs)
                gstep += 1
                if sentry is not None \
                        and gstep % sentry.snapshot_every == 0:
                    self._sentry_snapshot(sentry, gstep, timeline=tl)
                tl.end_step("completed")
                step_count += 1
                if num_iters is not None and step_count >= num_iters:
                    break
            cbk_list.on_epoch_end(epoch, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_loader, batch_size=batch_size,
                              verbose=0, _callbacks=cbk_list)

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None,
                 _callbacks=None):
        loader = self._make_loader(eval_data, batch_size, False)
        cbk_list = _callbacks or cbks_mod.CallbackList(
            [cbks_mod.ProgBarLogger(log_freq, verbose=verbose)])
        if _callbacks is None:
            cbk_list.set_model(self)
            cbk_list.set_params({"verbose": verbose})
        for m in self._metrics:
            m.reset()
        cbk_list.on_eval_begin()
        losses = []
        for step, batch in enumerate(loader):
            cbk_list.on_eval_batch_begin(step)
            x, y = self._unpack(batch)
            outs = self.eval_batch(x, y)
            if outs:
                losses.append(outs[0])
            cbk_list.on_eval_batch_end(step)
        logs = {}
        if losses:
            logs["loss"] = float(np.mean(losses))
        for m in self._metrics:
            logs[_name_str(m.name())] = _fmt_metric(m.accumulate())
        cbk_list.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        """Reference contract (`hapi/model.py:2005-2017`): returns a list
        with ONE entry per network output; each entry is the list of
        per-batch arrays, or one vstacked array when ``stack_outputs``."""
        loader = self._make_loader(test_data, batch_size, False)
        outputs = []
        for batch in loader:
            x, _ = self._unpack(batch)
            outputs.append(self.predict_batch(x))
        outputs = [list(outs) for outs in zip(*outputs)]   # [output][batch]
        if stack_outputs:
            outputs = [np.vstack(outs) for outs in outputs]
        return outputs

    # -- fault tolerance -------------------------------------------------------

    def _ft_user_state(self):
        state = {"model": self.network.state_dict()}
        if self._optimizer is not None:
            state["opt"] = self._optimizer.state_dict()
        return state

    def _ft_restore(self, user_state):
        self.network.set_state_dict(user_state["model"])
        if self._optimizer is not None and "opt" in user_state:
            self._optimizer.set_state_dict(user_state["opt"])

    def _ft_state_dict(self, step):
        """Generation payload via the shared ResilientLoop schema
        (including the AMP GradScaler when one is prepared), so
        fit-produced step checkpoints, ResilientLoop ones, and memory-
        ring snapshots share one resume contract (docs/RESILIENCE.md)."""
        from ..distributed.fault_tolerance import pack_state

        return pack_state(self._ft_user_state(), step,
                          scaler=self._scaler)

    def resume_from(self, ckpt_root):
        """Restore params/optimizer/RNG (and GradScaler state, when one
        is prepared) from the newest VALID step generation under
        ``ckpt_root`` (corrupt/torn generations are skipped).  Returns
        the restored global step (0 = fresh start).

        Note: fit-level resume restores state and continues generation
        numbering; it does not fast-forward the data iterator to the
        exact batch — for bitwise step-exact resume drive training with
        ``distributed.fault_tolerance.ResilientLoop``.
        """
        from ..distributed.fault_tolerance import ResilientLoop

        loop = ResilientLoop(ckpt_root, state_fn=self._ft_user_state,
                             restore_fn=self._ft_restore, verbose=False,
                             scaler=self._scaler)
        return loop.resume()

    # -- divergence sentry (fit-level policy) ----------------------------------

    def train_stats(self) -> dict:
        """The fit-level observatory snapshot (armed by
        ``fit(timeline=...)`` / ``PADDLE_TPU_TRAIN_TRACE=1``), surfaced
        through ``profiler.train_stats()`` alongside ResilientLoop
        runs."""
        out = {"name": "fit"}
        if self._fit_timeline is not None:
            out["timeline"] = self._fit_timeline.counters()
        if self._fit_sentry is not None:          # live (mid-fit)
            out["sentry"] = self._fit_sentry.counters()
        elif self._fit_sentry_counters is not None:   # frozen post-fit
            out["sentry"] = self._fit_sentry_counters
        return out

    def _sentry_snapshot(self, sentry, gstep, timeline=None):
        with (timeline or NULL_TIMELINE).phase("snapshot_capture"):
            state = self._ft_state_dict(gstep)
            state["@sentry"] = sentry.state_dict()
            sentry.ring.take(state)

    def _sentry_rollback(self, sentry, gstep, report, cbk_list, flight,
                         timeline=None):
        """Fit-level anomaly policy: restore the newest ring snapshot
        and move on to the next batch (the offending window is skipped,
        never replayed); escalate after ``max_rollbacks`` consecutive
        failures with the flight ring frozen onto the exception."""
        from ..distributed.fault_tolerance import (
            SentryEscalation, restore_packed_state)

        tl = timeline or NULL_TIMELINE
        action = sentry.note_anomaly(gstep, report)
        if action == "escalate":
            # leave the live model restored to the newest good snapshot
            # (not the poisoned weights) before fail-stopping, same as
            # ResilientLoop._escalate
            snap = sentry.ring.newest()
            if snap is not None:
                with tl.phase("rollback_restore"):
                    restore_packed_state(snap, self._ft_restore,
                                         scaler=self._scaler,
                                         sentry=sentry)
            dump = flight.dump("sentry_escalation")
            tl.on_escalate(gstep)
            raise SentryEscalation(
                f"divergence sentry escalated at fit step {gstep} "
                f"(anomaly {report.flags() or report.code}; "
                f"{sentry.max_rollbacks} consecutive rollbacks exhausted)",
                step=gstep, report=report, flight_dump=dump)
        snap = sentry.ring.newest()
        with tl.phase("rollback_restore"):
            restore_packed_state(snap, self._ft_restore,
                                 scaler=self._scaler, sentry=sentry)
        if self._optimizer is not None:
            # grads accumulated from the poisoned batch (including a
            # non-update micro-batch under accumulate_grad_batches)
            # are NOT part of the snapshot — clear them, or the NaN
            # keeps contaminating every later accumulation window
            self._optimizer.clear_grad()
        sentry.rollbacks += 1
        # the rollback ends this batch's attempt span (fit skips
        # forward, so there is no replay target step to point at — the
        # resume link lands on the next batch attempt)
        tl.on_rollback(gstep, code=report.code)
        # on_rollback IS the terminal event for this batch: the matching
        # on_train_batch_end deliberately does not fire (the batch's
        # effects were rolled back — per-batch-end hooks like LR
        # stepping must not run for it)
        cbk_list.on_rollback(gstep, report)

    # -- persistence -----------------------------------------------------------

    def save(self, path, training=True):
        from ..framework.io import save as _save

        _save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            _save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as _load

        self.network.set_state_dict(_load(path + ".pdparams"))
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(opt_path):
            self._optimizer.set_state_dict(_load(opt_path))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .summary import summary as _summary

        return _summary(self.network, input_size, dtypes=dtype)

    def _metrics_name(self):
        names = ["loss"]
        for m in self._metrics:
            n = m.name()
            names.extend(n if isinstance(n, (list, tuple)) else [n])
        return names


def _name_str(n):
    return n if isinstance(n, str) else n[0]


def _fmt_metric(v):
    if isinstance(v, (list, tuple)):
        return float(v[0])
    return float(v)
