"""paddle.summary (reference: python/paddle/hapi/model_summary.py)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import to_tensor
from ..nn.layer_base import Layer


def summary(net: Layer, input_size=None, dtypes=None, input=None):
    """Print a layer table and return {'total_params', 'trainable_params'}."""
    rows = []
    hooks = []

    def _hook(layer, inputs, outputs):
        n_params = sum(p.size for p in layer._parameters.values() if p is not None)
        out = outputs[0] if isinstance(outputs, (list, tuple)) else outputs
        try:
            shape = list(out.shape)
        except Exception:
            shape = "?"
        rows.append((type(layer).__name__, shape, n_params))

    for l in net.sublayers(include_self=False):
        hooks.append(l.register_forward_post_hook(_hook))

    if input is None and input_size is not None:
        sizes = input_size if isinstance(input_size, list) and \
            isinstance(input_size[0], (list, tuple)) else [input_size]
        dts = dtypes if isinstance(dtypes, (list, tuple)) else \
            [dtypes or "float32"] * len(sizes)
        input = [to_tensor(np.zeros(s, dtype=np.dtype(d or "float32")))
                 for s, d in zip(sizes, dts)]
    if input is not None:
        ins = input if isinstance(input, (list, tuple)) else [input]
        was_training = net.training
        net.eval()
        net(*ins)
        if was_training:
            net.train()
    for h in hooks:
        h.remove()

    total = sum(p.size for p in net.parameters())
    trainable = sum(p.size for p in net.parameters() if p.trainable)

    header = f"{'Layer (type)':<28}{'Output Shape':<24}{'Param #':>10}"
    lines = ["-" * len(header), header, "=" * len(header)]
    for name, shape, n in rows:
        lines.append(f"{name:<28}{str(shape):<24}{n:>10}")
    lines += ["=" * len(header),
              f"Total params: {total:,}",
              f"Trainable params: {trainable:,}",
              f"Non-trainable params: {total - trainable:,}",
              "-" * len(header)]
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}
