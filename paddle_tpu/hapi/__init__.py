"""hapi: high-level Model API (reference: python/paddle/hapi)."""
from .model import Model
from . import callbacks
from .summary import summary
