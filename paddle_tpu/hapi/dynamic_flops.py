"""paddle.flops — per-layer FLOP accounting via forward hooks (reference
`python/paddle/hapi/dynamic_flops.py:25`).

Counts multiply-accumulates as 1 FLOP (the reference's convention) for the
standard layer set; `custom_ops` maps Layer subclasses to
`fn(layer, input, output) -> flops` overrides."""
from __future__ import annotations

import numpy as np

from ..nn import layer_base


def _numel(shape):
    n = 1
    for s in shape:
        n *= int(s)
    return n


def _count_linear(layer, x, y):
    return _numel(x.shape) // x.shape[-1] * int(layer.weight.shape[0]) \
        * int(layer.weight.shape[1])


def _count_conv(layer, x, y):
    w = layer.weight
    kernel = _numel(w.shape[1:])             # cin/groups * kh * kw
    return _numel(y.shape) * kernel


def _count_norm(layer, x, y):
    return 2 * _numel(x.shape)


def _count_act(layer, x, y):
    return _numel(x.shape)


def _count_pool(layer, x, y):
    return _numel(y.shape)


def _count_embedding(layer, x, y):
    return 0


def _default_table():
    from .. import nn

    table = {}
    for name, fn in [
        ("Linear", _count_linear),
        ("Conv1D", _count_conv), ("Conv2D", _count_conv),
        ("Conv3D", _count_conv),
        ("Conv1DTranspose", _count_conv), ("Conv2DTranspose", _count_conv),
        ("BatchNorm", _count_norm), ("BatchNorm1D", _count_norm),
        ("BatchNorm2D", _count_norm), ("BatchNorm3D", _count_norm),
        ("LayerNorm", _count_norm), ("GroupNorm", _count_norm),
        ("ReLU", _count_act), ("GELU", _count_act), ("Sigmoid", _count_act),
        ("Tanh", _count_act), ("Softmax", _count_act),
        ("AvgPool1D", _count_pool), ("AvgPool2D", _count_pool),
        ("AvgPool3D", _count_pool), ("MaxPool1D", _count_pool),
        ("MaxPool2D", _count_pool), ("MaxPool3D", _count_pool),
        ("AdaptiveAvgPool2D", _count_pool),
        ("Embedding", _count_embedding),
    ]:
        cls = getattr(nn, name, None)
        if cls is not None:
            table[cls] = fn
    return table


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Total forward FLOPs of `net` on a zero tensor of `input_size`."""
    from ..core.tensor import Tensor
    import jax.numpy as jnp

    table = _default_table()
    if custom_ops:
        table.update(custom_ops)

    counts = {}
    handles = []

    def make_hook(layer, fn):
        def hook(lyr, inp, out):
            x = inp[0] if isinstance(inp, (tuple, list)) else inp
            y = out[0] if isinstance(out, (tuple, list)) else out
            counts[id(lyr)] = counts.get(id(lyr), 0) + int(fn(lyr, x, y))

        return hook

    for lyr in net.sublayers(include_self=True):
        fn = table.get(type(lyr))
        if fn is not None:
            handles.append(lyr.register_forward_post_hook(
                make_hook(lyr, fn)))

    was_training = net.training
    net.eval()
    try:
        x = Tensor._wrap(jnp.zeros(tuple(input_size), jnp.float32))
        net(x)
    finally:
        for h in handles:
            h.remove()
        if was_training:
            net.train()

    total = sum(counts.values())
    if print_detail:
        for lyr in net.sublayers(include_self=True):
            if id(lyr) in counts:
                print(f"{type(lyr).__name__:24s} {counts[id(lyr)]:>14,d}")
        print(f"{'Total':24s} {total:>14,d}")
    return total
