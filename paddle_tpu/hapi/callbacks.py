"""hapi callbacks (reference: python/paddle/hapi/callbacks.py)."""
from __future__ import annotations

import os
import time

import numpy as np


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass

    def on_predict_batch_begin(self, step, logs=None):
        pass

    def on_predict_batch_end(self, step, logs=None):
        pass

    def on_rollback(self, step, report=None):
        """Divergence-sentry rollback: training state was just restored
        from a memory snapshot and global step ``step`` was blocklisted
        (``fit(sentry=...)``, docs/RESILIENCE.md).  ``report`` is the
        triggering ``SentryReport``.  This REPLACES
        ``on_train_batch_end`` for the rolled-back batch: its effects
        were undone, so per-batch-end hooks (LR stepping, counters)
        must not run for it — an ``on_train_batch_begin`` paired with
        ``on_rollback`` is the anomalous-batch signature."""
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def _call(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)

            return _call
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        # monotonic: samples/s math must survive wall-clock steps (the
        # serving metrics hold the same discipline — ISSUE 9 audit)
        self._t0 = time.perf_counter()
        if self.verbose and self.params.get("verbose", 1):
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def on_train_batch_end(self, step, logs=None):
        logs = logs or {}
        if self.verbose and step % self.log_freq == 0:
            items = " - ".join(
                f"{k}: {v:.4f}" if isinstance(v, (int, float, np.floating))
                else f"{k}: {v}" for k, v in logs.items())
            ips = ""
            dt = time.perf_counter() - self._t0
            if dt > 0 and "batch_size" in self.params:
                ips = f" - {((step + 1) * self.params['batch_size']) / dt:.1f} samples/s"
            print(f"step {step + 1}/{self.steps or '?'} - {items}{ips}")

    def on_eval_end(self, logs=None):
        logs = logs or {}
        if self.verbose:
            items = " - ".join(
                f"{k}: {v}" for k, v in logs.items() if k != "batch_size")
            print(f"Eval - {items}")

    def on_rollback(self, step, report=None):
        if self.verbose:
            what = ",".join(report.flags()) if report is not None else "?"
            print(f"step {step + 1}: divergence ({what}) - rolled back "
                  "to last snapshot, window skipped")


class ModelCheckpoint(Callback):
    """Epoch-granular ``model.save`` plus (with ``save_steps``)
    step-granular checkpoint *generations* under ``<save_dir>/steps`` —
    CRC-verified, keep-last-K, auto-resumable via
    ``Model.fit(..., resume=True)`` (docs/RESILIENCE.md)."""

    def __init__(self, save_freq=1, save_dir=None, save_steps=None,
                 keep_last=3):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir
        self.save_steps = save_steps
        self.keep_last = keep_last
        self._gstep = 0               # global step across epochs

    @staticmethod
    def steps_root(save_dir):
        return os.path.join(save_dir, "steps")

    def on_train_begin(self, logs=None):
        # fit(resume=True) restored state before training started; pick
        # the generation numbering up where the previous run left off.
        # A FRESH fit into a dir that already holds generations must also
        # continue numbering past them: restarting at 0 would hand every
        # retention keep-slot to the stale higher-numbered generations
        # and delete each new checkpoint the moment it commits.
        start = int(getattr(self.model, "_resumed_step", 0) or 0)
        if self.save_dir and self.save_steps:
            from ..distributed import checkpoint as ckpt

            gens = ckpt.list_generations(self.steps_root(self.save_dir))
            if gens:
                start = max(start, gens[-1])
        self._gstep = start

    def on_train_batch_end(self, step, logs=None):
        if not (self.save_dir and self.save_steps):
            return
        self._gstep += 1
        if self._gstep % self.save_steps == 0:
            from ..distributed import checkpoint as ckpt

            ckpt.save_generation(self.model._ft_state_dict(self._gstep),
                                 self.steps_root(self.save_dir),
                                 self._gstep, keep_last=self.keep_last)

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "min" or (mode == "auto" and "loss" in monitor):
            self.better = lambda cur, best: cur < best - self.min_delta
            self.best = np.inf
        else:
            self.better = lambda cur, best: cur > best + self.min_delta
            self.best = -np.inf
        self.wait = 0
        self.stopped_epoch = 0

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        cur = float(cur)
        if self.better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(f"Early stopping: best {self.monitor}={self.best}")


class LRScheduler(Callback):
    """Steps an optimizer's LRScheduler per-batch or per-epoch."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler as Sched

        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if s and self.by_step:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if s and self.by_epoch:
            s.step()


class VisualDL(Callback):
    """Scalar logger writing TSV (VisualDL protocol replaced by plain files;
    the reference logs to the visualdl service)."""

    def __init__(self, log_dir="vdl_log"):
        super().__init__()
        self.log_dir = log_dir
        self._step = 0

    def on_train_batch_end(self, step, logs=None):
        os.makedirs(self.log_dir, exist_ok=True)
        logs = logs or {}
        self._step += 1
        with open(os.path.join(self.log_dir, "train.tsv"), "a") as f:
            for k, v in logs.items():
                if isinstance(v, (int, float, np.floating)):
                    f.write(f"{self._step}\t{k}\t{v}\n")
