"""Multinomial distribution (reference
`python/paddle/distribution/multinomial.py`)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import gammaln

from ..core.rng import next_key
from ..ops._helpers import op, unwrap, wrap
from .distribution import Distribution, _param


class Multinomial(Distribution):
    def __init__(self, total_count, probs):
        if int(total_count) < 1:
            raise ValueError("total_count must be >= 1")
        self.total_count = int(total_count)
        self.probs = _param(probs)
        # normalize like the reference (probs need not sum to 1 on input)
        p = unwrap(self.probs)
        self.probs = wrap(p / jnp.sum(p, axis=-1, keepdims=True))
        super().__init__(batch_shape=tuple(self.probs.shape[:-1]),
                         event_shape=tuple(self.probs.shape[-1:]))

    @property
    def mean(self):
        n = self.total_count
        return op("multinomial_mean", lambda p: n * p, [self.probs])

    @property
    def variance(self):
        n = self.total_count
        return op("multinomial_variance", lambda p: n * p * (1 - p),
                  [self.probs])

    def sample(self, shape=()):
        shp = tuple(shape) + self.batch_shape
        key = next_key()
        p = unwrap(self.probs)
        logits = jnp.log(p)
        # n iid categorical draws, one-hot summed -> counts (vectorized
        # over the sample+batch shape; n is a static python int)
        draws = jax.random.categorical(
            key, logits, axis=-1,
            shape=(self.total_count,) + shp)
        counts = jnp.sum(
            jax.nn.one_hot(draws, p.shape[-1], dtype=p.dtype), axis=0)
        return wrap(counts)

    def entropy(self):
        """Exact entropy via the binomial marginals:
        H = -log n! - n * sum_i p_i log p_i + sum_i E[log x_i!],
        with E[log x_i!] = sum_k Binom(n,k) p_i^k (1-p_i)^{n-k} log k!
        (x_i ~ Binomial(n, p_i); n is a static python int)."""
        n = self.total_count

        def _ent(p):
            k = jnp.arange(n + 1, dtype=p.dtype)               # [n+1]
            log_binom = (gammaln(jnp.asarray(float(n + 1)))
                         - gammaln(k + 1) - gammaln(n - k + 1))
            pe = p[..., None]                                   # [..., K, 1]
            log_pmf = (log_binom + k * jnp.log(pe)
                       + (n - k) * jnp.log1p(-pe))              # [..., K, n+1]
            e_log_fact = jnp.sum(jnp.exp(log_pmf) * gammaln(k + 1),
                                 axis=-1)                       # [..., K]
            return (-gammaln(jnp.asarray(float(n + 1)))
                    - n * jnp.sum(p * jnp.log(p), axis=-1)
                    + jnp.sum(e_log_fact, axis=-1))

        return op("multinomial_entropy", _ent, [self.probs])

    def log_prob(self, value):
        value = _param(value)
        n = self.total_count

        def _lp(v, p):
            logits = jnp.log(p)
            return (gammaln(jnp.asarray(float(n + 1)))
                    - jnp.sum(gammaln(v + 1), axis=-1)
                    + jnp.sum(v * logits, axis=-1))

        return op("multinomial_log_prob", _lp, [value, self.probs])
