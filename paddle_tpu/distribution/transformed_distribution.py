"""TransformedDistribution (reference
`python/paddle/distribution/transformed_distribution.py`)."""
from __future__ import annotations

import jax.numpy as jnp

from ..ops._helpers import op
from .distribution import Distribution
from .transform import ChainTransform, Transform


class TransformedDistribution(Distribution):
    def __init__(self, base, transforms):
        if not isinstance(base, Distribution):
            raise TypeError("base must be a Distribution")
        if isinstance(transforms, Transform):
            transforms = [transforms]
        for t in transforms:
            if not isinstance(t, Transform):
                raise TypeError("all transforms must be Transform instances")
        self._base = base
        self._transforms = list(transforms)
        chain = ChainTransform(self._transforms)
        base_shape = base.batch_shape + base.event_shape
        out_shape = chain.forward_shape(base_shape)
        event_rank = max(chain._codomain_event_rank, len(base.event_shape))
        cut = len(out_shape) - event_rank
        super().__init__(batch_shape=tuple(out_shape[:cut]),
                         event_shape=tuple(out_shape[cut:]))

    def sample(self, shape=()):
        x = self._base.sample(shape)
        for t in self._transforms:
            x = t.forward(x)
        return x

    def rsample(self, shape=()):
        x = self._base.rsample(shape)
        for t in self._transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        """log p(y) = log p_base(x) - sum log|det J_t(x)| with x = t^-1(y),
        event dims of each transform summed out."""
        log_prob = None
        y = value
        event_rank = len(self.event_shape)
        for t in reversed(self._transforms):
            x = t.inverse(y)
            ldj = t.forward_log_det_jacobian(x)
            extra = event_rank - t._codomain_event_rank

            def _sum_rightmost(e, n=extra):
                if n <= 0:
                    return e
                return jnp.sum(e, axis=tuple(range(e.ndim - n, e.ndim)))

            term = op("transformed_ldj_sum", _sum_rightmost, [ldj])
            log_prob = term if log_prob is None else op(
                "transformed_add", lambda a, b: a + b, [log_prob, term])
            y = x
            event_rank = t._domain_event_rank + max(
                event_rank - t._codomain_event_rank, 0)
        base_lp = self._base.log_prob(y)
        extra_base = event_rank - len(self._base.event_shape)
        if extra_base > 0:
            base_lp = op(
                "transformed_base_sum",
                lambda e: jnp.sum(
                    e, axis=tuple(range(e.ndim - extra_base, e.ndim))),
                [base_lp])
        if log_prob is None:
            return base_lp
        return op("transformed_log_prob",
                  lambda b, l: b - l, [base_lp, log_prob])
