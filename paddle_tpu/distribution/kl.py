"""KL divergence registry + dispatch (reference
`python/paddle/distribution/kl.py:29-115`).

`register_kl(P, Q)` decorates a function computing KL(p||q); dispatch picks
the most-specific registered (super_p, super_q) pair by total MRO distance,
exactly mirroring the reference resolution order."""
from __future__ import annotations

import functools
import warnings

import jax
import jax.numpy as jnp

from ..ops._helpers import op
from .beta import Beta
from .categorical import Categorical
from .dirichlet import Dirichlet
from .exponential_family import ExponentialFamily
from .normal import Normal
from .uniform import Uniform

_REGISTER_TABLE = {}


def kl_divergence(p, q):
    return _dispatch(type(p), type(q))(p, q)


def register_kl(cls_p, cls_q):
    def decorator(f):
        _REGISTER_TABLE[cls_p, cls_q] = f
        return f

    return decorator


def _dispatch(cls_p, cls_q):
    matches = [
        (sp, sq) for sp, sq in _REGISTER_TABLE
        if issubclass(cls_p, sp) and issubclass(cls_q, sq)
    ]
    if not matches:
        raise NotImplementedError(
            f"no KL registered for ({cls_p.__name__}, {cls_q.__name__})")

    def total_distance(pair):
        sp, sq = pair
        return cls_p.__mro__.index(sp) + cls_q.__mro__.index(sq)

    matches.sort(key=total_distance)
    left = min(matches, key=lambda m: cls_p.__mro__.index(m[0]))
    right = min(matches, key=lambda m: cls_q.__mro__.index(m[1]))
    if _REGISTER_TABLE[left] is not _REGISTER_TABLE[right]:
        warnings.warn(
            f"ambiguous KL for ({cls_p.__name__}, {cls_q.__name__})")
    return _REGISTER_TABLE[left]


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    from jax.scipy.special import betaln, digamma

    def _kl(a0, b0, a1, b1):
        s0 = a0 + b0
        return ((a0 - a1) * digamma(a0) + (b0 - b1) * digamma(b0)
                + (a1 - a0 + b1 - b0) * digamma(s0)
                + betaln(a1, b1) - betaln(a0, b0))

    return op("kl_beta_beta", _kl, [p.alpha, p.beta, q.alpha, q.beta])


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    from jax.scipy.special import gammaln, digamma

    def _kl(c0, c1):
        s0 = jnp.sum(c0, axis=-1)
        t1 = gammaln(s0) - jnp.sum(gammaln(c0), axis=-1)
        t2 = jnp.sum(gammaln(c1), axis=-1) - gammaln(jnp.sum(c1, axis=-1))
        t3 = jnp.sum((c0 - c1) * (digamma(c0) - digamma(s0)[..., None]),
                     axis=-1)
        return t1 + t2 + t3

    return op("kl_dirichlet", _kl, [p.concentration, q.concentration])


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p, q):
    return p.kl_divergence(q)


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    return p.kl_divergence(q)


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    return p.kl_divergence(q)


@register_kl(ExponentialFamily, ExponentialFamily)
def _kl_expfamily_expfamily(p, q):
    """Bregman-divergence KL between same-family exponential-family members
    (reference `kl.py:171` computes the identical quantity with a static
    graph; here the gradient term is one `jax.grad`)."""
    if type(p) is not type(q):
        raise NotImplementedError(
            "Bregman KL needs both distributions from the same family")
    p_params = list(p._natural_parameters)
    q_params = list(q._natural_parameters)
    n = len(p_params)

    def _kl(*theta):
        tp, tq = theta[:n], theta[n:]
        f = lambda *t: jnp.sum(p._log_normalizer(*t))
        grads = jax.grad(f, argnums=tuple(range(n)))(*tp)
        kl = q._log_normalizer(*tq) - p._log_normalizer(*tp)
        for a, b, g in zip(tp, tq, grads):
            term = (a - b) * g
            if term.shape != kl.shape:
                term = jnp.sum(term, axis=-1)
            kl = kl + term
        return kl

    return op("kl_expfamily", _kl, p_params + q_params)
