"""Categorical distribution (reference
`python/paddle/distribution/categorical.py:32`).

Follows the reference semantics: `logits` are unnormalized log-probabilities
(KL/entropy normalize with a log-sum-exp, `categorical.py:213-228`); `probs`
selects per-category probabilities by index; `sample` draws indices."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.rng import next_key
from ..ops._helpers import op, unwrap, wrap
from .distribution import Distribution, _param


def _log_softmax(z):
    z = z - jnp.max(z, axis=-1, keepdims=True)
    return z - jnp.log(jnp.sum(jnp.exp(z), axis=-1, keepdims=True))


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _param(logits)
        self.name = name or "Categorical"
        super().__init__(batch_shape=tuple(self.logits.shape[:-1]))

    def sample(self, shape=()):
        shp = tuple(shape)
        key = next_key()
        z = unwrap(self.logits)
        # jax.random.categorical samples over the last axis; prepend the
        # sample shape like the reference (sample index dims first).
        out = jax.random.categorical(key, _log_softmax(z),
                                     shape=shp + z.shape[:-1])
        return wrap(out)

    def entropy(self):
        def _ent(z):
            lp = _log_softmax(z)
            return -jnp.sum(jnp.exp(lp) * lp, axis=-1)

        return op("categorical_entropy", _ent, [self.logits])

    def kl_divergence(self, other):
        assert isinstance(other, Categorical)

        def _kl(z0, z1):
            lp0 = _log_softmax(z0)
            lp1 = _log_softmax(z1)
            return jnp.sum(jnp.exp(lp0) * (lp0 - lp1), axis=-1,
                           keepdims=True)

        return op("categorical_kl", _kl, [self.logits, other.logits])

    def probs(self, value):
        idx = unwrap(_param(value)).astype(jnp.int32)

        def _simple(z):
            p = jnp.exp(_log_softmax(z))
            if p.ndim == 1:
                return p[idx]
            return jnp.take_along_axis(p, idx, axis=-1)

        return op("categorical_probs", _simple, [self.logits])

    def log_prob(self, value):
        idx = unwrap(_param(value)).astype(jnp.int32)

        def _lp(z):
            lp = _log_softmax(z)
            if lp.ndim == 1:
                return lp[idx]
            return jnp.take_along_axis(lp, idx, axis=-1)

        return op("categorical_log_prob", _lp, [self.logits])
