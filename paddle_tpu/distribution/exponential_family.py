"""Exponential-family base with Bregman-divergence entropy (reference
`python/paddle/distribution/exponential_family.py`).

entropy = -F(theta) + <theta, grad F(theta)> - E[log h(x)] where F is the
log normalizer; on TPU the gradient term is `jax.grad` of the log
normalizer (the reference differentiates a static program for the same
quantity)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops._helpers import op
from .distribution import Distribution


class ExponentialFamily(Distribution):
    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_parameters):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        return 0.0

    def entropy(self):
        """H = F(theta) - <theta, grad F(theta)> - E[log h(x)]."""
        nparams = list(self._natural_parameters)

        def _entropy(*theta):
            f = lambda *t: jnp.sum(self._log_normalizer(*t))
            grads = jax.grad(f, argnums=tuple(range(len(theta))))(*theta)
            result = self._log_normalizer(*theta) - \
                self._mean_carrier_measure
            for t, g in zip(theta, grads):
                term = t * g
                if term.shape != result.shape:
                    term = jnp.sum(term, axis=-1)
                result = result - term
            return result

        return op("expfamily_entropy", _entropy, nparams)
