"""Probability distribution base class.

API parity with the reference `python/paddle/distribution/distribution.py:40`
(batch_shape/event_shape properties, sample/rsample/entropy/kl_divergence/
prob/log_prob/probs surface).  TPU-native: parameters are stored as jax
arrays behind the Tensor facade, all math is traced through the dispatch
tape so log_prob/entropy are differentiable, and sampling consumes the
global functional RNG key (`core.rng.next_key`) so it is reproducible under
`paddle.seed` and usable inside `to_static` programs.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core import dtype as dtype_mod
from ..core.tensor import Tensor
from ..ops._helpers import op, unwrap, wrap


def _param(x, dtype=None):
    """Convert a scalar/list/ndarray/Tensor parameter to a float Tensor."""
    if isinstance(x, Tensor):
        if not np.issubdtype(np.dtype(x.dtype), np.floating):
            return wrap(unwrap(x).astype(dtype_mod.get_default_dtype()))
        return x
    arr = np.asarray(x)
    if not np.issubdtype(arr.dtype, np.floating):
        arr = arr.astype(dtype or dtype_mod.get_default_dtype())
    return wrap(jnp.asarray(arr))


class Distribution:
    """Abstract base class for probability distributions
    (reference `distribution.py:40`)."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(
            batch_shape.shape if isinstance(batch_shape, Tensor)
            else batch_shape)
        self._event_shape = tuple(
            event_shape.shape if isinstance(event_shape, Tensor)
            else event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        from .kl import kl_divergence
        return kl_divergence(self, other)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        """exp(log_prob(value)) unless a subclass has a closed form."""
        lp = self.log_prob(_param(value))
        return op("dist_prob", jnp.exp, [lp])

    def probs(self, value):
        return self.prob(value)

    def _extend_shape(self, sample_shape):
        return tuple(sample_shape) + self.batch_shape + self.event_shape

    # helpers shared by subclasses -------------------------------------
    @staticmethod
    def _probs_to_logits(probs, is_binary=False):
        p = unwrap(probs)
        out = jnp.log(p / (1.0 - p)) if is_binary else jnp.log(p)
        return wrap(out)

    @staticmethod
    def _logits_to_probs(logits, is_binary=False):
        z = unwrap(logits)
        if is_binary:
            return wrap(1.0 / (1.0 + jnp.exp(-z)))
        return wrap(jnp.exp(z - jnp.max(z, axis=-1, keepdims=True))
                    / jnp.sum(jnp.exp(z - jnp.max(z, axis=-1, keepdims=True)),
                              axis=-1, keepdims=True))
