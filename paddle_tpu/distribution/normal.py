"""Normal distribution (reference `python/paddle/distribution/normal.py:30`)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core.rng import next_key
from ..ops._helpers import op, unwrap, wrap
from .distribution import Distribution, _param


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _param(loc)
        self.scale = _param(scale)
        self.name = name or "Normal"
        batch = jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        super().__init__(batch_shape=batch)

    @property
    def mean(self):
        return op("normal_mean", lambda l, s: jnp.broadcast_to(
            l, jnp.broadcast_shapes(l.shape, s.shape)),
            [self.loc, self.scale])

    @property
    def variance(self):
        return op("normal_variance", lambda l, s: jnp.broadcast_to(
            s * s, jnp.broadcast_shapes(l.shape, s.shape)),
            [self.loc, self.scale])

    @property
    def stddev(self):
        return op("normal_stddev", lambda l, s: jnp.broadcast_to(
            s, jnp.broadcast_shapes(l.shape, s.shape)),
            [self.loc, self.scale])

    def sample(self, shape=(), seed=0):
        from ..core import autograd
        with autograd.no_grad():
            return self.rsample(shape)

    def rsample(self, shape=()):
        shp = self._extend_shape(tuple(shape))
        key = next_key()

        def _sample(l, s):
            eps = jax.random.normal(key, shp, dtype=jnp.result_type(l))
            return l + s * eps

        return op("normal_rsample", _sample, [self.loc, self.scale])

    def entropy(self):
        def _ent(l, s):
            b = jnp.broadcast_shapes(l.shape, s.shape)
            return jnp.broadcast_to(
                0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s), b)

        return op("normal_entropy", _ent, [self.loc, self.scale])

    def log_prob(self, value):
        value = _param(value)

        def _lp(v, l, s):
            var = s * s
            return (-((v - l) ** 2) / (2 * var) - jnp.log(s)
                    - 0.5 * math.log(2 * math.pi))

        return op("normal_log_prob", _lp, [value, self.loc, self.scale])

    def probs(self, value):
        value = _param(value)

        def _p(v, l, s):
            var = s * s
            return jnp.exp(-((v - l) ** 2) / (2 * var)) / jnp.sqrt(
                2 * math.pi * var)

        return op("normal_probs", _p, [value, self.loc, self.scale])

    def kl_divergence(self, other):
        assert isinstance(other, Normal)

        def _kl(l0, s0, l1, s1):
            ratio = s0 / s1
            diff = (l0 - l1) / s1
            return 0.5 * (ratio * ratio + diff * diff) - 0.5 - jnp.log(ratio)

        return op("normal_kl", _kl,
                  [self.loc, self.scale, other.loc, other.scale])
