"""Beta distribution (reference `python/paddle/distribution/beta.py`)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import betaln, digamma

from ..core.rng import next_key
from ..ops._helpers import op
from .distribution import _param
from .exponential_family import ExponentialFamily


class Beta(ExponentialFamily):
    def __init__(self, alpha, beta):
        self.alpha = _param(alpha)
        self.beta = _param(beta)
        batch = jnp.broadcast_shapes(self.alpha.shape, self.beta.shape)
        super().__init__(batch_shape=batch)

    @property
    def mean(self):
        return op("beta_mean", lambda a, b: a / (a + b),
                  [self.alpha, self.beta])

    @property
    def variance(self):
        def _var(a, b):
            s = a + b
            return a * b / (s * s * (s + 1))

        return op("beta_variance", _var, [self.alpha, self.beta])

    def sample(self, shape=()):
        shp = self._extend_shape(tuple(shape))
        key = next_key()

        def _sample(a, b):
            return jax.random.beta(key, a, b, shape=shp or None)

        return op("beta_sample", _sample, [self.alpha, self.beta])

    def entropy(self):
        def _ent(a, b):
            s = a + b
            return (betaln(a, b) - (a - 1) * digamma(a)
                    - (b - 1) * digamma(b) + (s - 2) * digamma(s))

        return op("beta_entropy", _ent, [self.alpha, self.beta])

    def log_prob(self, value):
        value = _param(value)

        def _lp(v, a, b):
            return ((a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v)
                    - betaln(a, b))

        return op("beta_log_prob", _lp, [value, self.alpha, self.beta])

    def prob(self, value):
        lp = self.log_prob(value)
        return op("beta_prob", jnp.exp, [lp])

    @property
    def _natural_parameters(self):
        # p(x) = exp((a-1)log x + (b-1)log(1-x) - ln B(a,b))
        return (op("beta_natural", lambda a: a - 1.0, [self.alpha]),
                op("beta_natural", lambda b: b - 1.0, [self.beta]))

    def _log_normalizer(self, x, y):
        return betaln(x + 1.0, y + 1.0)
