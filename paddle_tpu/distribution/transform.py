"""Bijective/injective tensor transforms (reference
`python/paddle/distribution/transform.py`).

Each transform exposes forward/inverse, the log-det-Jacobian of both
directions, and shape propagation; `TransformedDistribution` composes them
with a base distribution.  All math runs through the dispatch tape (taped
jnp ops) so transformed log_probs are differentiable."""
from __future__ import annotations

import enum
import functools
import operator

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops._helpers import op, unwrap, wrap
from .distribution import _param

__all__ = [
    'Transform', 'AbsTransform', 'AffineTransform', 'ChainTransform',
    'ExpTransform', 'IndependentTransform', 'PowerTransform',
    'ReshapeTransform', 'SigmoidTransform', 'SoftmaxTransform',
    'StackTransform', 'StickBreakingTransform', 'TanhTransform',
]


class Type(enum.Enum):
    BIJECTION = 'bijection'
    INJECTION = 'injection'
    SURJECTION = 'surjection'
    OTHER = 'other'

    @classmethod
    def is_injective(cls, t):
        return t in (cls.BIJECTION, cls.INJECTION)


class Transform:
    _type = Type.OTHER

    # event dims consumed/produced (0 = elementwise)
    _domain_event_rank = 0
    _codomain_event_rank = 0

    def _is_injective(self):
        # instance method: composite transforms (Chain/Stack) compute their
        # _type per-instance from their members
        return Type.is_injective(self._type)

    def __call__(self, x):
        if isinstance(x, Transform):
            return ChainTransform([x, self])
        return self.forward(x)

    def forward(self, x):
        return op(type(self).__name__ + "_fwd", self._forward, [_param(x)])

    def inverse(self, y):
        return op(type(self).__name__ + "_inv", self._inverse, [_param(y)])

    def forward_log_det_jacobian(self, x):
        if hasattr(self, "_forward_log_det_jacobian"):
            return op(type(self).__name__ + "_fldj",
                      self._forward_log_det_jacobian, [_param(x)])
        if hasattr(self, "_inverse_log_det_jacobian"):
            y = self.forward(x)
            return op(type(self).__name__ + "_fldj_via_inv",
                      lambda v: -self._inverse_log_det_jacobian(v), [y])
        raise NotImplementedError

    def inverse_log_det_jacobian(self, y):
        if hasattr(self, "_inverse_log_det_jacobian"):
            return op(type(self).__name__ + "_ildj",
                      self._inverse_log_det_jacobian, [_param(y)])
        # negate the forward log-det at the preimage (works for subclasses
        # that override the *public* forward_log_det_jacobian too)
        x = self.inverse(y)
        ldj = self.forward_log_det_jacobian(x)
        return op(type(self).__name__ + "_ildj_neg",
                  lambda v: -v, [ldj])

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)


class AbsTransform(Transform):
    """y = |x| — surjective onto [0, inf); inverse returns the positive
    preimage like the reference."""
    _type = Type.SURJECTION

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y


class AffineTransform(Transform):
    """y = loc + scale * x."""
    _type = Type.BIJECTION

    def __init__(self, loc, scale):
        self.loc = _param(loc)
        self.scale = _param(scale)

    def forward(self, x):
        return op("AffineTransform_fwd",
                  lambda v, l, s: l + s * v,
                  [_param(x), self.loc, self.scale])

    def inverse(self, y):
        return op("AffineTransform_inv",
                  lambda v, l, s: (v - l) / s,
                  [_param(y), self.loc, self.scale])

    def forward_log_det_jacobian(self, x):
        return op("AffineTransform_fldj",
                  lambda v, s: jnp.broadcast_to(
                      jnp.log(jnp.abs(s)),
                      jnp.broadcast_shapes(v.shape, s.shape)),
                  [_param(x), self.scale])

    def inverse_log_det_jacobian(self, y):
        return op("AffineTransform_ildj",
                  lambda v, s: jnp.broadcast_to(
                      -jnp.log(jnp.abs(s)),
                      jnp.broadcast_shapes(v.shape, s.shape)),
                  [_param(y), self.scale])


class ExpTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class PowerTransform(Transform):
    """y = x ** power on x > 0."""
    _type = Type.BIJECTION

    def __init__(self, power):
        self.power = _param(power)

    def forward(self, x):
        return op("PowerTransform_fwd", lambda v, p: jnp.power(v, p),
                  [_param(x), self.power])

    def inverse(self, y):
        return op("PowerTransform_inv", lambda v, p: jnp.power(v, 1.0 / p),
                  [_param(y), self.power])

    def forward_log_det_jacobian(self, x):
        return op("PowerTransform_fldj",
                  lambda v, p: jnp.log(jnp.abs(p * jnp.power(v, p - 1))),
                  [_param(x), self.power])


class SigmoidTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return 1.0 / (1.0 + jnp.exp(-x))

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        # log sigmoid'(x) = -softplus(-x) - softplus(x)
        sp = lambda v: jnp.logaddexp(v, 0.0)
        return -sp(-x) - sp(x)


class TanhTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _forward_log_det_jacobian(self, x):
        # log(1 - tanh^2 x) = 2 (log 2 - x - softplus(-2x))
        return 2.0 * (jnp.log(2.0) - x - jnp.logaddexp(-2.0 * x, 0.0))


class SoftmaxTransform(Transform):
    """y = softmax(x) over the last axis — surjective onto the simplex."""
    _type = Type.OTHER
    _domain_event_rank = 1
    _codomain_event_rank = 1

    def _forward(self, x):
        z = x - jnp.max(x, axis=-1, keepdims=True)
        e = jnp.exp(z)
        return e / jnp.sum(e, axis=-1, keepdims=True)

    def _inverse(self, y):
        return jnp.log(y)


class StickBreakingTransform(Transform):
    """R^{K-1} -> open simplex in R^K via stick breaking."""
    _type = Type.BIJECTION
    _domain_event_rank = 1
    _codomain_event_rank = 1

    def _forward(self, x):
        k = x.shape[-1]
        offset = jnp.arange(k, 0, -1, dtype=x.dtype)
        z = 1.0 / (1.0 + jnp.exp(-(x - jnp.log(offset))))
        zc = jnp.cumprod(1 - z, axis=-1)
        ones = jnp.ones(x.shape[:-1] + (1,), dtype=x.dtype)
        return jnp.concatenate([z, ones], axis=-1) * jnp.concatenate(
            [ones, zc], axis=-1)

    def _inverse(self, y):
        y_crop = y[..., :-1]
        k = y_crop.shape[-1]
        offset = jnp.arange(k, 0, -1, dtype=y.dtype)
        sf = 1.0 - jnp.cumsum(y_crop, axis=-1)
        sf = jnp.concatenate(
            [jnp.ones(y.shape[:-1] + (1,), dtype=y.dtype), sf[..., :-1]],
            axis=-1)
        z = y_crop / sf
        return jnp.log(z) - jnp.log1p(-z) + jnp.log(offset)

    def _forward_log_det_jacobian(self, x):
        k = x.shape[-1]
        offset = jnp.arange(k, 0, -1, dtype=x.dtype)
        u = x - jnp.log(offset)
        z = 1.0 / (1.0 + jnp.exp(-u))
        # log prod z_i * (1-z)_cumulative
        sp = lambda v: jnp.logaddexp(v, 0.0)
        log_z = -sp(-u)
        log_1mz_cum = jnp.cumsum(-sp(u), axis=-1)
        shifted = jnp.concatenate(
            [jnp.zeros(x.shape[:-1] + (1,), dtype=x.dtype),
             log_1mz_cum[..., :-1]], axis=-1)
        return jnp.sum(log_z + shifted, axis=-1)

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)


class ReshapeTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, in_event_shape, out_event_shape):
        self._in = tuple(in_event_shape)
        self._out = tuple(out_event_shape)
        if functools.reduce(operator.mul, self._in, 1) != functools.reduce(
                operator.mul, self._out, 1):
            raise ValueError("event sizes must match")
        self._domain_event_rank = len(self._in)
        self._codomain_event_rank = len(self._out)

    @property
    def in_event_shape(self):
        return self._in

    @property
    def out_event_shape(self):
        return self._out

    def _forward(self, x):
        batch = x.shape[:x.ndim - len(self._in)]
        return jnp.reshape(x, batch + self._out)

    def _inverse(self, y):
        batch = y.shape[:y.ndim - len(self._out)]
        return jnp.reshape(y, batch + self._in)

    def _forward_log_det_jacobian(self, x):
        batch = x.shape[:x.ndim - len(self._in)]
        return jnp.zeros(batch, dtype=x.dtype)

    def forward_shape(self, shape):
        n = len(self._in)
        if tuple(shape[len(shape) - n:]) != self._in:
            raise ValueError(f"shape {shape} does not end in {self._in}")
        return tuple(shape[:len(shape) - n]) + self._out

    def inverse_shape(self, shape):
        n = len(self._out)
        if tuple(shape[len(shape) - n:]) != self._out:
            raise ValueError(f"shape {shape} does not end in {self._out}")
        return tuple(shape[:len(shape) - n]) + self._in


class IndependentTransform(Transform):
    """Promote batch dims of a base transform to event dims (sums the
    log-det over the reinterpreted dims)."""

    def __init__(self, base, reinterpreted_batch_rank):
        if not isinstance(base, Transform):
            raise TypeError("base must be a Transform")
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        self._type = base._type
        self._domain_event_rank = base._domain_event_rank + self.rank
        self._codomain_event_rank = base._codomain_event_rank + self.rank

    def forward(self, x):
        return self.base.forward(x)

    def inverse(self, y):
        return self.base.inverse(y)

    def forward_log_det_jacobian(self, x):
        ldj = self.base.forward_log_det_jacobian(x)
        return op("IndependentTransform_sum",
                  lambda v: jnp.sum(
                      v, axis=tuple(range(v.ndim - self.rank, v.ndim))),
                  [ldj])

    def inverse_log_det_jacobian(self, y):
        ldj = self.base.inverse_log_det_jacobian(y)
        return op("IndependentTransform_sum",
                  lambda v: jnp.sum(
                      v, axis=tuple(range(v.ndim - self.rank, v.ndim))),
                  [ldj])

    def forward_shape(self, shape):
        return self.base.forward_shape(shape)

    def inverse_shape(self, shape):
        return self.base.inverse_shape(shape)


class ChainTransform(Transform):
    """Composition t_n(...t_1(x)); log-dets accumulate."""

    def __init__(self, transforms):
        if isinstance(transforms, Transform):
            transforms = [transforms]
        for t in transforms:
            if not isinstance(t, Transform):
                raise TypeError("all elements must be Transforms")
        self.transforms = list(transforms)
        self._type = (Type.BIJECTION if all(
            t._type == Type.BIJECTION for t in self.transforms)
            else Type.OTHER if any(not t._is_injective()
                                   for t in self.transforms)
            else Type.INJECTION)
        self._domain_event_rank = max(
            (t._domain_event_rank for t in self.transforms), default=0)
        self._codomain_event_rank = max(
            (t._codomain_event_rank for t in self.transforms), default=0)

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        total = None
        for t in self.transforms:
            ldj = t.forward_log_det_jacobian(x)
            total = ldj if total is None else op(
                "ChainTransform_add", lambda a, b: a + b, [total, ldj])
            x = t.forward(x)
        return total

    def inverse_log_det_jacobian(self, y):
        total = None
        for t in reversed(self.transforms):
            ldj = t.inverse_log_det_jacobian(y)
            total = ldj if total is None else op(
                "ChainTransform_add", lambda a, b: a + b, [total, ldj])
            y = t.inverse(y)
        return total

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return tuple(shape)

    def inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return tuple(shape)


class StackTransform(Transform):
    """Apply a list of transforms to slices along `axis`."""

    def __init__(self, transforms, axis=0):
        for t in transforms:
            if not isinstance(t, Transform):
                raise TypeError("all elements must be Transforms")
        self.transforms = list(transforms)
        self.axis = int(axis)
        self._type = (Type.BIJECTION if all(
            t._type == Type.BIJECTION for t in self.transforms)
            else Type.OTHER)

    def _split(self, x):
        x = _param(x)
        n = len(self.transforms)
        arr = unwrap(x)
        return [wrap(a) for a in jnp.split(arr, n, axis=self.axis)]

    def _stack(self, parts):
        arrs = [unwrap(p) for p in parts]
        return wrap(jnp.concatenate(arrs, axis=self.axis))

    def forward(self, x):
        return self._stack([t.forward(p)
                            for t, p in zip(self.transforms, self._split(x))])

    def inverse(self, y):
        return self._stack([t.inverse(p)
                            for t, p in zip(self.transforms, self._split(y))])

    def forward_log_det_jacobian(self, x):
        return self._stack([
            t.forward_log_det_jacobian(p)
            for t, p in zip(self.transforms, self._split(x))])
