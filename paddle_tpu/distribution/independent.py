"""Independent distribution (reference
`python/paddle/distribution/independent.py`): reinterprets trailing batch
dims of a base distribution as event dims."""
from __future__ import annotations

import jax.numpy as jnp

from ..ops._helpers import op
from .distribution import Distribution


class Independent(Distribution):
    def __init__(self, base, reinterpreted_batch_rank):
        if not isinstance(base, Distribution):
            raise TypeError("base must be a Distribution")
        rank = int(reinterpreted_batch_rank)
        if not (0 < rank <= len(base.batch_shape)):
            raise ValueError(
                f"reinterpreted_batch_rank {rank} out of range for base "
                f"batch shape {base.batch_shape}")
        self._base = base
        self._reinterpreted_batch_rank = rank
        shape = base.batch_shape + base.event_shape
        cut = len(base.batch_shape) - rank
        super().__init__(batch_shape=shape[:cut], event_shape=shape[cut:])

    @property
    def mean(self):
        return self._base.mean

    @property
    def variance(self):
        return self._base.variance

    def sample(self, shape=()):
        return self._base.sample(shape)

    def rsample(self, shape=()):
        return self._base.rsample(shape)

    def entropy(self):
        ent = self._base.entropy()
        r = self._reinterpreted_batch_rank
        return op("independent_entropy_sum",
                  lambda e: jnp.sum(e, axis=tuple(range(e.ndim - r, e.ndim))),
                  [ent])

    def log_prob(self, value):
        lp = self._base.log_prob(value)
        r = self._reinterpreted_batch_rank
        return op("independent_log_prob_sum",
                  lambda e: jnp.sum(e, axis=tuple(range(e.ndim - r, e.ndim))),
                  [lp])
