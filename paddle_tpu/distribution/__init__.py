"""paddle.distribution — probability distributions, transforms, and KL
(reference `python/paddle/distribution/__init__.py`)."""
from . import transform
from .beta import Beta
from .categorical import Categorical
from .dirichlet import Dirichlet
from .distribution import Distribution
from .exponential_family import ExponentialFamily
from .independent import Independent
from .kl import kl_divergence, register_kl
from .multinomial import Multinomial
from .normal import Normal
from .transform import (  # noqa: F401
    AbsTransform, AffineTransform, ChainTransform, ExpTransform,
    IndependentTransform, PowerTransform, ReshapeTransform, SigmoidTransform,
    SoftmaxTransform, StackTransform, StickBreakingTransform, TanhTransform,
    Transform,
)
from .transformed_distribution import TransformedDistribution
from .uniform import Uniform

__all__ = [
    'Beta', 'Categorical', 'Dirichlet', 'Distribution', 'ExponentialFamily',
    'Multinomial', 'Normal', 'Uniform', 'kl_divergence', 'register_kl',
    'Independent', 'TransformedDistribution',
]
__all__.extend(transform.__all__)
