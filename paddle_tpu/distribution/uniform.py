"""Uniform distribution (reference `python/paddle/distribution/uniform.py`)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.rng import next_key
from ..ops._helpers import op
from .distribution import Distribution, _param


class Uniform(Distribution):
    """U(low, high) on [low, high)."""

    def __init__(self, low, high, name=None):
        self.low = _param(low)
        self.high = _param(high)
        self.name = name or "Uniform"
        batch = jnp.broadcast_shapes(self.low.shape, self.high.shape)
        super().__init__(batch_shape=batch)

    @property
    def mean(self):
        return op("uniform_mean", lambda a, b: (a + b) / 2,
                  [self.low, self.high])

    @property
    def variance(self):
        return op("uniform_variance", lambda a, b: (b - a) ** 2 / 12,
                  [self.low, self.high])

    def sample(self, shape=(), seed=0):
        from ..core import autograd
        with autograd.no_grad():
            return self.rsample(shape)

    def rsample(self, shape=()):
        shp = self._extend_shape(tuple(shape))
        key = next_key()

        def _sample(a, b):
            u = jax.random.uniform(key, shp, dtype=jnp.result_type(a))
            return a + (b - a) * u

        return op("uniform_rsample", _sample, [self.low, self.high])

    def entropy(self):
        return op("uniform_entropy", lambda a, b: jnp.log(b - a),
                  [self.low, self.high])

    def log_prob(self, value):
        value = _param(value)

        def _lp(v, a, b):
            inside = jnp.logical_and(v >= a, v < b)
            lp = -jnp.log(b - a)
            return jnp.where(inside, lp, -jnp.inf)

        return op("uniform_log_prob", _lp, [value, self.low, self.high])

    def probs(self, value):
        value = _param(value)

        def _p(v, a, b):
            inside = jnp.logical_and(v >= a, v < b)
            return jnp.where(inside, 1.0 / (b - a), 0.0)

        return op("uniform_probs", _p, [value, self.low, self.high])

    def kl_divergence(self, other):
        assert isinstance(other, Uniform)

        def _kl(a0, b0, a1, b1):
            return jnp.log((b1 - a1) / (b0 - a0))

        return op("uniform_kl", _kl,
                  [self.low, self.high, other.low, other.high])
