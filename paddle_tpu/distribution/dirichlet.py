"""Dirichlet distribution (reference
`python/paddle/distribution/dirichlet.py`)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import gammaln, digamma

from ..core.rng import next_key
from ..ops._helpers import op
from .distribution import _param
from .exponential_family import ExponentialFamily


class Dirichlet(ExponentialFamily):
    def __init__(self, concentration):
        self.concentration = _param(concentration)
        if len(self.concentration.shape) < 1:
            raise ValueError(
                "concentration must be at least 1-dimensional")
        super().__init__(
            batch_shape=tuple(self.concentration.shape[:-1]),
            event_shape=tuple(self.concentration.shape[-1:]))

    @property
    def mean(self):
        return op("dirichlet_mean",
                  lambda c: c / jnp.sum(c, axis=-1, keepdims=True),
                  [self.concentration])

    @property
    def variance(self):
        def _var(c):
            c0 = jnp.sum(c, axis=-1, keepdims=True)
            return c * (c0 - c) / (c0 * c0 * (c0 + 1))

        return op("dirichlet_variance", _var, [self.concentration])

    def sample(self, shape=()):
        shp = tuple(shape) + self.batch_shape
        key = next_key()

        def _sample(c):
            return jax.random.dirichlet(key, c, shape=shp or None)

        return op("dirichlet_sample", _sample, [self.concentration])

    def entropy(self):
        def _ent(c):
            k = c.shape[-1]
            c0 = jnp.sum(c, axis=-1)
            lnB = jnp.sum(gammaln(c), axis=-1) - gammaln(c0)
            return (lnB + (c0 - k) * digamma(c0)
                    - jnp.sum((c - 1) * digamma(c), axis=-1))

        return op("dirichlet_entropy", _ent, [self.concentration])

    def log_prob(self, value):
        value = _param(value)

        def _lp(v, c):
            lnB = jnp.sum(gammaln(c), axis=-1) - gammaln(
                jnp.sum(c, axis=-1))
            return jnp.sum((c - 1) * jnp.log(v), axis=-1) - lnB

        return op("dirichlet_log_prob", _lp, [value, self.concentration])

    @property
    def _natural_parameters(self):
        # p(x) = exp(<alpha-1, log x> - ln B(alpha)): theta = alpha - 1
        return (op("dirichlet_natural", lambda c: c - 1.0,
                   [self.concentration]),)

    def _log_normalizer(self, x):
        a = x + 1.0
        return jnp.sum(gammaln(a), axis=-1) - gammaln(jnp.sum(a, axis=-1))
