"""Sync-point sanitizer: measure (and optionally forbid) device→host
transfers on the serving decode hot path.

The decode discipline the engine is built around — ONE fixed-shape
compiled step per token, host work limited to sampling and scheduling —
is only as real as its measurement.  ``SyncSanitizer`` makes it
measurable (docs/ANALYSIS.md "Sync-point sanitizer"):

- **counting window**: while a decode *dispatch* runs, every
  framework-level host coercion (``Tensor.numpy()/.item()/.tolist()/
  __array__/__float__/__int__/__bool__``) is counted and attributed to
  the source line that forced it (the first stack frame outside the
  tensor/sanitizer plumbing).  The measured number is **0.0 per decode
  step** since ROADMAP item 2 moved sampling on-device (the PR 7
  baseline was 1.0, the per-step sampling logits pull); the post-step
  stream-delivery token pull sits outside the window by design —
  exported as ``stats()["sanitizer"]`` and as
  ``serving_decode_host_transfers`` on ``bench.py --serving``, pinned
  at 0.0 by tests so a sync cannot creep back in.
- **compiled guard**: the compiled decode call itself is additionally
  wrapped in ``jax.transfer_guard_device_to_host`` — ``"log"`` by
  default, ``"disallow"`` in strict mode — asserting the *compiled*
  step performs no host round-trip at the runtime level (the guard is
  enforced by the backend on TPU; on the CPU backend host and device
  share memory, so the framework-level counting window is the
  CPU-verifiable surface and the guard is armed but vacuous).

Arming: ``PADDLE_TPU_SANITIZE=1`` (count + log) or
``PADDLE_TPU_SANITIZE=strict`` (count + disallow: a d2h transfer inside
the compiled decode step raises, failing the implicated batch loudly)
arms every Engine at construction via :meth:`SyncSanitizer.from_env`;
tests and the bench attach one explicitly (``engine.sanitizer =
SyncSanitizer()``).
"""
from __future__ import annotations

import os
import sys
from contextlib import contextmanager, nullcontext
from typing import Dict, Optional

import jax

__all__ = ["SyncSanitizer"]

#: files whose frames are plumbing, not an attributable sync site
_PLUMBING = (os.sep + "core" + os.sep + "tensor.py",
             os.sep + "serving" + os.sep + "sanitize.py")

#: the conversion surface itself is plumbing wherever it lives — the
#: attributable site is whoever CALLED the coercion (ops/misc.py's
#: ``tolist`` op shadows the core method, so file matching alone would
#: blame the op function for its caller's pull)
_CONVERSION_FNS = frozenset({
    "numpy", "item", "tolist", "__array__", "__bool__", "__float__",
    "__int__", "__format__", "__repr__", "__str__"})


def _attribute_site(skip: int = 2) -> str:
    """``file:line`` of the nearest caller outside the tensor/sanitizer
    plumbing, path shortened to the repo-relative tail."""
    f = sys._getframe(skip)
    while f is not None:
        fname = f.f_code.co_filename
        if not fname.endswith(_PLUMBING) \
                and f.f_code.co_name not in _CONVERSION_FNS:
            parts = fname.split(os.sep)
            for anchor in ("paddle_tpu", "tests", "tools"):
                if anchor in parts:
                    fname = os.sep.join(parts[parts.index(anchor):])
                    break
            else:
                fname = os.sep.join(parts[-2:])
            return f"{fname}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


class SyncSanitizer:
    """Per-engine host-transfer meter for steady-state decode.

    One instance is owned by one Engine (single-threaded scheduler —
    the counting hook is installed only inside ``decode_window``, so
    concurrent engines never see each other's windows).
    """

    def __init__(self, strict: bool = False):
        self.strict = bool(strict)
        self.decode_steps = 0
        self.host_transfers = 0
        self.by_site: Dict[str, int] = {}
        self.guard_violations = 0
        self._in_window = False

    # -- construction ------------------------------------------------------

    @classmethod
    def from_env(cls) -> Optional["SyncSanitizer"]:
        """The env-armed sanitizer (``PADDLE_TPU_SANITIZE=1|strict``),
        or None when the mode is off (the default: zero overhead)."""
        v = os.environ.get("PADDLE_TPU_SANITIZE", "").strip().lower()
        if v in ("", "0", "false", "off", "no"):
            return None
        if v in ("1", "true", "on", "yes"):
            return cls(strict=False)
        if v == "strict":
            return cls(strict=True)
        raise ValueError(
            f"PADDLE_TPU_SANITIZE={v!r}: expected 1 (count+log), "
            "strict (count+disallow), or 0/off to disable")

    # -- the two measurement surfaces --------------------------------------

    def _on_sync(self, _tensor) -> None:
        self.host_transfers += 1
        site = _attribute_site()
        self.by_site[site] = self.by_site.get(site, 0) + 1

    def note_step(self) -> None:
        """One compiled decode step actually executed.  Called by the
        engine after a successful step call — NOT by ``decode_window``,
        so windows that abort before the compiled call (paged pool
        exhaustion retiring every request, retry budget exhausted) never
        dilute ``per_decode_step`` below the real baseline."""
        self.decode_steps += 1

    @contextmanager
    def decode_window(self):
        """Count + attribute every framework-level host coercion during
        one decode step.  Reentrancy-safe (inner windows don't
        reinstall the hook); steps are counted by ``note_step``, not by
        window entry."""
        from ..core import tensor as tensor_mod

        if self._in_window:
            yield
            return
        self._in_window = True
        prev = tensor_mod._sync_hook
        tensor_mod._sync_hook = self._on_sync
        try:
            yield
        finally:
            tensor_mod._sync_hook = prev
            self._in_window = False

    def compiled_guard(self):
        """Context manager armed around the compiled decode call: the
        step itself must not transfer device→host.  ``"log"`` surfaces
        violations on stderr; strict mode raises (the engine's error
        isolation then fails the implicated batch — loud by design)."""
        guard = getattr(jax, "transfer_guard_device_to_host", None)
        if guard is None:                # ancient jax: counting only
            return nullcontext()
        return guard("disallow" if self.strict else "log")

    # -- export ------------------------------------------------------------

    def per_decode_step(self) -> float:
        return (self.host_transfers / self.decode_steps
                if self.decode_steps else 0.0)

    def report(self, top: int = 10) -> dict:
        """JSON-ready snapshot (``stats()["sanitizer"]``)."""
        sites = sorted(self.by_site.items(), key=lambda kv: (-kv[1], kv[0]))
        return {
            "strict": self.strict,
            "decode_steps": self.decode_steps,
            "host_transfers": self.host_transfers,
            "per_decode_step": round(self.per_decode_step(), 3),
            "by_site": dict(sites[:top]),
            "guard_violations": self.guard_violations,
        }
