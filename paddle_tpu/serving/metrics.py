"""Serving metrics: the observability layer of the serving engine.

Counters and latency distributions a production deployment exports per
engine: time-to-first-token (TTFT), inter-token latency (ITL), decode
throughput, queue depth, slot occupancy, the compile-executable cache
hit/miss counters that back the zero-recompile steady-state guarantee,
and the failure-path counters of the resilience layer (failed/cancelled/
rejected requests, deadline expiries, callback errors, step failures and
retries) plus the engine's ``health()`` snapshot.

``FleetMetrics`` is the same idea one level up: per-fleet supervision
counters (dispatches and affinity hit rate, ejections, rebuilds,
redispatches, failover recovery time) plus a per-replica occupancy table
fed by the router — ``profiler.serving_fleet()`` aggregates every live
fleet.

``snapshot()`` returns a ``/stats``-style plain dict (JSON-serializable).
Each ``ServingMetrics`` registers itself with ``paddle_tpu.profiler`` so
``profiler.serving_stats()`` aggregates every live engine in the process.
"""
from __future__ import annotations

import copy
import time
from collections import deque
from typing import Dict, Optional

__all__ = ["ServingMetrics", "FleetMetrics"]

# Latency distributions keep a bounded sliding window (a long-running
# engine must not grow host memory with traffic); the cumulative totals
# live in the counters.
_LATENCY_WINDOW = 4096


def _dist(xs) -> Dict[str, float]:
    if not xs:
        return {"count": 0, "mean": 0.0, "p50": 0.0, "p99": 0.0, "max": 0.0}
    s = sorted(xs)
    n = len(s)

    def q(p):
        return s[min(n - 1, int(p * (n - 1) + 0.5))]

    return {"count": n, "mean": sum(s) / n, "p50": q(0.5), "p99": q(0.99),
            "max": s[-1]}


class ServingMetrics:
    """Mutable metric sink for one ``serving.Engine``."""

    def __init__(self, name: str = "engine", num_slots: int = 1):
        self.name = name
        self.num_slots = num_slots
        self.t_start = time.perf_counter()
        # counters
        self.requests_enqueued = 0
        self.requests_admitted = 0
        self.requests_completed = 0
        # failure-path counters (the resilience layer's observability:
        # every rejection/cancellation/deadline/retry is visible here)
        self.requests_failed = 0
        self.requests_cancelled = 0
        self.requests_rejected = 0
        self.deadline_expired = 0
        self.callback_errors = 0
        # overload regime (ISSUE 8): preemption evictions and SLO-shed
        # admissions (sheds also count as rejections — a shed IS a
        # rejection, this counter distinguishes the cause)
        self.requests_preempted = 0
        self.requests_shed = 0
        # durability (ISSUE 14): pre-crash terminal outcomes banked from
        # the request journal at recovery (folded into the live counters
        # so completed/failed stay MONOTONE across a process restart —
        # the same banking FleetMetrics does for ejected replicas), plus
        # recovery/hot-swap counters
        self.banked_outcomes: Dict[str, int] = {}
        self.requests_recovered = 0
        self.weight_swaps = 0
        self.model_version = 0
        self.step_failures = 0
        self.step_retries = 0
        self.retries_by_point: Dict[str, int] = {}
        # speculative decoding (ISSUE 15): per-round proposal/acceptance
        # counters — the multiplicative-win observability (accept rate ×
        # (k+1) bounds the target-step savings); spec_cb (set by the
        # engine when speculation is on) contributes the config half
        self.spec_rounds = 0
        self.spec_draft_steps = 0
        self.spec_verify_steps = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_cb = None
        # multi-tenant serving (ISSUE 20): per-tenant SLO accounting —
        # tenant label = adapter name / "grammar:<name>" / "base" — plus
        # the adapter lifecycle counters.  Tenants appear on first
        # traffic; a single-tenant engine exports {"base": ...} only.
        self.tenants: Dict[str, dict] = {}
        self.adapter_loads = 0
        self.adapter_unloads = 0
        # engine-provided liveness snapshot (set by serving.Engine)
        self.health_cb = None
        # paged-KV observability (set by serving.Engine in paged mode):
        # block-pool occupancy, eviction, copy-on-extend, and prefix-hit
        # counters, exported as the snapshot's "paging" section
        self.paging_cb = None
        self.prefix_lookup_errors = 0
        self.prefix_register_errors = 0
        self.prefill_tokens = 0
        self.decode_tokens = 0
        self.decode_steps = 0
        self.compile_hits = 0
        self.compile_misses = 0
        self.prefills_by_bucket: Dict[int, int] = {}
        # gauges / distributions
        self.queue_depth = 0
        self.queue_depth_max = 0
        self.ttft_s: deque = deque(maxlen=_LATENCY_WINDOW)
        self.itl_s: deque = deque(maxlen=_LATENCY_WINDOW)
        self.decode_time_s = 0.0
        self.prefill_time_s = 0.0
        self._occupancy_sum = 0.0
        self._occupancy_samples = 0
        self._slots_busy = 0
        from .. import profiler as _profiler

        _profiler._register_serving_metrics(self)

    # -- recording hooks ---------------------------------------------------

    def on_enqueue(self, depth: int) -> None:
        self.requests_enqueued += 1
        self.queue_depth = depth
        self.queue_depth_max = max(self.queue_depth_max, depth)

    def on_admit(self, bucket: int, prompt_len: int, depth: int) -> None:
        self.requests_admitted += 1
        self.prefill_tokens += prompt_len
        self.prefills_by_bucket[bucket] = \
            self.prefills_by_bucket.get(bucket, 0) + 1
        self.queue_depth = depth

    def _tenant(self, tenant: str) -> dict:
        t = self.tenants.get(tenant)
        if t is None:
            t = self.tenants[tenant] = {
                "ttft_s": deque(maxlen=_LATENCY_WINDOW),
                "completed": 0, "failed": 0, "tokens": 0,
            }
        return t

    def on_first_token(self, ttft_s: float,
                       tenant: Optional[str] = None) -> None:
        self.ttft_s.append(ttft_s)
        if tenant is not None:
            self._tenant(tenant)["ttft_s"].append(ttft_s)

    def on_decode_step(self, n_active: int, step_s: float) -> None:
        self.decode_steps += 1
        self.decode_tokens += n_active
        self.decode_time_s += step_s
        # per-token latency for each active stream is the step latency
        self.itl_s.extend([step_s] * n_active)

    def on_spec_round(self, step_s: float, *, draft_steps: int,
                      proposed: int, accepted: int,
                      delivered) -> None:
        """One speculative round: ``draft_steps`` draft dispatches + one
        verify dispatch emitted ``delivered[i]`` tokens per active slot
        (``accepted`` of the ``proposed`` draft tokens survived
        verification; emitted = accepted + one bonus/resample each,
        minus any stop-token truncation).  Folds into the same decode
        token/time counters as plain decode steps so
        ``decode_tokens_per_sec`` and the ITL window stay comparable
        across modes (a burst of n tokens in one round prices each at
        step_s / n)."""
        self.spec_rounds += 1
        self.spec_draft_steps += int(draft_steps)
        self.spec_verify_steps += 1
        self.spec_proposed += int(proposed)
        self.spec_accepted += int(accepted)
        self.decode_steps += 1
        self.decode_time_s += step_s
        for n in delivered:
            if n > 0:
                self.decode_tokens += n
                self.itl_s.extend([step_s / n] * n)

    def on_complete(self, tenant: Optional[str] = None,
                    n_tokens: int = 0) -> None:
        self.requests_completed += 1
        if tenant is not None:
            t = self._tenant(tenant)
            t["completed"] += 1
            t["tokens"] += int(n_tokens)

    def on_fail(self, tenant: Optional[str] = None) -> None:
        self.requests_failed += 1
        if tenant is not None:
            self._tenant(tenant)["failed"] += 1

    def on_adapter_load(self, name: str, version: int) -> None:
        """A LoRA adapter was loaded (or hot-swapped) into a pool lane."""
        self.adapter_loads += 1

    def on_adapter_unload(self, name: str, version: int) -> None:
        self.adapter_unloads += 1

    def on_cancel(self) -> None:
        self.requests_cancelled += 1

    def on_reject(self) -> None:
        self.requests_rejected += 1

    def on_deadline(self) -> None:
        self.deadline_expired += 1

    def on_preempt(self, depth: int) -> None:
        """A running request was evicted for a higher-priority admission
        and requeued (NOT a terminal outcome — the request resumes)."""
        self.requests_preempted += 1
        self.queue_depth = depth
        self.queue_depth_max = max(self.queue_depth_max, depth)

    def on_shed(self) -> None:
        """An admission was SLO-shed: its estimated queue wait already
        exceeded its deadline, so it was rejected with ``retry_after_s``
        instead of prefilled doomed."""
        self.requests_shed += 1

    def bank_outcomes(self, outcomes: Dict[str, int]) -> None:
        """Fold a recovered journal's pre-crash terminal counts into the
        live counters (``Engine.recover``): a restarted engine's
        ``requests_completed``/``requests_failed`` continue from where
        the crashed process left off instead of resetting to zero.  The
        raw banked dict stays visible in the snapshot for auditing."""
        total = 0
        for state, n in outcomes.items():
            self.banked_outcomes[state] = \
                self.banked_outcomes.get(state, 0) + int(n)
            total += int(n)
        # the pipeline counters move together so derived gauges
        # (in-flight = enqueued - terminal, completion rate) stay sane:
        # every banked outcome was enqueued — and, rejections aside,
        # admitted — in the crashed process (the fleet-side bank adds
        # to `submitted` for the same reason)
        self.requests_enqueued += total
        self.requests_admitted += total - int(outcomes.get("rejected", 0))
        self.requests_completed += int(outcomes.get("finished", 0))
        self.requests_failed += int(outcomes.get("failed", 0))
        self.requests_cancelled += int(outcomes.get("cancelled", 0))
        self.requests_rejected += int(outcomes.get("rejected", 0))

    def on_recovered(self) -> None:
        """One journaled non-terminal request was rehydrated and
        re-enqueued by crash recovery."""
        self.requests_recovered += 1

    def on_weight_swap(self, version: int) -> None:
        """The engine's weights were hot-swapped in place (drained,
        written through the existing buffers, prefix epoch bumped)."""
        self.weight_swaps += 1
        self.model_version = int(version)

    def on_callback_error(self) -> None:
        self.callback_errors += 1

    def on_prefix_lookup_error(self) -> None:
        """A raising/over-budget prefix-cache lookup degraded to a miss
        (the request still prefills its full prompt)."""
        self.prefix_lookup_errors += 1

    def on_prefix_register_error(self) -> None:
        """Registering a prompt's blocks for future reuse failed — the
        request itself is unaffected, future requests just can't hit
        this prompt.  Counted apart from lookup errors so the two
        degradation modes stay distinguishable on a dashboard."""
        self.prefix_register_errors += 1

    def on_step_failure(self, point: str) -> None:
        self.step_failures += 1

    def on_retry(self, point: str) -> None:
        self.step_retries += 1
        self.retries_by_point[point] = \
            self.retries_by_point.get(point, 0) + 1

    def on_slots(self, busy: int) -> None:
        self._slots_busy = busy
        self._occupancy_sum += busy / max(self.num_slots, 1)
        self._occupancy_samples += 1

    def on_compile(self, miss: bool) -> None:
        if miss:
            self.compile_misses += 1
        else:
            self.compile_hits += 1

    # -- export ------------------------------------------------------------

    def tokens_per_sec(self) -> float:
        return self.decode_tokens / self.decode_time_s \
            if self.decode_time_s > 0 else 0.0

    def _paging_section(self):
        """Engine-fed paged-KV gauges (None for the contiguous layout)."""
        if self.paging_cb is None:
            return None
        out = self.paging_cb()
        out["prefix_lookup_errors"] = self.prefix_lookup_errors
        out["prefix_register_errors"] = self.prefix_register_errors
        return out

    def _speculation_section(self):
        """Speculative-decoding counters (None when speculation is off —
        the snapshot shape says which mode served the traffic)."""
        if self.spec_cb is None:
            return None
        out = dict(self.spec_cb())
        out.update({
            "rounds": self.spec_rounds,
            "draft_steps": self.spec_draft_steps,
            "verify_steps": self.spec_verify_steps,
            "proposed": self.spec_proposed,
            "accepted": self.spec_accepted,
            "accept_rate": round(
                self.spec_accepted / self.spec_proposed, 4)
            if self.spec_proposed else 0.0,
            "mean_accepted_per_round": round(
                self.spec_accepted / self.spec_rounds, 4)
            if self.spec_rounds else 0.0,
        })
        return out

    def _tenants_section(self) -> dict:
        """Per-tenant SLO gauges keyed by tenant label, plus the adapter
        lifecycle counters — always present (empty ``by_tenant`` before
        the first tenant-labelled request) so dashboards can bind to the
        shape unconditionally."""
        by_tenant = {}
        for name in sorted(self.tenants):
            t = self.tenants[name]
            by_tenant[name] = {
                "completed": t["completed"],
                "failed": t["failed"],
                "tokens": t["tokens"],
                "ttft_ms": {k: round(v * 1e3, 3) if k != "count" else v
                            for k, v in _dist(t["ttft_s"]).items()},
            }
        return {"adapter_loads": self.adapter_loads,
                "adapter_unloads": self.adapter_unloads,
                "by_tenant": by_tenant}

    def occupancy(self) -> float:
        """Mean busy-slot fraction over all samples so far (0.0 before
        the first step) — shared by ``snapshot()`` and the fleet
        router's per-replica table."""
        return self._occupancy_sum / self._occupancy_samples \
            if self._occupancy_samples else 0.0

    def snapshot(self) -> dict:
        """The ``/stats`` endpoint payload: one JSON-ready dict.  Latency
        distributions cover the last ``_LATENCY_WINDOW`` samples.

        **Copy-on-read guarantee** (ISSUE 9): the returned structure
        shares NO mutable state with the engine — every nested dict and
        list is deep-copied, so a caller mutating (or json-mangling) a
        snapshot can never corrupt live counters, allocator gauges, or
        a health/paging callback's backing store."""
        occ = self.occupancy()
        return copy.deepcopy({
            "name": self.name,
            "uptime_s": round(time.perf_counter() - self.t_start, 3),
            "requests": {
                "enqueued": self.requests_enqueued,
                "admitted": self.requests_admitted,
                "completed": self.requests_completed,
                "running": self._slots_busy,
            },
            "failures": {
                "failed": self.requests_failed,
                "cancelled": self.requests_cancelled,
                "rejected": self.requests_rejected,
                "deadline_expired": self.deadline_expired,
                "callback_errors": self.callback_errors,
                "step_failures": self.step_failures,
                "step_retries": self.step_retries,
                "retries_by_point": dict(sorted(
                    self.retries_by_point.items())),
            },
            "health": self.health_cb() if self.health_cb is not None
            else None,
            "overload": {"preemptions": self.requests_preempted,
                         "shed": self.requests_shed},
            "durability": {
                "recovered": self.requests_recovered,
                "banked": dict(sorted(self.banked_outcomes.items())),
                "weight_swaps": self.weight_swaps,
                "model_version": self.model_version,
            },
            "paging": self._paging_section(),
            "speculation": self._speculation_section(),
            "tenants": self._tenants_section(),
            "queue_depth": self.queue_depth,
            "queue_depth_max": self.queue_depth_max,
            "slot_occupancy": round(occ, 4),
            "slots": {"total": self.num_slots, "busy": self._slots_busy},
            "tokens": {"prefill": self.prefill_tokens,
                       "decode": self.decode_tokens},
            "decode_tokens_per_sec": round(self.tokens_per_sec(), 2),
            "ttft_ms": {k: round(v * 1e3, 3) if k != "count" else v
                        for k, v in _dist(self.ttft_s).items()},
            "inter_token_ms": {k: round(v * 1e3, 3) if k != "count" else v
                               for k, v in _dist(self.itl_s).items()},
            "prefills_by_bucket": dict(sorted(
                self.prefills_by_bucket.items())),
            "compile_cache": {"hits": self.compile_hits,
                              "misses": self.compile_misses},
        })


class FleetMetrics:
    """Mutable metric sink for one ``serving.router.Fleet``.

    Counts fleet-level request outcomes (terminal states are recorded
    here exactly once per request — ``duplicate_terminals`` existing at
    all is the audit that the exactly-once contract held), dispatch
    decisions (total / prefix-affinity / operator-pinned), and the
    supervision loop's actions: ejections, rebuilds (with the measured
    eject→rejoin recovery time — the failover number the serving bench
    reports), and request redispatches.
    """

    def __init__(self, name: str = "fleet", num_replicas: int = 1):
        self.name = name
        self.num_replicas = num_replicas
        self.t_start = time.perf_counter()
        # request outcomes (fleet-level, exactly once per request)
        self.submitted = 0
        self.completed = 0
        self.failed = 0
        self.cancelled = 0
        self.rejected = 0
        self.duplicate_terminals = 0     # must stay 0: exactly-once audit
        # dispatch decisions
        self.dispatches = 0
        self.affinity_hits = 0
        self.affinity_hit_tokens = 0
        self.pinned_dispatches = 0
        # supervision
        self.redispatches = 0
        self.ejections = 0
        self.rebuilds = 0
        self.rebuild_failures = 0
        self.last_recovery_s: Optional[float] = None
        self.total_recovery_s = 0.0
        # degraded-mode sharded serving: shard-group rebuilds at a
        # smaller viable mp after device loss
        self.degrades = 0
        self.last_degrade_old_mp: Optional[int] = None
        self.last_degrade_mp: Optional[int] = None
        self.last_degrade_s: Optional[float] = None
        self.total_degrade_s = 0.0
        # durability (ISSUE 14): crash recovery + rolling weight rolls
        self.banked_outcomes: Dict[str, int] = {}
        self.requests_recovered = 0
        self.crash_recoveries = 0
        self.last_crash_recovery_s: Optional[float] = None
        self.weight_rolls = 0
        self.last_roll_s: Optional[float] = None
        self.model_version = 0
        # router-provided per-replica table (occupancy, state, queue)
        self.replicas_cb = None
        # router-provided banked flight-recorder dumps, keyed by engine
        # name — merged into profiler.serving_flight_record() so an
        # ejected engine's post-mortem outlives the engine
        self.flight_cb = None
        from .. import profiler as _profiler

        _profiler._register_fleet_metrics(self)

    # -- recording hooks ---------------------------------------------------

    def on_submit(self) -> None:
        self.submitted += 1

    def on_terminal(self, state: str) -> None:
        if state == "finished":
            self.completed += 1
        elif state == "failed":
            self.failed += 1
        elif state == "cancelled":
            self.cancelled += 1
        elif state == "rejected":
            self.rejected += 1

    def on_duplicate_terminal(self) -> None:
        self.duplicate_terminals += 1

    def on_dispatch(self, affinity_tokens: int = 0,
                    pinned: bool = False) -> None:
        self.dispatches += 1
        if pinned:
            self.pinned_dispatches += 1
        elif affinity_tokens > 0:
            self.affinity_hits += 1
            self.affinity_hit_tokens += affinity_tokens

    def on_redispatch(self) -> None:
        self.redispatches += 1

    def on_eject(self) -> None:
        self.ejections += 1

    def on_rebuild(self, recovery_s: float, ok: bool = True) -> None:
        if ok:
            self.rebuilds += 1
            self.last_recovery_s = recovery_s
            self.total_recovery_s += recovery_s
        else:
            self.rebuild_failures += 1

    def on_degrade(self, old_mp: int, new_mp: int,
                   recovery_s: float) -> None:
        """A shard group was rebuilt DEGRADED — at ``new_mp < old_mp``
        on its surviving devices after device loss.  ``recovery_s`` is
        the same eject→rejoin wall time ``on_rebuild`` records (every
        degrade is also counted as a rebuild)."""
        self.degrades += 1
        self.last_degrade_old_mp = int(old_mp)
        self.last_degrade_mp = int(new_mp)
        self.last_degrade_s = recovery_s
        self.total_degrade_s += recovery_s

    def bank_outcomes(self, outcomes: Dict[str, int]) -> None:
        """Fold a recovered journal's pre-crash FINAL terminal counts
        into the fleet counters (``Fleet.recover``) so completed/failed
        stay monotone across a process restart — the same scheme the
        fleet already uses to bank an ejected replica's preemptions."""
        total = 0
        for state, n in outcomes.items():
            self.banked_outcomes[state] = \
                self.banked_outcomes.get(state, 0) + int(n)
            total += int(n)
        self.submitted += total
        self.completed += int(outcomes.get("finished", 0))
        self.failed += int(outcomes.get("failed", 0))
        self.cancelled += int(outcomes.get("cancelled", 0))
        self.rejected += int(outcomes.get("rejected", 0))

    def on_crash_recovery(self, replayed: int, recovery_s: float) -> None:
        self.crash_recoveries += 1
        self.requests_recovered += int(replayed)
        self.last_crash_recovery_s = recovery_s

    def on_weight_roll(self, version: int, roll_s: float) -> None:
        self.weight_rolls += 1
        self.last_roll_s = roll_s
        self.model_version = int(version)

    # -- export ------------------------------------------------------------

    def affinity_hit_rate(self) -> float:
        """Fraction of ROUTED dispatches (operator pins excluded — they
        bypass the policy) that landed on a replica already holding a
        prompt prefix."""
        routed = self.dispatches - self.pinned_dispatches
        return self.affinity_hits / routed if routed else 0.0

    def snapshot(self) -> dict:
        """JSON-ready fleet snapshot, deep-copied like
        :meth:`ServingMetrics.snapshot` (copy-on-read: mutating it
        cannot corrupt the fleet's live counters or replica table)."""
        return copy.deepcopy({
            "name": self.name,
            "uptime_s": round(time.perf_counter() - self.t_start, 3),
            "requests": {
                "submitted": self.submitted,
                "completed": self.completed,
                "failed": self.failed,
                "cancelled": self.cancelled,
                "rejected": self.rejected,
                "duplicate_terminals": self.duplicate_terminals,
            },
            "dispatch": {
                "total": self.dispatches,
                "affinity_hits": self.affinity_hits,
                "affinity_hit_tokens": self.affinity_hit_tokens,
                "affinity_hit_rate": round(self.affinity_hit_rate(), 4),
                "pinned": self.pinned_dispatches,
                "redispatches": self.redispatches,
            },
            "supervision": {
                "ejections": self.ejections,
                "rebuilds": self.rebuilds,
                "rebuild_failures": self.rebuild_failures,
                "last_recovery_ms": None if self.last_recovery_s is None
                else round(self.last_recovery_s * 1e3, 3),
                "total_recovery_ms": round(self.total_recovery_s * 1e3, 3),
            },
            "degraded": {
                "degrades": self.degrades,
                "last_old_mp": self.last_degrade_old_mp,
                "last_mp": self.last_degrade_mp,
                "last_degrade_ms": None if self.last_degrade_s is None
                else round(self.last_degrade_s * 1e3, 3),
                "total_degrade_ms": round(self.total_degrade_s * 1e3, 3),
            },
            "durability": {
                "crash_recoveries": self.crash_recoveries,
                "recovered": self.requests_recovered,
                "last_crash_recovery_ms":
                    None if self.last_crash_recovery_s is None
                    else round(self.last_crash_recovery_s * 1e3, 3),
                "banked": dict(sorted(self.banked_outcomes.items())),
                "weight_rolls": self.weight_rolls,
                "last_roll_ms": None if self.last_roll_s is None
                else round(self.last_roll_s * 1e3, 3),
                "model_version": self.model_version,
            },
            "replicas": (self.replicas_cb()
                         if self.replicas_cb is not None else None),
        })
