"""Durable request journal: a crash-consistent write-ahead log of
request lifecycle.

PR 6's fleet survives its *replicas* — an in-process engine can wedge,
corrupt its pool, or fail its compiled step, and the supervisor ejects
and rebuilds it without losing a request.  The process boundary was the
end of that story: an OOM-kill, host reboot, or watchdog ``os._exit``
silently dropped every queued and in-flight request.
:class:`RequestJournal` moves the line one ring out, the same way PR 2's
CRC generation checkpoints did for training: every accepted request is
journaled durably enough that a *fresh process* can rehydrate it
(``Engine.recover`` / ``Fleet.recover``) and replay it from its prompt
under the established stream-restart contract — restart at token 0,
``recovered`` flag set, the journaled effective seed making greedy and
seeded outputs bitwise identical to an uninterrupted run.

Format — append-only segments of CRC-framed JSON lines:

- A journal is a **directory** of segment files ``seg-<n>.jrnl``; each
  record is one line ``<crc32 hex8> <json>\\n`` with the CRC computed
  over the exact JSON payload bytes.  A process killed mid-write can
  tear at most the FINAL record of the FINAL segment; the scanner
  truncates exactly that (counted in ``torn_records``) and treats any
  *interior* CRC/parse failure as real corruption
  (:class:`JournalCorrupt`) rather than guessing.
- Record kinds: ``admit`` (the full replay recipe: prompt ids,
  ``SamplingParams`` + the *effective* seed, priority, deadline,
  ``max_new_tokens``, eos, model version), ``tokens`` (BATCHED — one
  record per engine step covering every delivered slot, never one per
  token), ``restart`` (a preemption reset the stream mid-engine),
  ``end`` (terminal; ``final`` false for engine-level attempt ends of
  fleet-owned requests — the router's exactly-once ``_finish`` writes
  the one final), and ``weights`` (a hot-swap version bump).
- **Segment rotation + compaction**: the active segment rotates after
  ``segment_records`` appends; on rotation (and on explicit
  :meth:`compact`) the longest *prefix* of closed segments whose every
  referenced request is final — with all of its records inside that
  prefix — is deleted.  A long-lived journal therefore holds only the
  segments still needed to replay non-terminal work.
- **fsync policy** (``fsync=``): ``"always"`` fsyncs every append (the
  power-loss bar), ``"rotate"`` (default) fsyncs at segment
  rotation/close, ``"never"`` leaves it to the OS.  Every append is
  ``flush()``-ed regardless, so records survive process death (SIGKILL
  included) under every policy — fsync only adds the machine-crash
  guarantee.

What is deliberately NOT durable (documented in docs/SERVING.md):
stream *delivery* is at-least-once across a crash (a token streamed a
microsecond before the kill is streamed again, from token 0, on the
recovered run), per-request wall-clock deadlines restart at recovery
(a replay is a fresh admission, the redispatch contract), and rejected
requests are never journaled — their rejection was already delivered
synchronously to the caller.

Everything here is host-side file I/O on the scheduler thread, outside
the ``# tpulint: hot-path`` dispatch functions: journaling adds zero
device syncs and zero compile keys (the shape manifest stays
byte-identical).
"""
from __future__ import annotations

import json
import os
import time
import zlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Union

__all__ = ["RequestJournal", "JournalCorrupt"]

_FSYNC_POLICIES = ("always", "rotate", "never")
_SEG_FMT = "seg-%08d.jrnl"


class JournalCorrupt(RuntimeError):
    """An *interior* journal record failed its CRC or JSON framing.
    Only the final record of the final segment may legally be torn (a
    crash mid-append); anything else means the log was tampered with or
    the storage corrupted it, and recovery refuses to guess."""


def _seg_index(fname: str) -> Optional[int]:
    if not (fname.startswith("seg-") and fname.endswith(".jrnl")):
        return None
    try:
        return int(fname[4:-5])
    except ValueError:
        return None


class RequestJournal:
    """Append-only CRC-per-record WAL of serving request lifecycle.

    One journal serves one engine or one whole fleet (pass the same
    instance to ``Engine(journal=...)`` / ``Fleet(journal=...)``).
    Reopening an existing journal directory scans every segment,
    rebuilds the pending/terminal request state, and continues
    appending into a FRESH segment — a possibly-torn tail segment is
    never appended to.

    Args:
        path: journal directory (created if absent).
        fsync: ``"always" | "rotate" | "never"`` — see module docstring.
        segment_records: appends per segment before rotation (rotation
            also triggers compaction of fully-terminal prefix segments).
    """

    def __init__(self, path: str, *, fsync: str = "rotate",
                 segment_records: int = 4096):
        if fsync not in _FSYNC_POLICIES:
            raise ValueError(f"fsync must be one of {_FSYNC_POLICIES}, "
                             f"got {fsync!r}")
        if segment_records < 1:
            raise ValueError("segment_records must be >= 1, "
                             f"got {segment_records}")
        self.path = str(path)
        self.fsync = fsync
        self.segment_records = int(segment_records)
        os.makedirs(self.path, exist_ok=True)
        # replay state, rebuilt from disk on open
        self._admissions: "OrderedDict[str, dict]" = OrderedDict()
        self._tokens: Dict[str, List[int]] = {}
        self._finals: Dict[str, int] = {}
        self._final_state: Dict[str, str] = {}
        self._seg_jids: Dict[int, set] = {}
        self._jid_max_seg: Dict[str, int] = {}
        self._jid_final_seg: Dict[str, int] = {}
        self._fleet_ids: set = set()
        self._pending = None         # (jid, recovered, origin_wall)
        self.torn_records = 0
        self.records_read = 0
        self.records_written = 0
        self.compacted_segments = 0
        #: mesh_reshard records seen (written + rescanned): how many
        #: times recovery replayed this journal's pending work across a
        #: mesh-shape change (degraded-mode sharded serving)
        self.mesh_reshards = 0
        # aggregate counters for requests whose records left the disk
        # (compaction prunes their per-jid state too — the in-memory
        # maps stay bounded by the UN-compacted suffix, not by all-time
        # traffic).  Persisted as a CUMULATIVE "compacted" record in the
        # active segment at every compaction, so outcomes()/audit() —
        # and therefore the banked-counter monotonicity recovery
        # promises — survive both rotation and reopen.
        self._compacted_admitted = 0
        self._compacted_outcomes: Dict[str, int] = {}
        self._compacted_duplicates = 0
        self._in_compact = False
        self._closed_segments: List[int] = []
        existing = sorted(i for i in (
            _seg_index(f) for f in os.listdir(self.path)) if i is not None)
        for idx in existing:
            self._scan_segment(idx, last=(idx == existing[-1]))
        self._closed_segments = existing
        #: monotonically-increasing reopen marker: the first segment
        #: index this instance writes.  Engines/fleets mix it into
        #: generated journal ids so a fresh process (whose request
        #: counters restart at 0) can never collide with pre-crash ids.
        self.boot = (existing[-1] + 1) if existing else 1
        self._seg = None
        self._seg_count = 0
        self._seg_cur = self.boot - 1
        self._open_segment()
        # crash artifacts land next to the journal when no trace dir is
        # configured (obs.crashdump: "a crash/ sibling of the journal")
        from ..obs import crashdump

        crashdump.register_journal_dir(self.path)

    # -- low-level append/scan ---------------------------------------------

    def _seg_path(self, idx: int) -> str:
        return os.path.join(self.path, _SEG_FMT % idx)

    def _open_segment(self) -> None:
        self._seg_cur += 1
        self._seg_count = 0
        self._seg_jids.setdefault(self._seg_cur, set())
        self._seg = open(self._seg_path(self._seg_cur), "a",
                         encoding="utf-8")

    def _append(self, rec: dict) -> None:
        payload = json.dumps(rec, separators=(",", ":"), sort_keys=True)
        data = payload.encode("utf-8")
        self._seg.write(f"{zlib.crc32(data) & 0xFFFFFFFF:08x} {payload}\n")
        # flush ALWAYS: the OS page cache survives process death, so a
        # flushed record survives SIGKILL under every fsync policy
        self._seg.flush()
        if self.fsync == "always":
            os.fsync(self._seg.fileno())
        self.records_written += 1
        self._seg_count += 1
        self._track(rec, self._seg_cur)
        if self._seg_count >= self.segment_records and \
                not self._in_compact:    # compact()'s own record defers
            self._rotate()               # rotation to the next append

    def _rotate(self) -> None:
        if self.fsync in ("always", "rotate"):
            os.fsync(self._seg.fileno())
        self._seg.close()
        self._closed_segments.append(self._seg_cur)
        # open the next segment BEFORE compacting: compaction persists
        # its cumulative-outcomes record into the active segment
        self._open_segment()
        self.compact()

    def _track(self, rec: dict, seg: int) -> None:
        """Fold one record into the in-memory replay state."""
        kind = rec.get("kind")
        jids = []
        if kind == "admit":
            jid = rec["jid"]
            jids = [jid]
            # latest admission wins (redispatch/recovery re-admits) but
            # the ORIGINAL arrival order is kept for replay fairness
            self._admissions[jid] = rec
            self._tokens[jid] = []
        elif kind == "tokens":
            for jid, tok in rec.get("toks", {}).items():
                if isinstance(tok, list):        # speculative burst
                    self._tokens.setdefault(jid, []).extend(
                        int(x) for x in tok)
                else:
                    self._tokens.setdefault(jid, []).append(int(tok))
                jids.append(jid)
        elif kind == "restart":
            jid = rec["jid"]
            jids = [jid]
            self._tokens[jid] = []
        elif kind == "end":
            jid = rec["jid"]
            jids = [jid]
            if rec.get("final", True):
                self._finals[jid] = self._finals.get(jid, 0) + 1
                self._final_state[jid] = rec.get("state", "finished")
                self._jid_final_seg[jid] = seg
        elif kind == "mesh_reshard":
            # a shape-change replay: reference every disposed request so
            # segment containment (and therefore compaction) treats this
            # record as part of each request's history
            jids = list(rec.get("requests", {}))
            self.mesh_reshards += 1
        elif kind == "compacted":
            # CUMULATIVE totals for everything compaction ever pruned:
            # replace-semantics (later records supersede earlier ones),
            # so dropping an old compacted record with its segment is
            # harmless — every compact() writes a fresh one
            self._compacted_admitted = int(rec.get("admitted", 0))
            self._compacted_outcomes = {
                k: int(v) for k, v in rec.get("finals", {}).items()}
            self._compacted_duplicates = int(rec.get("duplicates", 0))
        for jid in jids:
            self._seg_jids.setdefault(seg, set()).add(jid)
            self._jid_max_seg[jid] = seg

    def _scan_segment(self, idx: int, last: bool) -> None:
        path = self._seg_path(idx)
        with open(path, "rb") as f:
            raw = f.read()
        lines = raw.split(b"\n")
        # a well-formed file ends with a newline → final split is empty
        tail_complete = lines and lines[-1] == b""
        if tail_complete:
            lines = lines[:-1]
        consumed = 0                     # bytes of committed records
        for i, line in enumerate(lines):
            is_final_line = (i == len(lines) - 1)
            rec, torn = None, None
            if len(line) < 10 or line[8:9] != b" ":
                torn = "framing"
            else:
                payload = line[9:]
                try:
                    want = int(line[:8], 16)
                except ValueError:
                    want, torn = None, "crc framing"
                if torn is None:
                    if (zlib.crc32(payload) & 0xFFFFFFFF) != want:
                        torn = "crc mismatch"
                    else:
                        try:
                            rec = json.loads(payload.decode("utf-8"))
                        except (ValueError, UnicodeDecodeError):
                            torn = "json parse"
            if torn is None and is_final_line and not tail_complete:
                # a record missing its newline is a cut-short append
                # even when its CRC frames (the terminator is part of
                # the commit) — treat it exactly like a torn record
                torn = "missing newline"
            if torn is not None:
                if last and is_final_line:
                    self.torn_records += 1
                    # truncate the torn bytes ON DISK: this segment
                    # stops being the last one the moment we open a
                    # fresh segment, and a later reopen would then read
                    # the tear as interior corruption.  Best-effort — a
                    # read-only reopen still tolerates it in memory.
                    try:
                        with open(path, "r+b") as f:
                            f.truncate(consumed)
                            f.flush()
                            os.fsync(f.fileno())
                    except OSError:
                        pass
                    return
                raise JournalCorrupt(
                    f"{path} line {i + 1}: {torn} on an interior record "
                    "(only the final record of the final segment may be "
                    "torn)")
            consumed += len(line) + 1
            self.records_read += 1
            self._track(rec, idx)

    # -- lifecycle records (engine/router-facing) ----------------------------

    def record_admission(self, jid: str, *, prompt_ids, sampling: dict,
                         seed_effective: int, priority: int,
                         deadline_s: Optional[float],
                         max_new_tokens: int,
                         eos_token_id: Optional[int], engine: str,
                         model_version: int,
                         recovered: bool = False,
                         mesh_shape: Optional[str] = None,
                         adapter_version: Optional[int] = None) -> None:
        """The replay recipe: everything a fresh process needs to
        re-admit this request bitwise (``seed_effective`` is the seed
        ``Engine._seed_for`` resolved at THIS admission, so an unseeded
        temperature request replays the same stream it was drawing).

        ``mesh_shape`` is the sharded engine's mesh-shape key
        (``"model=2"``) — recorded only when set, so unsharded journals
        are byte-identical to pre-sharding ones, and recovery can refuse
        to replay a sharded admission onto a different topology.
        Tenancy rides the same only-when-set discipline: the sampling
        dict's ``adapter``/``grammar`` keys and the top-level
        ``adapter_version`` appear only for tenant requests, so
        base-tenant records stay byte-identical to pre-tenancy ones —
        and recovery replays a tenant request ONLY onto the exact
        journaled adapter version (bitwise or not at all)."""
        s = dict(sampling)
        extra = {} if mesh_shape is None else {"mesh_shape": mesh_shape}
        if adapter_version is not None:
            extra["adapter_version"] = int(adapter_version)
        samp = {
            "temperature": float(s.get("temperature", 0.0)),
            "top_k": int(s.get("top_k", 0)),
            "top_p": float(s.get("top_p", 1.0)),
            "seed": (None if s.get("seed") is None
                     else int(s["seed"])),
        }
        if s.get("adapter") is not None:
            samp["adapter"] = str(s["adapter"])
        if s.get("grammar") is not None:
            samp["grammar"] = str(s["grammar"])
        self._append({
            **extra,
            "kind": "admit", "jid": jid, "wall": round(time.time(), 6),
            "prompt_ids": [int(t) for t in prompt_ids],
            # plain-python coercion: numpy scalars are not JSON
            "sampling": samp,
            "seed_effective": int(seed_effective),
            "priority": int(priority),
            "deadline_s": (None if deadline_s is None
                           else float(deadline_s)),
            "max_new_tokens": int(max_new_tokens),
            "eos_token_id": (None if eos_token_id is None
                             else int(eos_token_id)),
            "engine": engine,
            "model_version": int(model_version),
            "recovered": bool(recovered),
        })

    def record_tokens(self, engine: str, step: int,
                      toks: Dict[str, Union[int, Sequence[int]]]) -> None:
        """One BATCHED record per engine step: every slot's delivered
        token keyed by journal id (never one record per token).  A
        speculative round delivers a BURST per slot — the value may be
        a list of ints (one record per round, the same batching
        discipline; scan-side the burst appends in order)."""
        self._append({"kind": "tokens", "engine": engine,
                      "step": int(step),
                      "toks": {j: ([int(x) for x in t]
                                   if isinstance(t, (list, tuple))
                                   else int(t))
                               for j, t in toks.items()}})

    def record_restart(self, jid: str, reason: str = "preempt") -> None:
        """The stream restarted from token 0 mid-engine (preemption):
        tokens journaled before this record are superseded."""
        self._append({"kind": "restart", "jid": jid, "reason": reason})

    def record_end(self, jid: str, state: str, *, final: bool = True,
                   error: Optional[str] = None, n_tokens: int = 0,
                   engine: Optional[str] = None) -> None:
        """Terminal record.  ``final=False`` marks an engine-level
        attempt end of a fleet-owned request (the router replays it or
        writes the one final end itself)."""
        rec = {"kind": "end", "jid": jid, "state": state,
               "final": bool(final), "n_tokens": int(n_tokens),
               "wall": round(time.time(), 6)}
        if error is not None:
            rec["error"] = str(error)[:500]
        if engine is not None:
            rec["engine"] = engine
        self._append(rec)

    def record_mesh_reshard(self, engine: str,
                            old_shape: Optional[str],
                            new_shape: Optional[str],
                            requests: Dict[str, str]) -> None:
        """Recovery replayed journaled work across a mesh-shape change
        (``old_shape`` → ``new_shape``, e.g. ``"model=2"`` →
        ``"model=1"`` after a degraded rebuild).  ``requests`` maps each
        affected journal id to its disposition (``"replayed"`` /
        ``"redispatched"`` / ``"failed"``) so ``audit()`` spans the
        degradation: every request is accounted for exactly once, on
        one side of the shape change or the other."""
        self._append({"kind": "mesh_reshard", "engine": engine,
                      "old_shape": old_shape, "new_shape": new_shape,
                      "requests": {str(j): str(d)
                                   for j, d in requests.items()},
                      "wall": round(time.time(), 6)})

    def record_weight_swap(self, engine: str, version: int) -> None:
        """A rolling hot-swap bumped this engine to ``version`` — KV
        prefilled before this record was computed under older weights
        (the prefix-cache epoch bump enforces that in-process; this
        record makes it auditable)."""
        self._append({"kind": "weights", "engine": engine,
                      "version": int(version),
                      "wall": round(time.time(), 6)})

    # -- adoption (router/recovery → engine), the tracer's pattern ----------

    def begin_attempt(self, jid: str, *, fleet_owned: bool = False,
                      recovered: bool = False,
                      origin_wall: Optional[float] = None) -> None:
        """Arm the adoption window around ONE ``engine.add_request``
        call: the admission record the engine writes inside it carries
        this journal id (and, for a recovery replay, the ``recovered``
        flag plus the pre-crash admission's wall stamp for the tracer's
        cross-process resume link)."""
        if fleet_owned:
            self._fleet_ids.add(jid)
        self._pending = (jid, bool(recovered), origin_wall)

    def end_attempt(self) -> None:
        self._pending = None

    def take_pending(self):
        """The armed adoption (or None) — read by ``Engine.add_request``;
        cleared by the router's ``end_attempt`` so a raising admission
        cannot leak the window onto an unrelated request."""
        return self._pending

    def is_fleet_owned(self, jid: str) -> bool:
        return jid in self._fleet_ids

    def has_admission(self, jid: str) -> bool:
        return jid in self._admissions

    # -- replay / audit -----------------------------------------------------

    @staticmethod
    def replay_sampling(rec: dict) -> dict:
        """The bitwise-replay sampling recipe for one admission record:
        the journaled ``SamplingParams`` fields with an unseeded
        request's seed backfilled from the journaled EFFECTIVE seed —
        the replay draws the exact stream the crashed attempt was
        drawing.  Shared by ``Engine.recover`` and ``Fleet.recover`` so
        the determinism contract cannot drift between them."""
        s = dict(rec["sampling"])
        if s.get("seed") is None:
            s["seed"] = rec["seed_effective"]
        # pre-tenancy records carry no adapter/grammar keys: backfill
        # None so SamplingParams(**s) stays constructible forever
        s.setdefault("adapter", None)
        s.setdefault("grammar", None)
        return s

    def tokens_for(self, jid: str) -> list:
        """Tokens journaled for ``jid`` since its last admission or
        restart record, in delivery order (speculative per-round bursts
        flattened) — the delivery audit surface."""
        return list(self._tokens.get(jid, []))

    def pending(self) -> "OrderedDict[str, dict]":
        """Non-terminal journaled requests — admission recorded, no
        FINAL end — keyed by journal id in original admission order.
        This is the recovery worklist ``Engine.recover`` /
        ``Fleet.recover`` rehydrates."""
        return OrderedDict(
            (jid, rec) for jid, rec in self._admissions.items()
            if not self._finals.get(jid))

    def outputs(self, jid: str) -> List[int]:
        """Tokens journaled for ``jid`` since its latest admission (or
        stream restart) — the delivered stream of the current attempt."""
        return list(self._tokens.get(jid, ()))

    def outcomes(self) -> Dict[str, int]:
        """Final terminal counts by state (``finished``/``failed``/...):
        what a recovered engine banks into its metrics so the counters
        stay monotone across the restart."""
        out = dict(self._compacted_outcomes)
        for jid, n in self._finals.items():
            if n:
                st = self._final_state.get(jid, "finished")
                out[st] = out.get(st, 0) + 1
        return out

    def audit(self) -> dict:
        """The exactly-once ledger: every admitted request must reach a
        final terminal at most once *ever* — across preemption,
        redispatch, AND process crashes.  ``duplicate_terminals`` > 0
        means the recovery contract was violated."""
        dup = self._compacted_duplicates + \
            sum(1 for n in self._finals.values() if n > 1)
        # finals counts final RECORDS: compacted jids contributed one
        # outcome each plus any duplicate records
        finals = sum(self._compacted_outcomes.values()) + \
            self._compacted_duplicates + sum(self._finals.values())
        return {
            "admitted": self._compacted_admitted + len(self._admissions),
            "pending": len(self.pending()),
            "finals": finals,
            "duplicate_terminals": dup,
            "torn_records": self.torn_records,
            "records_read": self.records_read,
            "records_written": self.records_written,
            "segments": len(self._closed_segments) + 1,
            "compacted_segments": self.compacted_segments,
            "mesh_reshards": self.mesh_reshards,
        }

    # -- compaction ---------------------------------------------------------

    def compact(self) -> int:
        """Delete the longest prefix of CLOSED segments in which every
        referenced request is final and entirely contained (its final
        end AND its last record are inside the prefix).  Returns how
        many segments were deleted.  The open segment never compacts."""
        droppable, seen = 0, set()
        for k, idx in enumerate(self._closed_segments):
            seen |= self._seg_jids.get(idx, set())
            if any(not self._finals.get(j) for j in seen):
                # a pending request: every larger prefix contains it
                # too, so nothing further can become droppable
                break
            # containment is judged against THIS candidate prefix's end
            # (a request may legally straddle a rotation boundary: its
            # admit in seg N and its final in seg N+1 drop together)
            if all(self._jid_max_seg.get(j, idx) <= idx and
                   self._jid_final_seg.get(j, idx) <= idx
                   for j in seen):
                droppable = k + 1
        if not droppable:
            return 0
        dropped, rest = (self._closed_segments[:droppable],
                         self._closed_segments[droppable:])
        gone: set = set()
        for idx in dropped:
            try:
                os.unlink(self._seg_path(idx))
            except OSError:
                pass
            gone |= self._seg_jids.pop(idx, set())
        # prune the per-jid replay state along with the disk records:
        # every dropped jid is final and fully contained in the dropped
        # prefix, so only the aggregate totals are still meaningful —
        # without this, a long-lived journal's memory would grow with
        # ALL-TIME traffic even while compaction bounded the disk
        for jid in gone:
            self._admissions.pop(jid, None)
            self._tokens.pop(jid, None)
            n = self._finals.pop(jid, 0)
            st = self._final_state.pop(jid, "finished")
            self._jid_max_seg.pop(jid, None)
            self._jid_final_seg.pop(jid, None)
            self._fleet_ids.discard(jid)
            self._compacted_admitted += 1
            if n:
                # one OUTCOME per request (duplicates counted apart,
                # matching the live outcomes()/audit() split)
                self._compacted_outcomes[st] = \
                    self._compacted_outcomes.get(st, 0) + 1
            self._compacted_duplicates += max(0, n - 1)
        self._closed_segments = rest
        self.compacted_segments += len(dropped)
        if gone:
            # persist the new cumulative totals in the ACTIVE segment
            # (which this compaction cannot have dropped): a reopen —
            # and therefore recovery's outcome banking — sees the same
            # all-time counts the live process does
            self._in_compact = True
            try:
                self._append({"kind": "compacted",
                              "admitted": self._compacted_admitted,
                              "finals": dict(self._compacted_outcomes),
                              "duplicates": self._compacted_duplicates})
            finally:
                self._in_compact = False
        return len(dropped)

    # -- lifecycle ----------------------------------------------------------

    def flush(self) -> None:
        if self._seg is not None and not self._seg.closed:
            self._seg.flush()
            if self.fsync != "never":
                os.fsync(self._seg.fileno())

    def close(self) -> None:
        if self._seg is not None and not self._seg.closed:
            self.flush()
            self._seg.close()
        from ..obs import crashdump

        crashdump.unregister_journal_dir(self.path)

    def __enter__(self) -> "RequestJournal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def stats(self) -> dict:
        """JSON-ready observability snapshot (exported through the
        engine's ``stats()["durability"]`` section)."""
        return {"path": self.path, "fsync": self.fsync, "boot": self.boot,
                **self.audit()}
