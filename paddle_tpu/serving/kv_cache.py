"""Slot-paged KV cache for continuous-batching decode.

Design (TPU-first): ONE preallocated array per K and V of shape
``[slots, layers, max_seq, kv_heads, head_dim]`` plus a ``[slots]`` int32
length vector.  Every shape the serving engine ever compiles is a function
of (slots, bucket, max_seq) only — never of request content — so XLA
compiles each program once and steady-state serving runs zero recompiles.

State threading: the cache payloads are ordinary eager ``Tensor``s.  Inside
a ``jit.to_static`` trace, reads go through ``Tensor._value`` (lifted to
program inputs) and writes through ``Tensor._set_data`` (lifted to program
outputs and rebound after the call) — exactly how optimizer accumulators
thread through a compiled train step, so the cache needs no explicit
functional plumbing and buffer donation updates it in place.

Write discipline (why stale bytes are never read):
- prefill writes positions ``0..bucket-1`` of a slot (garbage past the real
  prompt length L) and sets ``lengths[slot] = L``;
- decode writes each active slot's token at position ``lengths[slot]`` and
  THEN advances ``lengths`` by the active mask;
- attention only reads positions ``<= lengths[slot]`` (current token
  included).  Every readable position was written by the current request,
  so slot reuse needs no cache zeroing.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import dtype as dtype_mod

__all__ = ["KVCache", "CacheContext"]


def _as_i32(x):
    if isinstance(x, Tensor):
        return x._value().astype(jnp.int32)
    return jnp.asarray(x, dtype=jnp.int32)


class KVCache:
    """Preallocated per-slot KV storage shared by all layers of one model.

    Args:
        num_slots:    fixed decode batch width (continuous-batching slots).
        num_layers:   decoder layer count.
        max_seq:      cache capacity per slot (prompt + generated tokens).
        num_kv_heads: KV head count (``< num_heads`` under GQA).
        head_dim:     per-head dimension.
        dtype:        cache dtype (default float32; bf16 halves HBM).
    """

    def __init__(self, num_slots: int, num_layers: int, max_seq: int,
                 num_kv_heads: int, head_dim: int, dtype="float32"):
        if num_slots < 1 or num_layers < 1 or max_seq < 1:
            raise ValueError("num_slots/num_layers/max_seq must be >= 1")
        self.num_slots = int(num_slots)
        self.num_layers = int(num_layers)
        self.max_seq = int(max_seq)
        self.num_kv_heads = int(num_kv_heads)
        self.head_dim = int(head_dim)
        self.dtype = dtype_mod.convert_dtype(dtype)
        shape = (self.num_slots, self.num_layers, self.max_seq,
                 self.num_kv_heads, self.head_dim)
        self.k = Tensor._wrap(jnp.zeros(shape, dtype=self.dtype))
        self.v = Tensor._wrap(jnp.zeros(shape, dtype=self.dtype))
        self.lengths = Tensor._wrap(
            jnp.zeros((self.num_slots,), dtype=jnp.int32))
        for t in (self.k, self.v, self.lengths):
            t.persistable = True

    # -- serving-loop state ops (called inside OR outside a trace) --------

    def prefill_write(self, layer_idx: int, slot, k, v) -> None:
        """Write a whole prompt's K/V into one slot at positions 0..S-1.

        ``k``/``v``: ``[1, S, Hkv, D]`` (S = prefill bucket ≤ max_seq);
        ``slot``: scalar int (may be traced — one compiled prefill serves
        every slot).
        """
        s = _as_i32(slot).reshape(())
        li = jnp.int32(layer_idx)
        zero = jnp.int32(0)
        for buf, new in ((self.k, k), (self.v, v)):
            arr = buf._value()
            upd = new._value().astype(arr.dtype)[:, None]   # [1,1,S,Hkv,D]
            arr = jax.lax.dynamic_update_slice(
                arr, upd, (s, li, zero, zero, zero))
            buf._set_data(arr)

    def set_length(self, slot, length) -> None:
        """Record a freshly prefilled slot's valid length (= prompt len)."""
        s = _as_i32(slot).reshape(())
        ln = _as_i32(length).reshape(())
        self.lengths._set_data(self.lengths._value().at[s].set(ln))

    def decode_write(self, layer_idx: int, k, v
                     ) -> Tuple[Tensor, Tensor, Tensor]:
        """Write one decode token per slot at that slot's current length.

        ``k``/``v``: ``[slots, 1, Hkv, D]``.  Returns the post-write layer
        caches ``[slots, max_seq, Hkv, D]`` and the pre-advance lengths
        ``[slots]`` — exactly what ``ops.cached_attention`` consumes.
        """
        lens = self.lengths._value()
        outs = []
        for buf, new in ((self.k, k), (self.v, v)):
            arr = buf._value()
            layer = arr[:, layer_idx]                       # [slots,T,Hkv,D]
            upd = new._value().astype(arr.dtype)            # [slots,1,Hkv,D]
            layer = jax.vmap(
                lambda c, u, p: jax.lax.dynamic_update_slice(
                    c, u, (p, jnp.int32(0), jnp.int32(0))))(layer, upd, lens)
            buf._set_data(arr.at[:, layer_idx].set(layer))
            outs.append(Tensor._wrap(layer))
        return outs[0], outs[1], Tensor._wrap(lens)

    def verify_write(self, layer_idx: int, k, v
                     ) -> Tuple[Tensor, Tensor, Tensor]:
        """Speculative verify write: W tokens per slot at that slot's
        positions ``lengths[slot] .. lengths[slot] + W - 1``.

        ``k``/``v``: ``[slots, W, Hkv, D]`` (W = k_draft + 1, a trace
        constant).  Returns the post-write layer caches
        ``[slots, max_seq, Hkv, D]`` and the window-start lengths
        ``[slots]`` — what ``ops.verify_attention`` consumes.  Writes
        past ``max_seq`` are scatter-dropped (a near-capacity slot's
        over-the-end window positions are junk the acceptance cap
        already makes unemittable — and unreadable, per the write
        discipline)."""
        lens = self.lengths._value()
        W = k.shape[1]
        rows = jnp.arange(self.num_slots, dtype=jnp.int32)[:, None]
        pos = lens[:, None] + jnp.arange(W, dtype=jnp.int32)[None, :]
        outs = []
        for buf, new in ((self.k, k), (self.v, v)):
            arr = buf._value()
            upd = new._value().astype(arr.dtype)        # [slots,W,Hkv,D]
            layer = arr[:, layer_idx]                   # [slots,T,Hkv,D]
            layer = layer.at[rows, pos].set(upd)        # OOB rows dropped
            buf._set_data(arr.at[:, layer_idx].set(layer))
            outs.append(Tensor._wrap(layer))
        return outs[0], outs[1], Tensor._wrap(lens)

    def verify_attention(self, layer_idx: int, q, k, v):
        """One verify-window step of attention for this layer: write the
        W-token window, then attend with the per-slot offset causal
        mask (``ops.verify_attention``)."""
        from ..ops.cached_attention import verify_attention

        k_full, v_full, lens = self.verify_write(layer_idx, k, v)
        return verify_attention(q, k_full, v_full, lens)

    def advance(self, active) -> None:
        """Grow lengths by one for active slots (call once per decode step,
        after all layers have written).  Speculative rounds pass
        ``active * accepted_count`` — the mask is added verbatim, so a
        multi-token advance rides the same op."""
        mask = _as_i32(active)
        self.lengths._set_data(self.lengths._value() + mask)

    # -- host-side management ---------------------------------------------

    def reset(self) -> None:
        """Forget all sequences (lengths → 0).  Cache payloads are left as
        is — the write discipline above makes stale bytes unreadable."""
        self.lengths._set_data(
            jnp.zeros((self.num_slots,), dtype=jnp.int32))

    def length_of(self, slot: int) -> int:
        return int(self.lengths.numpy()[slot])

    def nbytes(self) -> int:
        itemsize = jnp.zeros((), dtype=self.dtype).dtype.itemsize
        return 2 * self.num_slots * self.num_layers * self.max_seq * \
            self.num_kv_heads * self.head_dim * itemsize


@dataclass
class CacheContext:
    """Per-forward-call routing handle threaded through model layers.

    ``mode`` selects the path: ``"prefill"`` runs the normal causal forward
    while writing K/V into ``slot``; ``"decode"`` runs single-token cached
    attention for all slots at once; ``"verify"`` is the speculative-
    decoding verify window — ``width`` tokens per slot at each slot's own
    offset, one fixed-shape forward scoring every draft proposal at once
    (``width`` = k_draft + 1, a trace-time python constant).
    ``layer_idx`` is advanced by the model's layer loop (a per-trace
    python constant).  Models only duck-type this object, keeping
    ``models/`` free of serving imports.
    """

    cache: KVCache
    mode: str                           # "prefill" | "decode" | "verify"
    slot: Optional[Tensor] = None               # prefill: scalar int32
    length: Optional[Tensor] = None             # prefill: scalar int32
    active: Optional[Tensor] = None     # decode/verify: [slots] i32 mask
    layer_idx: int = 0
    width: int = 1                      # verify: tokens per slot (k+1)

    def __post_init__(self):
        if self.mode not in ("prefill", "decode", "verify"):
            raise ValueError(f"CacheContext mode {self.mode!r} "
                             "(want 'prefill', 'decode' or 'verify')")

    def write_prefill(self, k, v) -> None:
        self.cache.prefill_write(self.layer_idx, self.slot, k, v)

    def write_decode(self, k, v) -> Tuple[Tensor, Tensor, Tensor]:
        return self.cache.decode_write(self.layer_idx, k, v)

    def decode_attention(self, q, k, v):
        """One decode step of attention through the cache: write this
        layer's token K/V, then attend over the slot's valid window.
        The contiguous layout writes + runs the masked one-row oracle;
        a cache that defines its own ``decode_attention`` (the paged
        pool's kernel-vs-reference routing) takes over the whole step —
        models stay single-path either way.  In ``verify`` mode the same
        call site routes the W-token speculative window through the
        cache's ``verify_attention`` instead, so models need no
        speculation-specific branch at all."""
        if self.mode == "verify":
            return self.cache.verify_attention(self.layer_idx, q, k, v)
        cache_fn = getattr(self.cache, "decode_attention", None)
        if cache_fn is not None:
            return cache_fn(self.layer_idx, q, k, v)
        from ..ops.cached_attention import cached_attention

        k_full, v_full, lens = self.write_decode(k, v)
        return cached_attention(q, k_full, v_full, lens)

    def positions(self) -> Tensor:
        """Current token positions (pre-advance lengths) — position ids
        for learned embeddings / rotary offsets.  Decode: ``[slots, 1]``;
        verify: ``[slots, width]`` (each slot's window sits at its own
        offset ``lengths[slot] .. lengths[slot] + width - 1``)."""
        lens = self.cache.lengths._value()
        if self.mode == "verify":
            return Tensor._wrap(
                lens[:, None]
                + jnp.arange(self.width, dtype=jnp.int32)[None, :])
        return Tensor._wrap(lens[:, None])

    # -- prefill routing hooks (overridden by serving.PagedCacheContext) --

    def prefill_positions(self, seq_len: int) -> Optional[Tensor]:
        """Position ids for the prefill tokens, or None for the default
        ``0..S-1`` — the paged context offsets them past its cached
        prefix.  ``seq_len`` is a trace-time python constant."""
        return None

    def prefill_attention(self, q, k, v):
        """Prompt-forward attention.  The contiguous layout is ordinary
        causal attention (GQA kv heads expanded first, exactly like the
        models' no-cache path); the paged context overrides this with a
        gather-by-block-table attention that also covers its cached
        prefix."""
        from ..ops.pallas import flash_attention

        B, S, H, _ = q.shape
        Hkv = k.shape[2]
        if Hkv != H:
            rep = H // Hkv
            D = q.shape[3]
            k = k.unsqueeze(3).expand([B, S, Hkv, rep, D]) \
                 .reshape([B, S, H, D])
            v = v.unsqueeze(3).expand([B, S, Hkv, rep, D]) \
                 .reshape([B, S, H, D])
        return flash_attention(q, k, v, is_causal=True, training=False)
