"""Paged KV cache: a block-granular pool behind the CacheContext surface.

Instead of reserving a contiguous ``max_seq`` stripe per slot (the
:class:`~.kv_cache.KVCache` layout — HBM sized for the worst-case
sequence), the paged layout stores K/V in a fixed pool of
``[num_blocks, layers, block_size, kv_heads, head_dim]`` blocks and
addresses them through per-slot int32 block tables of fixed shape
``[slots, max_blocks_per_slot]``.  Two things fall out:

- **HBM scales with live tokens, not worst-case slots** — the same pool
  holds many more concurrent sequences when most are short; and
- **blocks are refcountable**, so identical prompt prefixes across
  requests (system prompts, few-shot headers) can share storage via
  :class:`~.prefix_cache.PrefixCache` instead of being re-prefilled.

The zero-recompile invariant survives because every compiled shape is a
function of ``(slots, bucket, block_size, max_blocks_per_slot)`` only:
block ids live *inside* the block-table tensor (device state threaded
through traces exactly like the contiguous cache's payloads), and all
allocation/eviction/copy-on-extend happens host-side between steps,
changing argument *values* only.

Write discipline (same contract as the contiguous cache, block-indirect):
prefill writes whole tail-bucket blocks starting at the block boundary
``start_pos // block_size``; decode writes each slot's token at
``lengths[slot]`` through the table; attention reads positions
``<= lengths[slot]`` via gather-by-block-table.  Block 0 is a reserved
scratch block: idle slots' table rows point at it, so the all-slots
fixed-shape decode write never touches a live block.
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import dtype as dtype_mod
from ..ops.cached_attention import (
    block_prefill_attention, cached_attention, gather_block_kv,
    paged_decode_attention, paged_prefill_attention, verify_attention,
)
from .kv_cache import CacheContext, _as_i32

__all__ = ["BlockAllocator", "PagedKVCache", "PagedCacheContext",
           "AllocatorError"]

#: Block id every idle/retired slot's table points at.  Never allocated.
SCRATCH_BLOCK = 0


class AllocatorError(RuntimeError):
    """A block-accounting invariant was about to be violated (double
    free, unref of a free block, ...).  The engine surfaces this as an
    unhealthy state instead of corrupting the pool silently."""


class BlockAllocator:
    """Host-side accounting for the fixed KV block pool.

    Blocks move between three disjoint states (plus the reserved scratch
    block): **free** (refcount 0, on the free list), **used** (referenced
    by at least one live slot), and **cached** (idle but retained by the
    prefix cache, which holds their single ref).  ``free + used + cached
    == total - reserved`` at every step — :meth:`check` verifies it and
    :meth:`stats` exports the gauges.

    When the free list runs dry, :meth:`alloc` asks ``evict_cb`` (wired
    to :meth:`PrefixCache._evict_for_alloc`) to release idle cached
    blocks, LRU-first.
    """

    def __init__(self, num_blocks: int, reserved: int = 1):
        if num_blocks < reserved + 1:
            raise ValueError(f"num_blocks must be > reserved={reserved}, "
                             f"got {num_blocks}")
        self.num_blocks = int(num_blocks)
        self.reserved = int(reserved)
        self._free: deque = deque(range(self.reserved, self.num_blocks))
        self._ref = [0] * self.num_blocks
        self._cached = set()            # block ids retained by PrefixCache
        self.evict_cb: Optional[Callable[[int], int]] = None
        # counters
        self.allocs = 0
        self.frees = 0
        self.alloc_failures = 0

    # -- core ops ----------------------------------------------------------

    def alloc(self, n: int) -> Optional[List[int]]:
        """Pop ``n`` blocks (refcount 1 each).  Evicts idle cached blocks
        under pressure; returns None (all-or-nothing) if the pool cannot
        supply ``n`` blocks even after eviction."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if len(self._free) < n and self.evict_cb is not None:
            self.evict_cb(n - len(self._free))
        if len(self._free) < n:
            self.alloc_failures += 1
            return None
        out = []
        for _ in range(n):
            b = self._free.popleft()
            self._ref[b] = 1
            out.append(b)
        self.allocs += n
        return out

    def ref(self, block_id: int) -> int:
        b = self._check_id(block_id)
        if self._ref[b] < 1:
            raise AllocatorError(f"ref of free block {b}")
        self._ref[b] += 1
        return self._ref[b]

    def unref(self, block_id: int) -> int:
        b = self._check_id(block_id)
        if self._ref[b] < 1:
            raise AllocatorError(f"double free of block {b}")
        self._ref[b] -= 1
        if self._ref[b] == 0:
            if b in self._cached:
                raise AllocatorError(
                    f"cached block {b} dropped to refcount 0: the prefix "
                    "cache must hold one ref per cached block")
            self._free.append(b)
            self.frees += 1
        return self._ref[b]

    def refcount(self, block_id: int) -> int:
        return self._ref[self._check_id(block_id)]

    def _check_id(self, block_id: int) -> int:
        b = int(block_id)
        if not (self.reserved <= b < self.num_blocks):
            raise AllocatorError(
                f"block id {b} out of pool range "
                f"[{self.reserved}, {self.num_blocks})")
        return b

    # -- prefix-cache bookkeeping -----------------------------------------

    def mark_cached(self, block_id: int) -> None:
        self._cached.add(self._check_id(block_id))

    def unmark_cached(self, block_id: int) -> None:
        self._cached.discard(self._check_id(block_id))

    # -- introspection / invariants ---------------------------------------

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def stats(self) -> dict:
        cached_idle = sum(1 for b in self._cached if self._ref[b] == 1)
        used = sum(1 for b in range(self.reserved, self.num_blocks)
                   if self._ref[b] > 0) - cached_idle
        return {
            "total": self.num_blocks,
            "reserved": self.reserved,
            "free": len(self._free),
            "used": used,
            "cached": cached_idle,
            "allocs": self.allocs,
            "frees": self.frees,
            "alloc_failures": self.alloc_failures,
        }

    def check(self) -> List[str]:
        """Invariant audit; a non-empty return means the pool is corrupt
        (the engine flips unhealthy on it)."""
        out = []
        neg = [b for b, r in enumerate(self._ref) if r < 0]
        if neg:
            out.append(f"negative refcounts on blocks {neg}")
        free_set = set(self._free)
        if len(free_set) != len(self._free):
            out.append("duplicate entries on the free list")
        both = [b for b in free_set if self._ref[b] != 0]
        if both:
            out.append(f"blocks {both} free-listed with nonzero refcount")
        s = self.stats()
        if s["free"] + s["used"] + s["cached"] != s["total"] - s["reserved"]:
            out.append(
                f"accounting leak: free({s['free']}) + used({s['used']}) "
                f"+ cached({s['cached']}) != total({s['total']}) - "
                f"reserved({s['reserved']})")
        uncached_idle = [b for b in self._cached if self._ref[b] == 0]
        if uncached_idle:
            out.append(f"cached blocks {uncached_idle} with refcount 0")
        return out


class PagedKVCache:
    """Block-pool KV storage exposing the :class:`KVCache` duck surface.

    Device state (threaded through compiled programs exactly like the
    contiguous cache): the K/V pools, the ``[slots, max_blocks_per_slot]``
    int32 block tables, and the ``[slots]`` lengths.  Host state: the
    :class:`BlockAllocator` and each slot's owned-block list.
    """

    def __init__(self, num_slots: int, num_layers: int, max_seq: int,
                 num_kv_heads: int, head_dim: int, dtype="float32", *,
                 block_size: int = 16, num_blocks: Optional[int] = None,
                 kernel: str = "reference"):
        if num_slots < 1 or num_layers < 1 or max_seq < 1:
            raise ValueError("num_slots/num_layers/max_seq must be >= 1")
        if kernel not in ("reference", "pallas"):
            raise ValueError(f"kernel must be 'reference' or 'pallas', "
                             f"got {kernel!r}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if max_seq % block_size != 0:
            raise ValueError(f"max_seq={max_seq} must be a multiple of "
                             f"block_size={block_size}")
        self.num_slots = int(num_slots)
        self.num_layers = int(num_layers)
        self.max_seq = int(max_seq)
        self.num_kv_heads = int(num_kv_heads)
        self.head_dim = int(head_dim)
        self.block_size = int(block_size)
        self.max_blocks_per_slot = self.max_seq // self.block_size
        if num_blocks is None:
            # contiguous-parity capacity + the reserved scratch block; the
            # prefix cache then *saves* blocks relative to this baseline
            num_blocks = self.num_slots * self.max_blocks_per_slot + 1
        self.num_blocks = int(num_blocks)
        #: attention path for decode + tail prefill: ``"pallas"`` streams
        #: pool blocks through the flash-decoding kernels (interpret mode
        #: off-TPU), ``"reference"`` keeps the jnp gather + masked-softmax
        #: oracle.  Selection changes no compiled *shape* — both paths
        #: hang off the same step signatures.
        self.kernel = kernel
        from ..ops.pallas import use_pallas

        self._interpret = not use_pallas()
        self.dtype = dtype_mod.convert_dtype(dtype)
        self.allocator = BlockAllocator(self.num_blocks, reserved=1)
        shape = (self.num_blocks, self.num_layers, self.block_size,
                 self.num_kv_heads, self.head_dim)
        self.k = Tensor._wrap(jnp.zeros(shape, dtype=self.dtype))
        self.v = Tensor._wrap(jnp.zeros(shape, dtype=self.dtype))
        self.block_tables = Tensor._wrap(jnp.full(
            (self.num_slots, self.max_blocks_per_slot), SCRATCH_BLOCK,
            dtype=jnp.int32))
        self.lengths = Tensor._wrap(
            jnp.zeros((self.num_slots,), dtype=jnp.int32))
        for t in (self.k, self.v, self.block_tables, self.lengths):
            t.persistable = True
        #: blocks each slot owns one ref on, by table index order
        self._slot_blocks: List[List[int]] = [[] for _ in range(num_slots)]
        self.copy_on_extends = 0

    # -- host-side slot lifecycle -----------------------------------------

    def _set_table(self, slot: int, idx: int, block_id: int) -> None:
        self.block_tables._set_data(
            self.block_tables._value().at[slot, idx].set(
                jnp.int32(block_id)))

    def begin_sequence(self, slot: int, shared_blocks: Sequence[int],
                       prefix_len: int, tail_bucket: int) -> bool:
        """Assign storage for one admission: ref the shared prefix blocks
        and allocate fresh blocks covering the whole tail bucket.  The
        slot must be empty (freshly popped).  All-or-nothing: returns
        False (slot untouched) when the pool cannot supply the tail —
        the scheduler defers the request instead of failing it."""
        if self._slot_blocks[slot]:
            raise AllocatorError(f"slot {slot} already owns blocks "
                                 f"{self._slot_blocks[slot]}")
        bs = self.block_size
        if prefix_len != len(shared_blocks) * bs:
            raise ValueError(f"prefix_len {prefix_len} != "
                             f"{len(shared_blocks)} shared blocks * {bs}")
        if tail_bucket % bs != 0:
            raise ValueError(f"tail bucket {tail_bucket} not a multiple "
                             f"of block_size {bs}")
        n_tail = tail_bucket // bs
        n_total = len(shared_blocks) + n_tail
        if n_total > self.max_blocks_per_slot:
            raise ValueError(
                f"prefix {len(shared_blocks)} + tail {n_tail} blocks "
                f"exceed max_blocks_per_slot {self.max_blocks_per_slot}")
        # ref the hit blocks BEFORE allocating the tail: alloc() may evict
        # idle cached blocks under pressure, and an un-ref'd hit block is
        # exactly that — pinning first makes the lookup result immune to
        # being recycled into this same sequence's tail
        owned = []
        for b in shared_blocks:
            self.allocator.ref(int(b))
            owned.append(int(b))
        fresh = self.allocator.alloc(n_tail)
        if fresh is None:
            for b in owned:
                self.allocator.unref(b)
            return False
        owned.extend(fresh)
        tbl = self.block_tables._value()
        row = [SCRATCH_BLOCK] * self.max_blocks_per_slot
        row[:len(owned)] = owned
        self.block_tables._set_data(
            tbl.at[slot].set(jnp.asarray(row, dtype=jnp.int32)))
        self._slot_blocks[slot] = owned
        return True

    def release_slot(self, slot: int) -> None:
        """Drop the slot's refs and point its table back at scratch.
        Idempotent (retire is the single exit path, but chaos paths may
        race a reset)."""
        owned, self._slot_blocks[slot] = self._slot_blocks[slot], []
        for b in owned:
            self.allocator.unref(b)
        if owned:
            self.block_tables._set_data(
                self.block_tables._value().at[slot].set(
                    jnp.full((self.max_blocks_per_slot,), SCRATCH_BLOCK,
                             dtype=jnp.int32)))
        self.lengths._set_data(
            self.lengths._value().at[slot].set(jnp.int32(0)))

    def ensure_capacity(self, slot: int, next_pos: int) -> bool:
        """Make position ``next_pos`` writable for ``slot`` before a
        decode step: allocate the covering block if the sequence is
        growing into one it doesn't own yet, and copy-on-extend if the
        covering block is shared (refcount > 1).  Returns False when the
        pool is exhausted (the engine fails that request, not the
        engine)."""
        bidx = next_pos // self.block_size
        if bidx >= self.max_blocks_per_slot:
            return False                 # capacity guard upstream
        owned = self._slot_blocks[slot]
        if bidx >= len(owned):
            if bidx != len(owned):
                raise AllocatorError(
                    f"slot {slot} skipping block index {len(owned)} "
                    f"to {bidx}")
            fresh = self.allocator.alloc(1)
            if fresh is None:
                return False
            owned.append(fresh[0])
            self._set_table(slot, bidx, fresh[0])
            return True
        block_id = owned[bidx]
        if self.allocator.refcount(block_id) > 1:
            # copy-on-extend: appending into a shared block would corrupt
            # the other holders' view — give this slot a private copy
            fresh = self.allocator.alloc(1)
            if fresh is None:
                return False
            for buf in (self.k, self.v):
                arr = buf._value()
                buf._set_data(arr.at[fresh[0]].set(arr[block_id]))
            owned[bidx] = fresh[0]
            self._set_table(slot, bidx, fresh[0])
            self.allocator.unref(block_id)
            self.copy_on_extends += 1
        return True

    def truncate_blocks(self, slot: int, n_tokens: int) -> int:
        """Speculative rollback bookkeeping: drop the slot's owned
        blocks past the ones covering positions ``0..n_tokens-1`` (the
        rejected tail of a verify window — no copy, just refcount +
        table writes; the rejected K/V bytes become unreadable the
        moment the in-graph length rollback lands).  Returns how many
        blocks were released."""
        owned = self._slot_blocks[slot]
        keep = (int(n_tokens) + self.block_size - 1) // self.block_size
        if len(owned) <= keep:
            return 0
        drop = owned[keep:]
        del owned[keep:]
        tbl = self.block_tables._value()
        row = jnp.asarray(
            [SCRATCH_BLOCK] * self.max_blocks_per_slot, dtype=jnp.int32)
        row = row.at[:len(owned)].set(jnp.asarray(owned, dtype=jnp.int32))
        self.block_tables._set_data(tbl.at[slot].set(row))
        for b in drop:
            self.allocator.unref(b)
        return len(drop)

    def reset(self) -> None:
        """Forget all sequences: release every slot and zero lengths.
        Cached (prefix) blocks are left to their owner — the engine
        clears its PrefixCache separately when it wants a cold pool."""
        for slot in range(self.num_slots):
            self.release_slot(slot)

    # -- traced state ops (CacheContext surface) --------------------------

    def prefill_write(self, layer_idx: int, slot, k, v, start=0) -> None:
        """Write a tail bucket's K/V through the block table.

        ``k``/``v``: ``[1, S, Hkv, D]`` with S = tail bucket (a multiple
        of block_size); ``slot``/``start`` scalar ints (may be traced) —
        ``start`` is the absolute position of the bucket's first token
        and is always a block boundary."""
        s = _as_i32(slot).reshape(())
        st = _as_i32(start).reshape(())
        bs = self.block_size
        li = jnp.int32(layer_idx)
        tbl = self.block_tables._value()
        row = jax.lax.dynamic_index_in_dim(tbl, s, axis=0, keepdims=False)
        start_block = st // bs
        for buf, new in ((self.k, k), (self.v, v)):
            arr = buf._value()
            upd = new._value().astype(arr.dtype)[0]     # [S, Hkv, D]
            n_blocks = upd.shape[0] // bs
            for j in range(n_blocks):                   # python const
                bid = jax.lax.dynamic_index_in_dim(
                    row, start_block + j, axis=0, keepdims=False)
                blk = upd[j * bs:(j + 1) * bs]          # [bs, Hkv, D]
                arr = jax.lax.dynamic_update_slice(
                    arr, blk[None, None].astype(arr.dtype),
                    (bid, li, jnp.int32(0), jnp.int32(0), jnp.int32(0)))
            buf._set_data(arr)

    def set_length(self, slot, length) -> None:
        s = _as_i32(slot).reshape(())
        ln = _as_i32(length).reshape(())
        self.lengths._set_data(self.lengths._value().at[s].set(ln))

    def _decode_token_write(self, layer_idx: int, k, v):
        """Write one token per slot at ``lengths[slot]`` through the
        table.  Idle slots' tables point at the scratch block, so the
        fixed-shape all-slots write never lands on live storage.
        Returns ``(k_layer, v_layer, tables, lengths)`` raw arrays
        (post-write layer pools)."""
        lens = self.lengths._value()
        bs = self.block_size
        tbl = self.block_tables._value()            # [slots, max_blocks]
        bidx = jnp.clip(lens // bs, 0, self.max_blocks_per_slot - 1)
        block_ids = jnp.take_along_axis(
            tbl, bidx[:, None], axis=1)[:, 0]       # [slots]
        off = lens % bs
        layers = []
        for buf, new in ((self.k, k), (self.v, v)):
            arr = buf._value()
            upd = new._value().astype(arr.dtype)[:, 0]   # [slots, Hkv, D]
            arr = arr.at[block_ids, layer_idx, off].set(upd)
            buf._set_data(arr)
            layers.append(arr[:, layer_idx])
        return layers[0], layers[1], tbl, lens

    def decode_write(self, layer_idx: int, k, v
                     ) -> Tuple[Tensor, Tensor, Tensor]:
        """Reference decode read: token write, then gather each slot's
        sequence back contiguous — the same ``([slots, T, Hkv, D],
        lengths)`` triple the contiguous cache hands
        ``ops.cached_attention``, with ``T = max_blocks_per_slot *
        block_size``."""
        k_layer, v_layer, tbl, lens = self._decode_token_write(
            layer_idx, k, v)
        return (Tensor._wrap(gather_block_kv(k_layer, tbl)),
                Tensor._wrap(gather_block_kv(v_layer, tbl)),
                Tensor._wrap(lens))

    def decode_attention(self, layer_idx: int, q, k, v):
        """One decode step of attention for this layer: write the token,
        then attend.  ``kernel="pallas"`` consumes the block table inside
        the flash-decoding kernel (no materialized contiguous K/V);
        ``"reference"`` gathers and runs the jnp oracle — identical
        semantics, asserted in tests/test_paged_kernel.py."""
        if self.kernel == "pallas":
            k_layer, v_layer, tbl, lens = self._decode_token_write(
                layer_idx, k, v)
            return paged_decode_attention(
                q, Tensor._wrap(k_layer), Tensor._wrap(v_layer),
                Tensor._wrap(tbl), Tensor._wrap(lens),
                interpret=self._interpret)
        k_full, v_full, lens = self.decode_write(layer_idx, k, v)
        return cached_attention(q, k_full, v_full, lens)

    def verify_write(self, layer_idx: int, k, v):
        """Speculative verify write through the block table: W tokens
        per slot at positions ``lengths[slot] .. lengths[slot]+W-1``.
        Block ids stay tensor VALUES (one executable for every table
        content); positions past ``max_seq`` are redirected to the
        scratch block, so a near-capacity slot's over-the-end window
        writes land on storage nothing ever reads.  The caller must
        have pre-extended each running slot's table to cover the
        in-range window (``ensure_capacity`` per position — exclusive
        ownership via copy-on-extend included).  Returns
        ``(k_layer, v_layer, tables, lengths)`` raw arrays."""
        lens = self.lengths._value()
        bs = self.block_size
        tbl = self.block_tables._value()            # [slots, max_blocks]
        W = int(k.shape[1])
        pos = lens[:, None] + jnp.arange(W, dtype=jnp.int32)[None, :]
        bidx = jnp.clip(pos // bs, 0, self.max_blocks_per_slot - 1)
        block_ids = jnp.take_along_axis(tbl, bidx, axis=1)   # [slots, W]
        block_ids = jnp.where(pos < self.max_seq, block_ids,
                              SCRATCH_BLOCK)
        off = pos % bs
        layers = []
        for buf, new in ((self.k, k), (self.v, v)):
            arr = buf._value()
            upd = new._value().astype(arr.dtype)    # [slots, W, Hkv, D]
            arr = arr.at[block_ids, layer_idx, off].set(upd)
            buf._set_data(arr)
            layers.append(arr[:, layer_idx])
        return layers[0], layers[1], tbl, lens

    def verify_attention(self, layer_idx: int, q, k, v):
        """One verify-window step for this layer: write the W-token
        window through the table, gather the slot sequences contiguous,
        and attend with the per-slot offset causal mask.  The verify
        path always uses the XLA gather + ``ops.verify_attention``
        oracle (the Pallas decode/prefill kernels are W-specific and
        stay on their own paths) — semantics identical either way, and
        kernel selection still never changes a compiled shape."""
        k_layer, v_layer, tbl, lens = self.verify_write(layer_idx, k, v)
        return verify_attention(
            q, Tensor._wrap(gather_block_kv(k_layer, tbl)),
            Tensor._wrap(gather_block_kv(v_layer, tbl)),
            Tensor._wrap(lens))

    def advance(self, active) -> None:
        mask = _as_i32(active)
        self.lengths._set_data(self.lengths._value() + mask)

    # -- host-side management ---------------------------------------------

    def owned_blocks(self, slot: int) -> List[int]:
        """The block ids ``slot`` holds a ref on, in table-index order —
        the engine's handle for prefix-cache registration (at admission,
        and again on preemption BEFORE the victim's slot releases, so a
        preempted request's resume is a cheap prefix hit)."""
        return self._slot_blocks[slot]

    def length_of(self, slot: int) -> int:
        return int(self.lengths.numpy()[slot])

    def nbytes(self) -> int:
        itemsize = jnp.zeros((), dtype=self.dtype).dtype.itemsize
        return 2 * self.num_blocks * self.num_layers * self.block_size * \
            self.num_kv_heads * self.head_dim * itemsize

    def blocks_in_use(self) -> int:
        s = self.allocator.stats()
        return s["used"] + s["cached"]

    def check_invariants(self) -> List[str]:
        """Allocator audit plus cache-level cross-checks."""
        out = self.allocator.check()
        seen = {}
        for slot, owned in enumerate(self._slot_blocks):
            for b in owned:
                seen.setdefault(b, []).append(slot)
                if self.allocator.refcount(b) < 1:
                    out.append(f"slot {slot} holds freed block {b}")
        for b, slots in seen.items():
            if len(slots) > self.allocator.refcount(b):
                out.append(f"block {b} held by slots {slots} with only "
                           f"{self.allocator.refcount(b)} refs")
        return out


@dataclass
class PagedCacheContext(CacheContext):
    """CacheContext over a :class:`PagedKVCache`: same duck surface, plus
    the tail-prefill routing (``start`` = absolute position of the
    bucket's first token, a traced scalar — block ids stay inside the
    block-table tensor)."""

    start: Optional[Tensor] = None              # prefill: scalar int32

    def write_prefill(self, k, v) -> None:
        self.cache.prefill_write(self.layer_idx, self.slot, k, v,
                                 self.start if self.start is not None
                                 else 0)

    def prefill_positions(self, seq_len: int) -> Optional[Tensor]:
        """Absolute positions of the tail bucket's tokens ``[1, S]`` —
        offset by the cached-prefix length."""
        st = _as_i32(self.start if self.start is not None else 0
                     ).reshape(())
        return Tensor._wrap(
            (st + jnp.arange(seq_len, dtype=jnp.int32))[None, :])

    def prefill_attention(self, q, k, v):
        """Tail queries attending over the slot's whole block table
        (cached prefix + freshly-written tail) with an absolute-position
        causal mask.  GQA expansion happens inside the op, like the
        decode kernel.  ``kernel="pallas"`` streams the block row through
        the fused prefix+tail kernel instead of gathering a contiguous
        copy first."""
        s = _as_i32(self.slot).reshape(())
        tbl = self.cache.block_tables._value()
        start = self.start if self.start is not None else 0
        if self.cache.kernel == "pallas":
            row = jax.lax.dynamic_index_in_dim(
                tbl, s, axis=0, keepdims=False)              # [MB]
            return paged_prefill_attention(
                q,
                Tensor._wrap(self.cache.k._value()[:, self.layer_idx]),
                Tensor._wrap(self.cache.v._value()[:, self.layer_idx]),
                Tensor._wrap(row), start,
                interpret=self.cache._interpret)
        row = jax.lax.dynamic_index_in_dim(tbl, s, axis=0)   # [1, MB]
        k_all = Tensor._wrap(gather_block_kv(
            self.cache.k._value()[:, self.layer_idx], row))
        v_all = Tensor._wrap(gather_block_kv(
            self.cache.v._value()[:, self.layer_idx], row))
        return block_prefill_attention(q, k_all, v_all, start)
