"""Serving fleet supervisor: a replica router over N in-process engines.

PRs 3-5 made one ``Engine`` degrade per-request, never per-engine — but
the engine itself is still a single point of failure: a wedged compiled
step or a corrupted block pool flips a sticky ``unhealthy`` flag and
every queued and in-flight request dies with it.  :class:`Fleet` is the
next containment ring: it owns N engine **replicas** (each with its own
KV pool, prefix cache, and compiled executables) behind one
submit/stream/cancel surface, and treats a replica as a *crashable,
ejectable, restartable unit*:

- **Dispatch** is prefix-affinity first — a request is routed to the
  replica whose :class:`~.prefix_cache.PrefixCache` already covers the
  longest prefix of its prompt (probed side-effect-free via
  ``Engine.prefix_probe``), so cross-request prefix reuse keeps working
  fleet-wide — and least-loaded otherwise, with fleet-level admission
  control aggregating per-replica queue depth.
- **Supervision**: every ``step()`` polls each replica's ``health()``.
  A replica that is ``unhealthy`` (watchdog, allocator-invariant
  violation) or failing consecutively (``eject_after_failures``) is
  **ejected** from rotation; its queued AND in-flight requests are
  exported (``Engine.export_requests``) and **re-dispatched** to
  survivors; the replica is then **rebuilt** (fresh engine over the
  shared model, re-``warmup()``) and rejoins rotation — the fleet heals
  without a process restart, and the eject→rejoin time is exported as
  the measured failover recovery.
- **Redispatch stream contract**: a re-dispatched request replays from
  its prompt — its stream restarts from token 0 with
  ``FleetRequest.redispatched`` / ``.redispatches`` set *before* the
  first replayed token, its ``output_ids`` are reset, and its terminal
  state is reached exactly once (fleet-level, audited by the
  ``duplicate_terminals`` counter).  At most ``max_redispatch`` replays
  are attempted before the request fails with the ejected replica's
  recorded error.  Greedy and seeded-sampling replays are
  deterministic; unseeded temperature sampling redraws (each attempt
  seeds from its per-replica request id).
- **Shape discipline**: replicas are ordinary engines, so no failure
  mode changes a compiled shape on a survivor — ejection, redispatch,
  and rebuild only move host-side bookkeeping, and the chaos tests
  assert survivors' executable-cache miss counters stay flat.

Everything is in-process and CPU-testable; the replica boundary is the
same one the tensor-parallel sharding work (ROADMAP item 1) will land
on, already fault-tolerant.
"""
from __future__ import annotations

import itertools
import time
import weakref
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .engine import (Engine, EngineStopped, PRIORITY_NORMAL, QueueFull,
                     Request, ShedReject, _as_priority)
from .metrics import FleetMetrics
from .sampling import SamplingParams
from .tracing import NULL_TRACER, RequestTracer

__all__ = ["Fleet", "FleetRequest"]

_fleet_counter = itertools.count()

#: Fleet-request states a request can never leave.
FLEET_TERMINAL_STATES = frozenset(
    {"finished", "failed", "cancelled", "rejected"})


@dataclass(eq=False)           # a live handle: identity, not field equality
class FleetRequest:
    """One generation request moving through the fleet.

    The fleet-level handle outlives any single replica attempt: the
    underlying engine :class:`~.engine.Request` is plumbing that may be
    replayed on a different replica after an ejection, while THIS handle
    carries the user-visible stream and reaches a terminal state exactly
    once.  ``output_ids`` mirror the *current* attempt's stream; on
    redispatch they reset to empty and ``redispatches``/``redispatched``
    are set before the first replayed token arrives — the stream
    restarts from token 0, marked.
    """

    prompt_ids: np.ndarray
    request_id: int = -1
    stream_cb: Optional[Callable[[int, "FleetRequest"], None]] = None
    done_cb: Optional[Callable[["FleetRequest"], None]] = None
    kwargs: dict = field(default_factory=dict)    # engine add_request kwargs

    # lifecycle (fleet-managed)
    state: str = "pending"
    error: Optional[str] = None
    #: machine-readable backpressure/shed context — same fields as the
    #: engine-level ``Request.error_ctx`` (``depth``, ``retry_after_s``)
    error_ctx: Optional[dict] = None
    output_ids: List[int] = field(default_factory=list)
    redispatches: int = 0
    redispatched: bool = False
    #: engine-level preemption markers mirrored from the CURRENT attempt
    #: (a preempted stream restarts from token 0, marked — the same
    #: contract as ``redispatched``, one level down)
    preempted: bool = False
    preemptions: int = 0
    #: durable identity in the fleet's request journal: stable across
    #: redispatch AND process crashes (every attempt's admission record
    #: carries it; the exactly-once audit keys on it)
    journal_id: Optional[str] = None
    #: this handle is a crash-recovery replay rehydrated from the
    #: journal by ``Fleet.recover`` (stream restarted from token 0)
    recovered: bool = False
    #: weight version of the replica that admitted the CURRENT attempt
    model_version: int = 0
    #: pre-crash admission wall stamp (tracer's cross-process link)
    _origin_wall: Optional[float] = field(default=None, repr=False)
    #: engine names this request was dispatched to, in order
    replica_history: List[str] = field(default_factory=list)
    t_submit: float = 0.0
    t_finish: Optional[float] = None
    _attempt: Optional[Request] = field(default=None, repr=False)
    _cancel: bool = False
    #: a replica shed this request during the dispatch hunt (the final
    #: rejection may be another replica's plain QueueFull — the fleet
    #: shed counter must still see it)
    _shed_seen: bool = field(default=False, repr=False)
    _fleet: Optional[object] = field(default=None, repr=False)

    @property
    def finished(self) -> bool:
        return self.state == "finished"

    @property
    def done(self) -> bool:
        return self.state in FLEET_TERMINAL_STATES

    def cancel(self) -> bool:
        """Stop this request wherever its current attempt lives.
        Returns False if it is already terminal."""
        if self.done:
            return False
        self._cancel = True
        fleet = self._fleet() if self._fleet is not None else None
        if fleet is not None:
            fleet._on_cancel(self)
        return True


class _Replica:
    """One supervised engine slot in the fleet rotation."""

    __slots__ = ("index", "engine", "state", "ejections", "rebuilds",
                 "rebuild_attempts", "last_error", "_eject_t",
                 "flight_dumps", "degraded")

    def __init__(self, index: int, engine: Engine):
        self.index = index
        self.engine = engine
        self.state = "active"            # active | ejected | dead
        self.ejections = 0
        self.rebuilds = 0
        self.rebuild_attempts = 0        # consecutive failed rebuilds
        self.last_error: Optional[str] = None
        self._eject_t: Optional[float] = None
        #: flight-recorder dumps banked at each ejection — the rebuild
        #: record's post-mortem attachment (the ejected engine itself is
        #: discarded, so the fleet keeps the dump alive)
        self.flight_dumps: List[dict] = []
        #: True once a degraded rebuild shrank this group's mesh below
        #: the fleet's configured ``shards_per_group``
        self.degraded = False

    def load(self) -> int:
        return len(self.engine.queue) + len(self.engine.running)

    def model_parallel(self) -> int:
        shard = getattr(self.engine, "shard", None)
        return shard.mp if shard is not None else 1


class Fleet:
    """N supervised :class:`~.engine.Engine` replicas behind one
    submit/stream/cancel surface.

    Args:
        model_or_config: anything ``Engine.from_config`` accepts (a model
            Layer, a ``GPTConfig``/``LlamaConfig``, or a registry name).
            The model is built ONCE and shared across replicas — weights
            are read-only during serving; each replica owns its own KV
            storage, prefix cache, and compiled executables.
        num_replicas: fleet width.
        max_redispatch: replay budget per request — after this many
            re-dispatches the request fails with the replica's recorded
            error.
        max_queue: fleet-level admission bound on the AGGREGATE queued
            (not-yet-admitted) depth across active replicas; ``None`` =
            unbounded.  A full fleet rejects with :class:`QueueFull`.
        eject_after_failures: eject a replica once its
            ``consecutive_step_failures`` reaches this (in addition to
            any replica whose ``health()`` reports ``unhealthy``).
        supervise_every: run the supervision poll every Nth fleet step
            (1 = every step).
        fault_plan: a shared ``ServingFaultPlan``; each replica's engine
            checks it through a replica-scoped view so
            ``serving.r<k>.<point>`` specs target exactly one replica
            (default: the env-armed plan).
        tracer: a :class:`~.tracing.RequestTracer` shared by the router
            and every replica engine — the fleet-wide request-lifecycle
            span chain (docs/SERVING.md "Tracing & flight recorder").
            Fleet-managed (rejected in ``engine_kwargs``); default: the
            env-armed tracer (``PADDLE_TPU_TRACE=1``) or the no-op
            tracer.
        journal: a :class:`~.journal.RequestJournal` shared by the
            router and every replica — submissions are journaled with
            fleet-scoped ids, the router's exactly-once ``_finish``
            writes each final terminal record, and a fresh process can
            ``recover()`` every non-terminal request after a crash.
            Fleet-managed (rejected in ``engine_kwargs``).
        isolate_weights: give each replica its OWN parameter buffers
            (cloned from the template model) so a rolling
            ``update_weights`` can swap one drained replica while the
            rest keep serving the old weights.  Default None =
            auto: isolate when ``num_replicas > 1`` and the model is
            reconstructible as ``type(model)(model.config)``, else
            share (where ``update_weights`` degrades to a
            stop-the-world swap).
        shards_per_group: tensor-parallel width of each replica.  With
            ``shards_per_group > 1`` every replica is a shard *group* —
            an ``Engine(mesh=...)`` over its own DISJOINT slice of
            ``jax.devices()`` — and the existing per-replica mechanisms
            become the per-group ones the sharded deployment needs:
            prefix-affinity dispatch targets a group, ``update_weights``
            rolls one drained group at a time (per-shard ``_set_data``
            write-through, one prefix-epoch bump per group), and
            recovery replays bitwise onto any mesh of the same shape —
            see docs/SERVING.md "Sharded serving".
        **engine_kwargs: forwarded to every replica's ``Engine(...)``
            (``num_slots``, ``max_seq``, ``kv_layout``, ...).  ``name``,
            ``fault_plan``, ``tracer``, ``journal``, ``model_version``
            and ``mesh`` are fleet-managed and rejected here.
    """

    def __init__(self, model_or_config, *, num_replicas: int = 2,
                 max_redispatch: int = 2, max_queue: Optional[int] = None,
                 eject_after_failures: int = 2, supervise_every: int = 1,
                 name: Optional[str] = None, fault_plan=None,
                 tracer=None, journal=None,
                 isolate_weights: Optional[bool] = None,
                 shards_per_group: int = 1,
                 **engine_kwargs):
        if num_replicas < 1:
            raise ValueError(f"num_replicas must be >= 1, "
                             f"got {num_replicas}")
        if shards_per_group < 1:
            raise ValueError(f"shards_per_group must be >= 1, "
                             f"got {shards_per_group}")
        if max_redispatch < 0:
            raise ValueError("max_redispatch must be >= 0")
        if eject_after_failures < 1:
            raise ValueError("eject_after_failures must be >= 1")
        if supervise_every < 1:
            raise ValueError("supervise_every must be >= 1")
        for k in ("name", "fault_plan", "tracer", "journal",
                  "model_version", "mesh"):
            if k in engine_kwargs:
                raise ValueError(f"{k!r} is fleet-managed; pass it to "
                                 "Fleet, not through engine kwargs")
        # shard groups (docs/SERVING.md "Sharded serving"): one replica
        # == one shard GROUP — a tensor-parallel engine on its own
        # DISJOINT device slice, so every fleet mechanism built for
        # replicas (prefix-affinity dispatch, per-replica drain in a
        # rolling update_weights, ejection/rebuild, journal recovery)
        # applies to shard groups without a line of new control flow.
        self.shards_per_group = int(shards_per_group)
        self.model = Engine.resolve_model(model_or_config)
        if self.shards_per_group > 1:
            import jax

            from .sharding import serving_mesh, viable_ladder

            # viability at construction (degraded-mode contract): the
            # configured mp must sit ON the model's viability ladder —
            # the same divisibility rules ServingShard enforces, named
            # here so a misconfigured fleet fails with the full ladder
            # (and therefore the degrade steps available to it) instead
            # of a bare divisibility error per engine
            kv, nh = self._head_counts()
            ladder = viable_ladder(kv, nh)
            if self.shards_per_group not in ladder:
                raise ValueError(
                    f"shards_per_group={self.shards_per_group} is not a "
                    f"viable model-parallel degree for this model "
                    f"(kv_heads={kv}, num_attention_heads={nh}): the "
                    f"viable ladder is {ladder} — every mp must divide "
                    f"both head counts so the KV pool shards whole GQA "
                    f"groups")
            devs = jax.devices()
            need = num_replicas * self.shards_per_group
            if len(devs) < need:
                raise ValueError(
                    f"shards_per_group={self.shards_per_group} with "
                    f"num_replicas={num_replicas} needs {need} devices "
                    f"(disjoint per-group meshes), have {len(devs)}")
            #: each group's ORIGINAL device slice — the degraded rebuild
            #: carves its smaller mesh out of whichever of these survive
            self._group_devices: List[Optional[list]] = [
                list(devs[k * self.shards_per_group:
                          (k + 1) * self.shards_per_group])
                for k in range(num_replicas)]
            self._group_meshes: List[Optional[object]] = [
                serving_mesh(self.shards_per_group, devices=slice_)
                for slice_ in self._group_devices]
        else:
            self._group_devices = [None] * num_replicas
            self._group_meshes = [None] * num_replicas
        #: devices recorded lost at ejection (``engine.lost_devices``):
        #: never handed to a rebuilt mesh again
        self._failed_devices: set = set()
        #: current fleet-wide weight version (bumped by update_weights;
        #: replicas join rolls — and rebuilds — at this version)
        self.model_version = 0
        # weight isolation (docs/SERVING.md "Durability & hot swap"):
        # each replica serves its OWN parameter buffers, cloned from
        # the template, so a rolling update can swap one drained
        # replica while the others keep answering on the old weights —
        # exactly the memory layout a multi-process deployment has.
        # isolate_weights=None auto-detects (falls back to the PR 6
        # shared-weights layout when the model cannot be cloned, where
        # update_weights degrades to a documented stop-the-world swap).
        if isolate_weights is None:
            self._isolate_mode = "auto" if num_replicas > 1 else "off"
        else:
            self._isolate_mode = "on" if isolate_weights else "off"
        # provisional under "auto": the first replica clone attempt
        # settles it (falls back to shared on an uncloneable model)
        self.weights_isolated = self._isolate_mode != "off"
        self.name = name or f"fleet-{next(_fleet_counter)}"
        self.num_replicas = int(num_replicas)
        self.max_redispatch = int(max_redispatch)
        self.max_queue = None if max_queue is None else int(max_queue)
        self.eject_after_failures = int(eject_after_failures)
        self.supervise_every = int(supervise_every)
        self._engine_kwargs = dict(engine_kwargs)
        if fault_plan is None:
            from ..distributed.fault_tolerance.injection import \
                ServingFaultPlan

            fault_plan = ServingFaultPlan.from_env()
        self.fault_plan = fault_plan
        # ONE tracer shared by the router and every replica generation:
        # the cross-replica span chain (dispatch → attempt → redispatch)
        # only links up when all parties record into the same tracer
        if tracer is None:
            tracer = RequestTracer.from_env() or NULL_TRACER
        self.tracer = tracer
        # ONE journal shared by the router and every replica: engine
        # admissions/tokens/attempt-ends ride fleet-scoped journal ids,
        # the router's exactly-once _finish writes each final end
        self.journal = journal
        self.replicas: List[_Replica] = [
            _Replica(k, self._make_engine(k))
            for k in range(self.num_replicas)]
        self.metrics = FleetMetrics(self.name,
                                    num_replicas=self.num_replicas)
        self.metrics.replicas_cb = self._replica_rows
        self.metrics.flight_cb = self._flight_dump_table
        self.state = "active"            # active | draining | stopped
        #: live attempt → (fleet request, replica) — the reap table
        self._attempts: Dict[Request, Tuple[FleetRequest, _Replica]] = {}
        #: replica-implicated failures reaped with NO survivor to take
        #: them — held for redispatch after the supervision pass, which
        #: may eject and rebuild the implicated replica this very tick
        self._repatriate: List[Tuple[FleetRequest, str]] = []
        self._req_counter = itertools.count()
        self._rr = 0                     # least-loaded tie-break rotation
        self._tick = 0
        #: preemptions of engines that left rotation (ejected / dead) —
        #: live engines are summed on top in ``stats()``
        self._banked_preemptions = 0
        #: fleet-level shed SUBMITS (counted once per request, however
        #: many replicas shed it while the dispatch hunted for one that
        #: would take it — the per-replica rows keep the raw decisions)
        self._sheds = 0

    # -- replica construction ----------------------------------------------

    def _head_counts(self) -> Tuple[int, int]:
        """(kv_heads, num_attention_heads) of the served model — the
        two divisors the viability ladder is built from (the same
        resolution Engine uses for its ServingShard)."""
        cfg = self.model.config
        kv = getattr(cfg, "n_kv_heads", None) or cfg.num_attention_heads
        return int(kv), int(cfg.num_attention_heads)

    def _replica_model(self):
        """The model a new replica engine serves: a per-replica clone
        of the template (current weights copied in) under weight
        isolation — rebuilt as ``type(model)(model.config)``, true for
        the served GPT/Llama families — else the shared template.
        Rebuilds after an ejection land here too, so a replica rebuilt
        mid-roll joins at the template's CURRENT weights."""
        if not self.weights_isolated:
            return self.model
        try:
            m = type(self.model)(self.model.config)
        except Exception as e:           # noqa: BLE001 — capability probe
            if self._isolate_mode == "auto":
                self.weights_isolated = False
                return self.model
            raise TypeError(
                "isolate_weights=True needs a model reconstructible as "
                "type(model)(model.config) "
                f"({type(e).__name__}: {e}); pass isolate_weights=False "
                "to share weights (rolling update_weights then degrades "
                "to a stop-the-world swap)") from e
        from .engine import _write_state_dict

        _write_state_dict(m, self.model.state_dict(),
                          what="replica model clone")
        m.eval()
        return m

    def _make_engine(self, index: int) -> Engine:
        return Engine(self._replica_model(),
                      name=f"{self.name}.r{index}",
                      fault_plan=self.fault_plan.scoped(index),
                      tracer=self.tracer, journal=self.journal,
                      model_version=self.model_version,
                      mesh=self._group_meshes[index],
                      **self._engine_kwargs)

    def warmup(self) -> dict:
        """Warm every replica (pre-compile all buckets + decode per
        engine) so steady-state serving — and post-failover serving on
        survivors — triggers zero recompiles."""
        return {rep.engine.name: rep.engine.warmup()
                for rep in self.replicas if rep.state == "active"}

    # -- dispatch ----------------------------------------------------------

    def _active(self, exclude: Sequence[_Replica] = ()
                ) -> List[_Replica]:
        return [r for r in self.replicas
                if r.state == "active" and r not in exclude]

    @staticmethod
    def _adapter_of(freq: FleetRequest) -> Optional[str]:
        """The adapter a fleet request selects (None for base) — probes
        ride the tenant's prefix-cache salt, so affinity only credits
        KV the request could actually hit."""
        s = freq.kwargs.get("sampling")
        return getattr(s, "adapter", None) if s is not None else None

    def _choose_replica(self, prompt_ids, exclude: Sequence[_Replica] = (),
                        adapter: Optional[str] = None
                        ) -> Tuple[Optional[_Replica], int]:
        """Dispatch policy: the replica whose prefix cache covers the
        longest prefix of the prompt (ties → least-loaded), else
        least-loaded (ties → round-robin).  Returns
        ``(replica, affinity_tokens)``."""
        cands = self._active(exclude)
        if not cands:
            return None, 0
        probed = [(rep, rep.engine.prefix_probe(prompt_ids,
                                                adapter=adapter))
                  for rep in cands]
        best_hit = max(hit for _, hit in probed)
        if best_hit > 0:
            tied = [rep for rep, hit in probed if hit == best_hit]
            return min(tied, key=self._effective_load), best_hit
        self._rr += 1
        order = cands[self._rr % len(cands):] + \
            cands[:self._rr % len(cands)]
        return min(order, key=self._effective_load), 0

    def _effective_load(self, rep: _Replica) -> float:
        """Dispatch-capacity rebalance: a DEGRADED group (rebuilt at a
        smaller mp after device loss) runs the same slot count on fewer
        chips, so its load is weighted up by ``full_mp / current_mp`` —
        least-loaded dispatch then naturally routes proportionally less
        new traffic to it, without starving it entirely."""
        mp = rep.model_parallel()
        if mp >= self.shards_per_group:
            return float(rep.load())
        return rep.load() * (self.shards_per_group / max(mp, 1))

    def _wrap_stream(self, freq: FleetRequest):
        """Per-attempt stream adapter: mirrors tokens onto the fleet
        handle and forwards to the user's callback with the FLEET
        request (so ``redispatches``/``redispatched`` are visible).  A
        raising user callback propagates into the engine's per-request
        isolation and fails this request (``error_kind="request"`` — a
        callback that raises would raise anywhere, so it is never
        replayed)."""
        def cb(tok: int, ereq: Request) -> None:
            entry = self._attempts.get(ereq)
            if entry is None or entry[0] is not freq:
                return               # stale attempt from an ejected replica
            # mirror the attempt's stream in lockstep, preserving the
            # fleet handle's list identity: the steady state is one
            # append per token; an engine-level preemption reset the
            # attempt's output_ids and restarted its stream from token
            # 0, so any length mismatch resyncs in place
            if len(ereq.output_ids) == len(freq.output_ids) + 1:
                freq.output_ids.append(int(tok))
            else:
                freq.output_ids[:] = ereq.output_ids
            freq.preempted = freq.preempted or ereq.preempted
            freq.preemptions = ereq.preemptions
            if freq.stream_cb is not None:
                freq.stream_cb(int(tok), freq)
        return cb

    def _dispatch(self, freq: FleetRequest,
                  exclude: Sequence[_Replica] = (),
                  pin: Optional[int] = None,
                  redispatch: bool = False) -> None:
        """Place ``freq`` on a replica (raises QueueFull/EngineStopped
        when the fleet genuinely cannot take it; ValueError only from
        enqueue-time validation, with the fleet handle rejected)."""
        excluded = list(exclude)
        while True:
            if pin is not None:
                if not (0 <= pin < self.num_replicas):
                    msg = (f"replica {pin} out of range "
                           f"[0, {self.num_replicas})")
                    self._finish(freq, "rejected", error=msg)
                    err = ValueError(msg)
                    err.request = freq
                    raise err
                rep = self.replicas[pin]
                if rep.state != "active":
                    raise EngineStopped(
                        f"replica {pin} is {rep.state}: cannot pin")
                affinity = 0
            else:
                rep, affinity = self._choose_replica(
                    freq.prompt_ids, excluded,
                    adapter=self._adapter_of(freq))
                if rep is None:
                    raise EngineStopped(
                        f"fleet {self.name!r} has no active replica "
                        "to dispatch to")
            # adoption window: the attempt span the engine creates
            # inside this add_request joins the fleet trace, parented on
            # the previous attempt (the redispatch chain) or the root;
            # the journal adoption mirrors it — every attempt's
            # admission record rides the ONE fleet-scoped journal id
            self.tracer.begin_attempt(freq, rep.engine.name)
            if self.journal is not None:
                if freq.journal_id is None:
                    freq.journal_id = (f"{self.name}:b{self.journal.boot}"
                                       f":f{freq.request_id}")
                self.journal.begin_attempt(
                    freq.journal_id, fleet_owned=True,
                    recovered=freq.recovered,
                    origin_wall=freq._origin_wall)
            try:
                ereq = rep.engine.add_request(
                    freq.prompt_ids, stream_cb=self._wrap_stream(freq),
                    **freq.kwargs)
            except ValueError as e:
                # enqueue-time validation: deterministic, final — with
                # the engine handle's machine-readable context (e.g. an
                # unknown/unloaded adapter's name + version) mirrored
                # onto the fleet handle
                ereq = getattr(e, "request", None)
                if ereq is not None and \
                        getattr(ereq, "error_ctx", None) is not None:
                    freq.error_ctx = dict(ereq.error_ctx)
                self._finish(freq, "rejected",
                             error=getattr(e.request, "error", str(e))
                             if hasattr(e, "request") else str(e))
                e.request = freq
                raise
            except (QueueFull, EngineStopped) as e:
                # this replica can't take it right now — try another
                if isinstance(e, ShedReject):
                    freq._shed_seen = True
                excluded.append(rep)
                if pin is not None or not self._active(excluded):
                    raise
                continue
            finally:
                self.tracer.end_attempt()
                if self.journal is not None:
                    self.journal.end_attempt()
            freq._attempt = ereq
            freq.model_version = rep.engine.model_version
            freq.replica_history.append(rep.engine.name)
            self._attempts[ereq] = (freq, rep)
            self.metrics.on_dispatch(affinity_tokens=affinity,
                                     pinned=pin is not None)
            self.tracer.on_dispatch(freq, rep.engine.name,
                                    redispatch=redispatch,
                                    affinity=affinity)
            return

    # -- public API --------------------------------------------------------

    def submit(self, prompt_ids: Sequence[int], *,
               max_new_tokens: int = 16,
               sampling: Optional[SamplingParams] = None,
               temperature: Optional[float] = None,
               eos_token_id: Optional[int] = None,
               stream_cb: Optional[Callable] = None,
               done_cb: Optional[Callable] = None,
               deadline_s: Optional[float] = None,
               priority=None,
               replica: Optional[int] = None) -> FleetRequest:
        """Enqueue a prompt on the fleet; returns the live
        :class:`FleetRequest` handle.

        Routing is prefix-affinity first, least-loaded otherwise;
        ``replica=<k>`` pins the dispatch (an operator/testing escape
        hatch that bypasses the policy).  A fleet whose aggregate queued
        depth is at ``max_queue`` raises :class:`QueueFull`; malformed
        prompts raise ``ValueError`` with the rejected handle on
        ``.request``.  ``deadline_s`` is a per-ATTEMPT wall-clock budget
        (it restarts on redispatch — a replay is a fresh prefill).
        ``priority`` (``"low"|"normal"|"high"`` or an int) rides in the
        dispatch kwargs, so it is preserved verbatim across redispatch —
        a replayed request keeps its class on the surviving replica."""
        if self.state != "active":
            raise EngineStopped(
                f"fleet {self.name!r} is {self.state}: not admitting "
                "new requests")
        self.metrics.on_submit()
        prompt = np.asarray(list(prompt_ids), dtype=np.int64).reshape(-1)
        if sampling is None and temperature is not None:
            sampling = SamplingParams(temperature=temperature)
        kwargs = {"max_new_tokens": int(max_new_tokens),
                  "eos_token_id": eos_token_id,
                  "deadline_s": deadline_s}
        if priority is not None:
            kwargs["priority"] = priority
        if sampling is not None:
            kwargs["sampling"] = sampling
        freq = FleetRequest(prompt_ids=prompt,
                            request_id=next(self._req_counter),
                            stream_cb=stream_cb, done_cb=done_cb,
                            kwargs=kwargs)
        freq.t_submit = time.perf_counter()
        freq._fleet = weakref.ref(self)
        self.tracer.on_submitted(freq, self.name)
        try:
            # normalized for the backpressure estimate only — kwargs keep
            # the caller's value verbatim for redispatch
            prio = _as_priority(kwargs.get("priority", PRIORITY_NORMAL))
        except ValueError as e:
            # a malformed priority must not leave the handle pending:
            # rejected exactly once, same contract as enqueue validation
            self._finish(freq, "rejected", error=str(e))
            e.request = freq
            raise
        if self.max_queue is not None:
            depth = sum(len(rep.engine.queue) for rep in self._active())
            if depth >= self.max_queue:
                # retry_after_s aggregates the same estimator the
                # engine-level shed uses — priced at THIS request's
                # priority class: the soonest any active replica expects
                # the backlog ahead of it to clear
                waits = [rep.engine.estimate_queue_wait_s(prio)
                         for rep in self._active()]
                retry = round(min(waits), 3) if waits else 0.0
                msg = (f"fleet queue full: {depth} >= "
                       f"max_queue={self.max_queue} across "
                       f"{len(self._active())} active replicas "
                       f"(retry_after_s={retry})")
                freq.error_ctx = {"depth": depth, "retry_after_s": retry}
                self._finish(freq, "rejected", error=msg)
                err = QueueFull(msg, depth, retry_after_s=retry)
                err.request = freq
                raise err
        try:
            self._dispatch(freq, pin=replica)
        except (QueueFull, EngineStopped) as e:
            # no replica could take it: the handle must still terminate
            # (rejected, exactly once) — a submit can never leave a
            # pending request the fleet no longer tracks.  Backpressure
            # and shed context stays machine-readable fleet-side.
            if isinstance(e, QueueFull):
                freq.error_ctx = {"depth": e.depth,
                                  "retry_after_s": e.retry_after_s}
            if isinstance(e, ShedReject) or freq._shed_seen:
                self._sheds += 1         # once per request, not per replica
            if not freq.done:
                self._finish(freq, "rejected", error=str(e))
            e.request = freq
            raise
        return freq

    def step(self) -> bool:
        """One fleet tick: step every active replica that has work, reap
        terminal attempts into fleet outcomes, then run the supervision
        poll (ejection → export/redispatch → rebuild).  Returns True
        while any request is in flight."""
        if self.state == "stopped":
            raise EngineStopped(f"fleet {self.name!r} is stopped")
        for rep in list(self.replicas):
            # "updating" replicas (mid weight-roll drain) keep stepping
            # their in-flight work; they just receive no new dispatches
            if rep.state not in ("active", "updating"):
                continue
            eng = rep.engine
            if (eng.queue or eng.running) and eng.state in (
                    "active", "draining"):
                try:
                    eng.step()
                except EngineStopped:
                    pass                 # unhealthy: supervision ejects it
            self._reap(rep)
        self._tick += 1
        if self._tick % self.supervise_every == 0:
            self._supervise()
            # replays parked for lack of a survivor go out only AFTER a
            # supervision pass — the implicated replica has had its
            # chance to be ejected and rebuilt before it can be chosen
            if self._repatriate:
                batch, self._repatriate = self._repatriate, []
                for freq, err in batch:
                    self._redispatch_or_fail(freq, err)
        return bool(self._attempts or self._repatriate)

    def run(self, max_steps: Optional[int] = None) -> None:
        """Drive ``step()`` until every submitted request is terminal
        (or ``max_steps``)."""
        n = 0
        while self.step():
            n += 1
            if max_steps is not None and n >= max_steps:
                break

    def generate(self, prompts: Sequence[Sequence[int]], *,
                 max_new_tokens: int = 16, **submit_kwargs
                 ) -> List[List[int]]:
        """Synchronous convenience: serve a batch of prompts through the
        fleet; returns generated ids per prompt."""
        reqs = [self.submit(p, max_new_tokens=max_new_tokens,
                            **submit_kwargs) for p in prompts]
        self.run()
        return [r.output_ids for r in reqs]

    # -- outcome plumbing --------------------------------------------------

    def _finish(self, freq: FleetRequest, state: str,
                error: Optional[str] = None) -> None:
        """THE single fleet-level terminal transition — guarded so every
        accepted request reaches a terminal state exactly once (a second
        arrival is counted on ``duplicate_terminals``, never applied)."""
        if freq.done:
            self.metrics.on_duplicate_terminal()
            return
        freq.state = state
        if error is not None:
            freq.error = error
        freq.t_finish = time.perf_counter()
        freq._attempt = None
        self.metrics.on_terminal(state)
        self.tracer.on_fleet_terminal(freq, state, error)
        if self.journal is not None and freq.journal_id is not None \
                and self.journal.has_admission(freq.journal_id):
            # THE one final end per journal id (engine-level retires of
            # fleet-owned requests were non-final attempt ends); a
            # rejected submit that never reached an engine admission
            # was delivered synchronously and is not journaled
            self.journal.record_end(
                freq.journal_id, state, final=True, error=freq.error,
                n_tokens=len(freq.output_ids))
        if freq.done_cb is not None:
            try:
                freq.done_cb(freq)
            except Exception:            # noqa: BLE001 — isolation boundary
                pass

    def _reap(self, rep: _Replica) -> None:
        """Map this replica's terminal engine requests onto fleet
        outcomes: finished/user-cancelled/request-fatal failures are
        final; replica-implicated failures re-dispatch within budget."""
        for ereq, (freq, _rep) in list(self._attempts.items()):
            if _rep is not rep or not ereq.done:
                continue
            del self._attempts[ereq]
            if freq.done:                # late echo of a settled request
                continue
            freq._attempt = None
            if getattr(ereq, "error_ctx", None) is not None and \
                    freq.error_ctx is None:
                # machine-readable failure context (adapter unload /
                # hot-swap mid-flight) survives onto the fleet handle
                freq.error_ctx = dict(ereq.error_ctx)
            if ereq.state == "finished":
                self._finish(freq, "finished")
            elif ereq.state == "cancelled":
                if freq._cancel:
                    self._finish(freq, "cancelled")
                elif ereq.error_kind == "replica":
                    # engine lifecycle cancelled it under the fleet
                    # (shutdown/export outside the eject path): replay
                    self._replay(freq, ereq.error, rep)
                else:
                    self._finish(freq, "cancelled", error=ereq.error)
            elif ereq.state == "failed":
                if ereq.error_kind == "replica":
                    self._replay(freq, ereq.error, rep)
                else:
                    self._finish(freq, "failed", error=ereq.error)
            else:                        # "rejected" cannot happen here
                self._finish(freq, ereq.state, error=ereq.error)

    def _replay(self, freq: FleetRequest, error: Optional[str],
                rep: _Replica) -> None:
        """Route a reaped replica-implicated failure: to a SURVIVOR when
        one exists (the implicated replica may still be in rotation,
        pre-ejection — never replay straight back onto it), else hold
        it for the post-supervision pass so a single-replica fleet can
        replay on its own rebuilt engine instead of failing outright."""
        if self._active((rep,)):
            self._redispatch_or_fail(freq, error, exclude=(rep,))
        else:
            self._repatriate.append((freq, error))

    def _redispatch_or_fail(self, freq: FleetRequest,
                            error: Optional[str],
                            exclude: Sequence[_Replica] = ()) -> None:
        """Replay ``freq`` from its prompt on another replica, within
        the at-most-``max_redispatch`` budget; over budget it fails with
        the replica's recorded error.  The stream contract: the marker
        fields flip and ``output_ids`` reset BEFORE the replay's token 0
        can arrive."""
        if freq.done:
            # settled while parked in _repatriate (user cancel between
            # steps): the terminal already happened exactly once
            return
        if freq._cancel:
            self._finish(freq, "cancelled")
            return
        if freq.redispatches >= self.max_redispatch:
            self._finish(
                freq, "failed",
                error=f"redispatch budget exhausted "
                      f"({self.max_redispatch}); last replica error: "
                      f"{error}")
            return
        freq.redispatches += 1
        freq.redispatched = True
        freq.output_ids = []
        self.metrics.on_redispatch()
        try:
            self._dispatch(freq, exclude=exclude, redispatch=True)
        except (QueueFull, EngineStopped) as e:
            self._finish(freq, "failed",
                         error=f"redispatch found no replica: {e}; "
                               f"original replica error: {error}")
        except ValueError:
            # _dispatch already finished it as rejected (cannot really
            # happen on a replay — the prompt validated once already)
            pass

    def _on_cancel(self, freq: FleetRequest) -> None:
        att = freq._attempt
        if att is not None:
            att.cancel()                 # reaped as cancelled next step
        elif not freq.done:
            self._finish(freq, "cancelled")

    # -- supervision -------------------------------------------------------

    def _supervise(self) -> None:
        """The robustness core: eject unhealthy/failing replicas (their
        orphaned requests collected for replay), rebuild every ejected
        replica, then re-dispatch the orphans onto the healed fleet."""
        orphans: List[Tuple[FleetRequest, str]] = []
        orphan_jids: Dict[int, List[str]] = {}
        for rep in self.replicas:
            if rep.state not in ("active", "updating"):
                continue
            h = rep.engine.health()      # also audits paged invariants
            if h["state"] == "unhealthy":
                reason = h.get("reason") or "unhealthy"
            elif h["consecutive_step_failures"] >= \
                    self.eject_after_failures:
                reason = (f"{h['consecutive_step_failures']} consecutive "
                          "compiled-step failures")
            else:
                continue
            mine = self._eject(rep, reason)
            orphans.extend(mine)
            orphan_jids[rep.index] = [
                freq.journal_id for freq, _ in mine
                if freq.journal_id is not None]
        for rep in self.replicas:
            if rep.state == "ejected":
                self._rebuild(rep,
                              orphan_jids=orphan_jids.get(rep.index, []))
        for freq, err in orphans:
            self._redispatch_or_fail(freq, err)

    def _eject(self, rep: _Replica, reason: str
               ) -> List[Tuple[FleetRequest, str]]:
        """Remove a replica from rotation: export its queued + in-flight
        requests for replay, shut the engine down (joins its watchdog
        thread; already-exported work cannot leak), and record why."""
        rep.state = "ejected"
        rep.ejections += 1
        rep._eject_t = time.perf_counter()
        rep.last_error = reason
        # devices the engine recorded lost (serving.shard_fail or real
        # device-loss detection) leave the pool for good: the rebuild
        # carves its mesh from whatever survives
        self._failed_devices.update(
            getattr(rep.engine, "lost_devices", ()))
        # the engine leaves rotation: bank its preemption counter so
        # the fleet aggregate survives the rebuild's fresh engine, and
        # freeze its flight recorder — the last-N-steps post-mortem is
        # attached to the rebuild record and outlives the engine
        self._banked_preemptions += rep.engine.metrics.requests_preempted
        rep.flight_dumps.append(
            rep.engine.flight.dump(f"ejected: {reason}"))
        del rep.flight_dumps[:-8]        # bounded: keep the newest 8
        self.metrics.on_eject()
        self.tracer.on_eject(rep.engine.name, reason)
        err = f"replica {rep.engine.name!r} ejected: {reason}"
        orphans = []
        for ereq in rep.engine.export_requests():
            entry = self._attempts.pop(ereq, None)
            if entry is None:
                continue
            freq = entry[0]
            freq._attempt = None
            if not freq.done:
                orphans.append((freq, err))
        try:
            rep.engine.shutdown(timeout_s=0.0)
        except Exception:                # noqa: BLE001 — already ejected
            pass
        return orphans

    #: consecutive failed rebuilds before a replica is marked ``dead``
    #: and leaves rotation for good — a deterministic rebuild failure
    #: must not spin warmup forever, but one transient hiccup must not
    #: permanently shrink the fleet either (each retry rides a later
    #: supervision pass, one per fleet step).
    MAX_REBUILD_ATTEMPTS = 3

    def _rebuild(self, rep: _Replica,
                 orphan_jids: Sequence[str] = ()) -> None:
        """Heal an ejected replica: fresh engine (fresh pool, fresh
        prefix cache, fresh executables), re-warm, rejoin rotation.  The
        eject→rejoin wall time is the fleet's measured failover
        recovery.

        **Degraded rebuild** (sharded groups): when ejection recorded
        lost devices, the group's surviving slice may no longer fit its
        configured mp — the rebuild then walks DOWN the viability
        ladder to the largest ``mp'`` the survivors support (down to
        ``mp'=1``) and carves a smaller mesh instead of dying.  The
        shape change is journaled as a ``mesh_reshard`` record carrying
        each orphaned request's disposition (``"redispatched"`` — they
        replay through the normal post-supervision pass), the degrade
        is counted/traced, and dispatch capacity rebalances via
        ``_effective_load``.  Only when not even ``mp'=1`` fits (every
        device of the slice lost) does the group go dead."""
        degrade = None                   # (old_mp, new_mp, old_key)
        devs = self._group_devices[rep.index]
        if devs is not None:
            from .sharding import (
                degrade_step, mesh_shape_key, serving_mesh, viable_ladder,
            )

            survivors = [d for d in devs
                         if d not in self._failed_devices]
            old_mesh = self._group_meshes[rep.index]
            old_mp = rep.model_parallel()
            if len(survivors) < old_mp:
                kv, nh = self._head_counts()
                new_mp = degrade_step(kv, nh, len(survivors))
                if new_mp is None:
                    rep.state = "dead"
                    rep.last_error = (
                        f"no viable degraded mesh: {len(survivors)} "
                        f"surviving device(s) in the group, viable "
                        f"ladder {viable_ladder(kv, nh)}")
                    self.metrics.on_rebuild(0.0, ok=False)
                    self.tracer.on_rebuild(rep.engine.name, 0.0,
                                           ok=False)
                    return
                self._group_meshes[rep.index] = serving_mesh(
                    new_mp, devices=survivors)
                degrade = (old_mp, new_mp, mesh_shape_key(old_mesh))
        try:
            eng = self._make_engine(rep.index)
            eng.warmup()
        except Exception as e:           # noqa: BLE001 — isolation boundary
            rep.rebuild_attempts += 1
            rep.state = ("dead" if rep.rebuild_attempts >=
                         self.MAX_REBUILD_ATTEMPTS else "ejected")
            rep.last_error = (f"rebuild failed "
                              f"({rep.rebuild_attempts}/"
                              f"{self.MAX_REBUILD_ATTEMPTS}): "
                              f"{type(e).__name__}: {e}")
            self.metrics.on_rebuild(0.0, ok=False)
            self.tracer.on_rebuild(rep.engine.name, 0.0, ok=False)
            return
        rep.engine = eng
        rep.state = "active"
        rep.rebuilds += 1
        rep.rebuild_attempts = 0
        recovery = time.perf_counter() - (rep._eject_t or
                                          time.perf_counter())
        rep._eject_t = None
        self.metrics.on_rebuild(recovery)
        self.tracer.on_rebuild(eng.name, recovery)
        if degrade is not None:
            old_mp, new_mp, old_key = degrade
            rep.degraded = new_mp < self.shards_per_group
            self.metrics.on_degrade(old_mp, new_mp, recovery)
            self.tracer.on_degrade(eng.name, old_mp, new_mp, recovery)
            if self.journal is not None:
                self.journal.record_mesh_reshard(
                    eng.name, old_key, eng.mesh_shape,
                    {jid: "redispatched" for jid in orphan_jids})

    # -- durability: crash recovery & rolling weight hot-swap --------------

    def recover(self, journal=None) -> dict:
        """Crash-consistent recovery: rehydrate every non-terminal
        journaled request from a previous process's
        :class:`~.journal.RequestJournal` and re-dispatch it across the
        fleet as a replay-from-prompt — ``recovered`` flag set, stream
        restarting at token 0, seeded from the journaled effective seed
        (greedy/seeded outputs bitwise identical to an uninterrupted
        run).  Pre-crash FINAL outcomes are banked into the fleet
        metrics so completed/failed stay monotone across the restart,
        and every replayed request keeps its original journal id — the
        journal-wide exactly-once audit (``duplicate_terminals == 0``)
        spans the crash.

        Call after ``warmup()``, before new traffic.  Returns
        ``{"replayed", "requests", "outcomes", "recovery_ms"}``."""
        journal = journal if journal is not None else self.journal
        if journal is None:
            raise ValueError("recover() needs a RequestJournal (pass "
                             "journal= here or to the Fleet)")
        if self.state != "active":
            raise EngineStopped(
                f"fleet {self.name!r} is {self.state}: cannot recover")
        if self._attempts or self._repatriate or any(
                rep.engine.queue or rep.engine.running
                for rep in self.replicas
                if rep.state in ("active", "updating")):
            # recovery on a LIVE fleet would re-dispatch every request
            # that is still in flight under its own journal id — a
            # guaranteed duplicate terminal (the engine-level recover
            # has the same guard)
            raise RuntimeError(
                "recover() must run before serving traffic: the fleet "
                f"has {self.pending} request(s) in flight whose journal "
                "ids the replay would duplicate")
        if self.journal is None:
            self.journal = journal
            for rep in self.replicas:
                rep.engine.journal = journal
        elif journal is not self.journal:
            # replaying journal B while recording into journal A would
            # leave B's pending set non-converging forever (a later
            # recover from B replays completed work again): one journal
            # per fleet, attached everywhere
            raise ValueError(
                "recover(journal=...) does not match the journal this "
                "fleet records into; recover into the SAME journal the "
                "fleet was constructed with (or construct the fleet "
                "with the journal being recovered)")
        t0 = time.perf_counter()
        outcomes = journal.outcomes()
        self.metrics.bank_outcomes(outcomes)
        replayed = []
        for jid, rec in journal.pending().items():
            replayed.append(self._submit_recovered(jid, rec))
        dt = time.perf_counter() - t0
        self.metrics.on_crash_recovery(len(replayed), dt)
        return {"replayed": len(replayed), "requests": replayed,
                "outcomes": outcomes,
                "recovery_ms": round(dt * 1e3, 3)}

    def _submit_recovered(self, jid: str, rec: dict) -> FleetRequest:
        """One journal replay: a fresh fleet handle carrying the
        ORIGINAL journal id and the journaled replay recipe, dispatched
        outside the fleet ``max_queue`` bound (this work was already
        accepted once — recovery must not shed it on backpressure)."""
        s = self.journal.replay_sampling(rec)
        kwargs = {"max_new_tokens": rec["max_new_tokens"],
                  "eos_token_id": rec["eos_token_id"],
                  "deadline_s": rec["deadline_s"],
                  "priority": rec["priority"],
                  "sampling": SamplingParams(**s)}
        freq = FleetRequest(
            prompt_ids=np.asarray(rec["prompt_ids"],
                                  dtype=np.int64).reshape(-1),
            request_id=next(self._req_counter), kwargs=kwargs)
        freq.journal_id = jid
        freq.recovered = True
        freq._origin_wall = rec.get("wall")
        freq.t_submit = time.perf_counter()
        freq._fleet = weakref.ref(self)
        self.metrics.on_submit()
        self.tracer.on_submitted(freq, self.name)
        problem = self._replay_tenancy_problem(rec, s)
        if problem is not None:
            # a replay whose adapter was unloaded / hot-swapped (or
            # whose grammar is gone) can never be bitwise — fail THIS
            # request with machine-readable context and keep draining
            # the rest of the pending set (never wedge the loop)
            msg, ctx = problem
            freq.error_ctx = ctx
            self._finish(freq, "failed", error=msg)
            return freq
        try:
            self._dispatch(freq)
        except (QueueFull, EngineStopped) as e:
            # the handle still terminates exactly once: a replay no
            # replica can take fails with the reason recorded
            if not freq.done:
                self._finish(freq, "failed",
                             error=f"recovery dispatch found no "
                                   f"replica: {e}")
        except ValueError:
            pass                         # _dispatch already rejected it
        return freq

    def _replay_tenancy_problem(self, rec: dict, s: dict):
        """Can this journaled replay still run bitwise on the current
        fleet?  Returns ``None`` when yes, else ``(message, error_ctx)``
        — the adapter must be loaded at the EXACT journaled version on
        some active replica (an unload or hot-swap in between means the
        replay would run different weights), and the grammar must still
        be registered."""
        a = s.get("adapter")
        if a is not None:
            want = rec.get("adapter_version")
            for rep in self._active():
                pool = getattr(rep.engine, "adapter_pool", None)
                if pool is None:
                    continue
                try:
                    _, v = pool.resolve(a)
                except KeyError:
                    continue
                if want is None or v == want:
                    break
            else:
                return (f"recovery replay rejected: journaled adapter "
                        f"{a!r} (v{want}) is not loaded at that version "
                        f"on any active replica",
                        {"adapter": a, "version": want})
        g = s.get("grammar")
        if g is not None:
            for rep in self._active():
                table = getattr(rep.engine, "grammar_table", None)
                if table is not None and g in table.names:
                    break
            else:
                return (f"recovery replay rejected: journaled grammar "
                        f"{g!r} is not registered on any active "
                        f"replica", {"grammar": g})
        return None

    def load_adapter(self, name: str, weights, *, scale: float = 1.0
                     ) -> int:
        """Load (or hot-swap) a LoRA adapter onto EVERY active replica's
        engine so fleet dispatch stays placement-free — any replica can
        serve any tenant.  Returns the adapter's registry version (all
        replicas agree when loads only go through the fleet).  Replicas
        rebuilt after a failure come back adapter-less: reload through
        this method before routing that tenant's traffic again."""
        if self.state != "active":
            raise EngineStopped(
                f"fleet {self.name!r} is {self.state}: cannot load "
                "adapters")
        version = None
        for rep in self.replicas:
            if rep.state not in ("active", "updating"):
                continue
            version = rep.engine.load_adapter(name, weights, scale=scale)
        if version is None:
            raise EngineStopped(
                f"fleet {self.name!r} has no active replica to load "
                f"adapter {name!r} onto")
        return version

    def unload_adapter(self, name: str) -> int:
        """Unload an adapter from every active replica.  In-flight
        requests of that tenant fail engine-side with machine-readable
        ``error_ctx`` (surfaced onto their fleet handles by ``_reap``);
        the registry remembers the name so version pins from journaled
        admissions keep failing loudly rather than replaying onto
        different weights."""
        if self.state != "active":
            raise EngineStopped(
                f"fleet {self.name!r} is {self.state}: cannot unload "
                "adapters")
        version = None
        for rep in self.replicas:
            if rep.state not in ("active", "updating"):
                continue
            version = rep.engine.unload_adapter(name)
        if version is None:
            raise EngineStopped(
                f"fleet {self.name!r} has no active replica to unload "
                f"adapter {name!r} from")
        return version

    def update_weights(self, state_or_path, *,
                       max_drain_steps: Optional[int] = None) -> dict:
        """Zero-downtime rolling weight hot-swap.

        Under weight isolation (the default for multi-replica fleets),
        replicas are taken out of dispatch rotation ONE AT A TIME
        (state ``updating``), drained of their in-flight work — the
        rest of the fleet keeps answering on the old weights the whole
        time — then swapped in place: the new weights are written
        *through* each replica's existing parameter buffers
        (``Engine.update_weights`` → ``set_state_dict`` write-through),
        so every warmed executable and its lifted state stay valid and
        ZERO new compile keys appear.  Each swap bumps the replica's
        prefix-cache version epoch (a request can never prefix-hit KV
        blocks prefilled under older weights) and its ``model_version``
        tag.  The template model is updated FIRST so a replica ejected
        and rebuilt mid-roll comes back at the new version.

        With shared weights (``isolate_weights=False`` or an
        uncloneable model) there is one parameter set, so the roll
        degrades to a documented stop-the-world swap: every replica is
        drained together, then the single write lands.

        ``max_drain_steps`` bounds each drain (RuntimeError past it —
        the fleet is left serving, partially rolled, with versions
        telling which replica serves what).  Accepts the same weight
        sources as ``Engine.update_weights``.  Returns
        ``{"model_version", "replicas_updated", "roll_ms"}``."""
        from .engine import _resolve_weights, _write_state_dict

        if self.state != "active":
            raise EngineStopped(
                f"fleet {self.name!r} is {self.state}: cannot roll "
                "weights")
        sd = _resolve_weights(state_or_path)
        new_version = self.model_version + 1
        t0 = time.perf_counter()
        updated = 0
        if self.weights_isolated:
            _write_state_dict(self.model, sd)
            self.model_version = new_version
            for rep in list(self.replicas):
                if rep.state != "active":
                    continue             # ejected/dead: rebuilds join
                rep.state = "updating"   # at the new template weights
                try:
                    self._drain_replica(rep, max_drain_steps)
                    if rep.state == "updating":
                        rep.engine.update_weights(sd,
                                                  version=new_version)
                        updated += 1
                finally:
                    if rep.state == "updating":
                        rep.state = "active"
        else:
            # stop-the-world fallback: ONE shared parameter set means
            # no replica can keep serving old weights while another
            # swaps — drain everything, then write once
            marked = [r for r in self.replicas if r.state == "active"]
            for rep in marked:
                rep.state = "updating"
            try:
                for rep in marked:
                    self._drain_replica(rep, max_drain_steps)
            finally:
                for rep in marked:
                    if rep.state == "updating":
                        rep.state = "active"
            _write_state_dict(self.model, sd)
            self.model_version = new_version
            for rep in marked:
                # ONE write through the shared buffers (above); each
                # engine still gets its own epoch/version bookkeeping
                if rep.state == "active" and not (
                        rep.engine.queue or rep.engine.running):
                    rep.engine._mark_weights_swapped(new_version)
                    updated += 1
        dt = time.perf_counter() - t0
        self.metrics.on_weight_roll(new_version, dt)
        self.tracer.on_weight_roll(self.name, new_version, dt, updated)
        return {"model_version": new_version,
                "replicas_updated": updated,
                "roll_ms": round(dt * 1e3, 3)}

    def _drain_replica(self, rep: _Replica,
                       max_drain_steps: Optional[int]) -> None:
        """Drive fleet steps until ``rep`` holds no queued or running
        work (the whole fleet — this replica's in-flight requests
        included — keeps stepping; only new dispatches avoid it).  An
        ejection mid-drain exits early: the rebuilt engine is empty."""
        n = 0
        while rep.state == "updating" and (rep.engine.queue or
                                           rep.engine.running):
            self.step()
            n += 1
            if max_drain_steps is not None and n >= max_drain_steps:
                raise RuntimeError(
                    f"replica {rep.engine.name!r} did not drain within "
                    f"{max_drain_steps} fleet steps (still "
                    f"{len(rep.engine.running)} running, "
                    f"{len(rep.engine.queue)} queued)")

    # -- lifecycle ---------------------------------------------------------

    def drain(self, max_steps: Optional[int] = None) -> dict:
        """Stop admitting, finish every in-flight request (supervision —
        ejection and rebuild included — keeps running while draining),
        stop all replicas, and return the final stats snapshot."""
        if self.state == "active":
            self.state = "draining"
        n = 0
        while (self._attempts or self._repatriate) and \
                self.state == "draining":
            self.step()
            n += 1
            if max_steps is not None and n >= max_steps:
                break
        for rep in self.replicas:
            if rep.state == "active":
                rep.engine.drain()
        # the engine drains above may have finished work the step loop
        # never saw (max_steps cut it short): reap it into fleet
        # terminals so every done_cb fires and pending reaches 0
        for rep in self.replicas:
            if rep.state == "active":
                self._reap(rep)
        if not (self._attempts or self._repatriate):
            self.state = "stopped"
        return self.stats()

    def shutdown(self, timeout_s: Optional[float] = None) -> dict:
        """Drain within a wall-clock budget, then cancel whatever is
        still unfinished and stop every replica."""
        if self.state == "active":
            self.state = "draining"
        deadline = None if timeout_s is None \
            else time.perf_counter() + float(timeout_s)
        while (self._attempts or self._repatriate) and \
                self.state == "draining":
            if deadline is not None and time.perf_counter() >= deadline:
                break
            self.step()
        for ereq, (freq, _rep) in list(self._attempts.items()):
            del self._attempts[ereq]
            self._finish(freq, "cancelled", error="fleet shutdown")
        for freq, _err in self._repatriate:
            if not freq.done:
                self._finish(freq, "cancelled", error="fleet shutdown")
        self._repatriate.clear()
        for rep in self.replicas:
            if rep.state == "active":
                try:
                    rep.engine.shutdown(timeout_s=0.0)
                except Exception:        # noqa: BLE001 — best effort
                    pass
        self.state = "stopped"
        return self.stats()

    # -- observability -----------------------------------------------------

    @property
    def pending(self) -> int:
        """Accepted requests not yet terminal."""
        return len(self._attempts) + len(self._repatriate)

    def _replica_rows(self) -> List[dict]:
        rows = []
        for rep in self.replicas:
            eng = rep.engine
            m = eng.metrics
            rows.append({
                "index": rep.index,
                "name": eng.name,
                "state": rep.state,
                "engine_state": eng.state,
                "ejections": rep.ejections,
                "rebuilds": rep.rebuilds,
                "last_error": rep.last_error,
                "queue_depth": len(eng.queue),
                "slots_busy": len(eng.running),
                "slots_total": eng.num_slots,
                "occupancy": round(m.occupancy(), 4),
                "compile_misses": m.compile_misses,
                "mesh_shape": eng.mesh_shape,
                "model_parallel": rep.model_parallel(),
                "degraded": rep.degraded,
                "preemptions": m.requests_preempted,
                "shed": m.requests_shed,
                # the rebuild record's post-mortem attachment: a summary
                # of the flight dump frozen at the last ejection (the
                # full dump rides profiler.serving_flight_record())
                "last_flight_record": (
                    {"reason": rep.flight_dumps[-1]["reason"],
                     "steps_seen": rep.flight_dumps[-1]["steps_seen"],
                     "events": len(rep.flight_dumps[-1]["events"])}
                    if rep.flight_dumps else None),
            })
        return rows

    def _flight_dump_table(self) -> Dict[str, List[dict]]:
        """Banked ejection dumps per engine name — merged into
        ``profiler.serving_flight_record()`` so a dump survives its
        (discarded) engine."""
        out: Dict[str, List[dict]] = {}
        for rep in self.replicas:
            if rep.flight_dumps:
                out.setdefault(rep.engine.name, []).extend(
                    rep.flight_dumps)
        return out

    def _overload_section(self) -> dict:
        """Fleet-wide overload totals: preemptions are per-engine events
        (banked from ejected engines plus every in-rotation engine's
        live counter); ``shed`` counts fleet-level shed *submits* —
        once per request, even when several replicas shed it before the
        dispatch gave up."""
        pre = self._banked_preemptions
        for rep in self.replicas:
            if rep.state != "active":
                continue                 # ejected engines are banked
            pre += rep.engine.metrics.requests_preempted
        return {"preemptions": pre, "shed": self._sheds}

    def health(self) -> dict:
        """Fleet liveness probe: fleet state, per-replica health, and
        in-flight depth — the load-balancer view one level above
        ``Engine.health()``."""
        return {
            "state": self.state,
            "pending": self.pending,
            "active_replicas": len(self._active()),
            "replicas": {rep.engine.name: {
                "replica_state": rep.state,
                **rep.engine.health(),
            } for rep in self.replicas},
        }

    def stats(self) -> dict:
        """``/stats``-style snapshot (also exported through
        ``paddle_tpu.profiler.serving_fleet()``): the fleet metrics plus
        each replica's full engine snapshot."""
        out = self.metrics.snapshot()
        out["state"] = self.state
        out["pending"] = self.pending
        out["durability"]["weights_isolated"] = self.weights_isolated
        if self.journal is not None:
            out["durability"]["journal"] = self.journal.stats()
        out["overload"] = self._overload_section()
        # degraded-mode view (docs/SERVING.md "Degraded sharded
        # serving"): the FleetMetrics "degraded" counters plus the live
        # per-group mp and the devices the fleet has written off
        out.setdefault("degraded", {})
        out["degraded"]["failed_devices"] = len(self._failed_devices)
        out["degraded"]["groups"] = {
            rep.engine.name: {
                "model_parallel": rep.model_parallel(),
                "configured": self.shards_per_group,
                "degraded": rep.degraded,
                "state": rep.state,
            } for rep in self.replicas}
        if self.tracer.enabled:
            out["tracing"] = self.tracer.snapshot()
        out["engines"] = {rep.engine.name: rep.engine.stats()
                          for rep in self.replicas}
        return out
