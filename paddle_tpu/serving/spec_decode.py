"""Speculative decoding: draft-propose, bucketed verify, device accept.

The decode loop's cost is one target-model forward per emitted token.
Speculative decoding breaks that coupling (ROADMAP item 4(b)): a small
**draft model** proposes ``k`` tokens autoregressively (k cheap forwards),
then the target model scores the last emitted token plus all k proposals
in ONE fixed-shape ``[slots, k+1]`` **verify step** — a k+1-wide bucket
through the same ``CacheContext`` machinery as prefill/decode — and
standard rejection-sampling acceptance keeps the longest valid draft
prefix plus one bonus/resample token.  Per round each slot emits between
1 and k+1 tokens for one target-window forward, so a well-matched draft
cuts target forwards per token by up to (k+1)×.

Fit with the engine's discipline (docs/SERVING.md "Speculative
decoding"):

- **Fixed shapes, zero steady-state recompiles.**  One draft-prefill
  program per bucket, ONE draft-decode program (the proposal column
  index ``j`` is a traced scalar argument), ONE verify program.  Slot
  index, lengths, active mask, caps, and proposals are all argument or
  state *values* — the compiled key set stays closed
  (``tools/shape_manifest.json`` ``speculative`` section).
- **Zero host transfers per round.**  Proposals chain through the draft
  sampler's device token lane, the verify step consumes them from the
  ``proposals`` state lane, and acceptance runs in-graph
  (:meth:`DeviceSampler.accept_speculative`).  The host pulls ONE small
  ``[slots, k+2]`` int32 array per round for stream delivery —  the
  same shape-class pull as non-speculative decode's token array, and
  outside the sanitizer window.
- **Greedy is bitwise.**  A greedy slot's every emitted token is the
  target argmax at its position, so speculative greedy output is
  bitwise identical to non-speculative decoding; seeded sampling is
  distribution-preserving by the rejection-sampling identity.
- **Rollback is bookkeeping.**  Rejected window positions are rolled
  back by the in-graph length advance (only ``m`` of ``k+1`` writes
  become readable); paged mode additionally truncates the slot's block
  table past the accepted length (refcount moves, no copies).
- **The draft's KV window is recomputed inside the verify step.**  The
  verify program runs the draft model over the same ``[slots, k+1]``
  window (after rewinding the draft lengths to the round start), which
  (a) supplies the exact proposal law for the acceptance ratio without
  stashing ``[slots, k, V]`` probabilities, and (b) writes the draft KV
  for ALL window positions — so even a fully-accepted round leaves both
  caches in lockstep (``draft length == target length``) with one
  pending token, and no per-slot catch-up state exists anywhere.  The
  draft runs twice per window; the premise of speculation is that the
  draft is small enough for that to be noise against the target.

Durability: draft KV is deliberately NOT journaled/durable — crash
recovery and preemption both replay from the prompt, which re-prefills
the draft cache as a side effect of re-admission (the PR 6/8/13
stream-restart contract covers a speculating request unchanged).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from .kv_cache import CacheContext, KVCache
from .sampling import DeviceSampler

__all__ = ["SpecConfig", "SpecState"]

#: Mixed into the request's effective seed to derive the draft model's
#: key-lane seed: the draft must draw an independent stream (its
#: proposals are priced by the acceptance ratio, not replayed by the
#: target), but a deterministic one — preempt-resume and journal
#: recovery re-seed both lanes from the same journaled effective seed.
DRAFT_SEED_SALT = 0x5DC0DE


@dataclass
class SpecConfig:
    """Opt-in speculative decoding for :class:`~.engine.Engine`.

    Args:
        draft_model: the proposal model — anything
            ``Engine.resolve_model`` accepts (a model Layer, a
            ``GPTConfig``/``LlamaConfig``, or a registry name like
            ``"gpt:tiny"``).  Must share the target's vocabulary and
            cover the engine's ``max_seq`` positions.  May be the
            target model itself (self-speculation — useful as a
            deterministic full-acceptance drill).
        k: draft tokens proposed per round (the verify bucket is
            ``k + 1`` wide).  Each round costs k draft steps + one
            verify step and emits 1..k+1 tokens per slot.
        draft_cache_dtype: draft KV cache dtype (default: the draft
            model's parameter dtype, like the engine's own cache).
    """

    draft_model: Any
    k: int = 4
    draft_cache_dtype: Optional[str] = None

    def __post_init__(self):
        if int(self.k) < 1:
            raise ValueError(f"SpecConfig.k must be >= 1, got {self.k}")


class SpecState:
    """Per-engine speculative-decoding state: the draft model, its own
    contiguous KV pool (sharing the engine's slot table — slot ``i`` of
    the draft cache mirrors slot ``i`` of the target's), the draft
    :class:`DeviceSampler` (proposal params/keys/token lanes), and the
    ``[slots, k]`` proposals lane the verify step consumes.

    The draft cache is contiguous regardless of the engine's layout —
    it is small by construction (draft model × max_seq) and holds no
    shareable prefixes worth paging; its ``max_seq`` carries ``k``
    positions of headroom so a near-capacity round's draft steps never
    clamp a write onto a live position.
    """

    def __init__(self, engine, config: SpecConfig):
        from .engine import Engine

        self.config = config
        self.k = int(config.k)
        model = Engine.resolve_model(config.draft_model)
        dcfg = getattr(model, "config", None)
        if dcfg is None:
            raise TypeError("SpecConfig.draft_model needs a model "
                            "carrying a .config")
        if dcfg.vocab_size != engine.config.vocab_size:
            raise ValueError(
                f"draft vocab_size {dcfg.vocab_size} != target "
                f"{engine.config.vocab_size}: speculative acceptance "
                "compares distributions over one shared vocabulary")
        max_pos = getattr(dcfg, "max_position_embeddings", None)
        if max_pos is not None and max_pos < engine.max_seq:
            raise ValueError(
                f"draft max_position_embeddings {max_pos} < engine "
                f"max_seq {engine.max_seq}: the draft must cover every "
                "position it verifies")
        self.model = model
        self.model.eval()
        dtype = config.draft_cache_dtype
        if dtype is None:
            params = model.parameters()
            dtype = params[0].dtype if params else "float32"
        kv_heads = getattr(dcfg, "n_kv_heads", None) \
            or dcfg.num_attention_heads
        self.cache = KVCache(
            num_slots=engine.num_slots,
            num_layers=dcfg.num_hidden_layers,
            max_seq=engine.max_seq + self.k,
            num_kv_heads=kv_heads, head_dim=dcfg.head_dim, dtype=dtype)
        # the draft sampler shares the ENGINE's grammar table (one
        # stacked trans/mask pair serves both models), so draft
        # proposals are drawn from the same masked support the verify
        # step prices — see DeviceSampler.accept_speculative
        self.sampler = DeviceSampler(engine.num_slots,
                                     grammar=engine.sampler.grammar)
        self.proposals = Tensor._wrap(
            jnp.zeros((engine.num_slots, self.k), dtype=jnp.int32))
        self.proposals.persistable = True

    # -- host-side slot lifecycle (value-only, never a shape) --------------

    @staticmethod
    def draft_seed(seed: int) -> int:
        return int(seed) ^ DRAFT_SEED_SALT

    def stage_slot(self, slot: int, params, seed: int) -> None:
        """Stage the draft lanes at admission (and preempt-resume /
        recovery re-admission): same sampling params as the target —
        the proposal law the acceptance ratio prices — with a
        salt-derived, deterministic key seed."""
        self.sampler.stage_slot(slot, params, self.draft_seed(seed))

    def release_slot(self, slot: int) -> None:
        """Forget a retired/preempted slot's draft sequence (the KV
        bytes become unreadable; re-admission re-prefills)."""
        self.cache.set_length(slot, 0)

    def reset(self) -> None:
        """Forget everything (warmup scribbles slot 0's draft state)."""
        self.cache.reset()
        self.sampler.reset()
        self.proposals._set_data(
            jnp.zeros(self.proposals.shape, dtype=jnp.int32))

    # -- program bodies (wrapped by Engine._build_steps via to_static) -----

    def make_draft_prefill(self, engine):
        """Draft prompt prefill, one program per bucket: writes the
        prompt's draft KV into the slot and chains the draft token lane
        off the target's pending (prefill-sampled) first token — so the
        first draft step of the first round feeds device-side."""
        spec = self

        def draft_prefill(input_ids, slot, length):
            ctx = CacheContext(spec.cache, "prefill", slot=slot,
                               length=length)
            spec.model(input_ids, cache_ctx=ctx)
            spec.cache.set_length(slot, length)
            s = slot._value().astype(jnp.int32).reshape(())
            tok = jax.lax.dynamic_index_in_dim(
                engine.sampler.tokens._value(), s, 0, keepdims=False)
            spec.sampler.tokens._set_data(
                spec.sampler.tokens._value().at[s].set(tok))
            if spec.sampler.grammar is not None:
                # sync the automaton alongside the token it chains off:
                # the target's prefill advanced past the first sampled
                # token; the draft's first round starts from that state
                gst = jax.lax.dynamic_index_in_dim(
                    engine.sampler.grammar_states._value(), s, 0,
                    keepdims=False)
                spec.sampler.grammar_states._set_data(
                    spec.sampler.grammar_states._value().at[s].set(gst))
            return Tensor._wrap(tok)

        return draft_prefill

    def make_draft_decode(self, engine):
        """ONE draft-decode program for every proposal position: the
        column index ``j`` is a traced scalar, so k sequential calls
        per round share one compiled key.  Each call feeds the draft
        token lane, writes this proposal into ``proposals[:, j]``, and
        chains the lane for the next call."""
        spec = self

        def draft_decode(active, j):
            tokens = Tensor._wrap(spec.sampler.tokens._value()[:, None])
            ctx = CacheContext(spec.cache, "decode", active=active)
            logits = spec.model(tokens, cache_ctx=ctx)
            spec.cache.advance(active)
            prop = spec.sampler.sample_all(
                logits._value()[:, -1, :].astype(jnp.float32))
            jcol = j._value().astype(jnp.int32).reshape(())
            spec.proposals._set_data(jax.lax.dynamic_update_slice(
                spec.proposals._value(), prop[:, None],
                (jnp.int32(0), jcol)))
            return Tensor._wrap(prop)

        return draft_decode

    def make_verify(self, engine):
        """The verify program: one ``[slots, k+1]`` target forward over
        (pending token + proposals), the draft's window recomputed in
        the same program (rewound to the round-start offset — see the
        module docstring for why), in-graph acceptance, and the length
        advance that IS the rollback (only the accepted prefix + bonus
        become readable)."""
        spec = self
        W = self.k + 1

        def verify_step(active, cap):
            draft_toks = spec.proposals._value()
            toks = jnp.concatenate(
                [engine.sampler.tokens._value()[:, None], draft_toks],
                axis=1)                                  # [slots, W]
            t_in = Tensor._wrap(toks)
            tctx = CacheContext(engine.cache, "verify", active=active,
                                width=W)
            pool = engine.adapter_pool
            if pool is not None:
                # target verifies under each slot's adapter lane; the
                # draft below runs un-adapted (acceptance prices the
                # real draft law — see serving.adapters docstring)
                pool.set_rows(pool.adapter_ids._value())
            try:
                tlogits = engine.model(t_in, cache_ctx=tctx)
            finally:
                if pool is not None:
                    pool.clear_rows()
            # rewind the draft to the round-start offset (its k decode
            # steps advanced it) and recompute its window: draft KV for
            # all W positions + the exact proposal law for acceptance
            spec.cache.lengths._set_data(engine.cache.lengths._value())
            dctx = CacheContext(spec.cache, "verify", active=active,
                                width=W)
            dlogits = spec.model(t_in, cache_ctx=dctx)
            emitted, m = engine.sampler.accept_speculative(
                tlogits._value().astype(jnp.float32),
                dlogits._value().astype(jnp.float32),
                draft_toks, cap._value().astype(jnp.int32),
                spec.sampler)
            adv = m * active._value().astype(jnp.int32)
            engine.cache.advance(adv)
            spec.cache.advance(adv)
            out = jnp.concatenate([adv[:, None], emitted], axis=1)
            return Tensor._wrap(out.astype(jnp.int32))

        return verify_step

    def nbytes(self) -> int:
        return self.cache.nbytes()

    def snapshot(self) -> dict:
        """Config half of ``stats()["speculation"]`` (the counters live
        in :class:`~.metrics.ServingMetrics`)."""
        return {
            "k": self.k,
            "draft_layers": self.cache.num_layers,
            "draft_cache_mb": round(self.cache.nbytes() / 2 ** 20, 3),
        }
