"""Cross-request prefix reuse: a host-side hash-chained block cache.

``PrefixCache`` maps whole prompt token *blocks* to KV-pool block ids so a
request whose prompt starts with a previously-served prefix skips
re-prefilling the shared span: the engine looks the prompt up, maps the
hit to refcounted shared blocks in the :class:`~.paging.BlockAllocator`
pool, and prefills only the uncached tail bucket.

Design points (all host-side — nothing here ever enters a trace, so the
zero-recompile property of the serving engine is untouchable from this
module):

- **Whole blocks only.**  A block of ``block_size`` tokens is the unit of
  both storage and matching: partial-block hits would share K/V lines that
  a later request must append into, which is exactly the aliasing the
  block-granular design avoids.
- **Hash-chained keys.**  Block ``i``'s key is
  ``H(key[i-1] || tokens[i*bs:(i+1)*bs])``, so a lookup hit is always a
  *contiguous prefix*: the walk stops at the first absent link and can
  never skip-match an interior block.
- **Capped below the full prompt.**  At most ``(len(prompt) - 1) // bs``
  blocks can hit, so the uncached tail always holds >= 1 token — the
  engine still runs a real prefill and gets first-token logits, and a
  tail write never lands inside a shared block (copy-on-extend stays a
  defensive path, not a steady-state one).
- **One reference per cached block.**  Registering a block takes a single
  allocator ref on behalf of the cache; live slots stack their own refs
  on top.  Evicting an entry drops only the cache's ref — blocks still
  referenced by running requests stay alive (they just stop being
  hittable).
- **LRU, leaf-first eviction.**  Entries are kept in recency order and
  only chain *leaves* (entries with no cached children) are evictable, so
  the cache always stores contiguous chains; candidates must also be
  idle (refcount 1 — the cache's own ref) or evicting them would free
  nothing.
- **Version epoch.**  Cached K/V bytes are a function of the *weights*
  that prefilled them, so a rolling weight hot-swap must make every
  pre-swap block unhittable: :meth:`bump_epoch` folds a monotonically
  increasing epoch into the chain-hash ROOT.  A lookup under epoch
  ``N+1`` can never match an entry registered under epoch ``N`` — the
  keys live in disjoint hash domains by construction, which is a
  stronger guarantee than clearing (there is no window where a stale
  entry is still reachable).  The bump also drops every idle entry so
  the old-weight blocks return to the pool.
- **Tenant salt.**  Multi-LoRA serving makes cached K/V a function of
  the *adapter* that prefilled it too, so every lookup/register/probe
  takes a ``salt`` (``b""`` for the base model, ``b"name@vN"`` from
  :meth:`~.adapters.AdapterPool.salt` for a tenant) folded into the
  chain-hash root alongside the epoch.  Tenant KV can never cross-hit
  another tenant — or a stale version of itself — by the same
  disjoint-domain argument as the epoch.
"""
from __future__ import annotations

import hashlib
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["PrefixCache"]

_ROOT = b"paddle-tpu-prefix-root"


def _chain_hash(parent: bytes, tokens: np.ndarray) -> bytes:
    h = hashlib.blake2b(digest_size=16)
    h.update(parent)
    h.update(np.ascontiguousarray(tokens, dtype=np.int64).tobytes())
    return h.digest()


@dataclass
class _Entry:
    block_id: int
    parent: Optional[bytes]
    children: int = 0
    depth: int = 0                      # chain position (0 = first block)
    hits: int = field(default=0)


class PrefixCache:
    """Host-side chained-hash map from prompt blocks to pool block ids."""

    def __init__(self, allocator, block_size: int):
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.allocator = allocator
        self.block_size = int(block_size)
        self._entries: "OrderedDict[bytes, _Entry]" = OrderedDict()
        #: weight-version epoch: folded into every chain-hash root, so
        #: entries registered under an older epoch are unreachable by
        #: construction (rolling hot-swap correctness — see module doc)
        self.epoch = 0
        # counters (exported via Engine metrics)
        self.lookups = 0
        self.hit_blocks_total = 0
        self.hit_tokens_total = 0
        self.lookup_tokens_total = 0
        self.evictions = 0
        # the allocator reclaims idle cached blocks through this hook
        allocator.evict_cb = self._evict_for_alloc

    # -- lookup / register -------------------------------------------------

    def _keys_for(self, prompt: np.ndarray, n_blocks: int,
                  salt: bytes = b"") -> List[bytes]:
        bs, keys = self.block_size, []
        parent = _ROOT + self.epoch.to_bytes(8, "little") + salt
        for i in range(n_blocks):
            parent = _chain_hash(parent, prompt[i * bs:(i + 1) * bs])
            keys.append(parent)
        return keys

    def record_lookup(self, prompt_tokens: int, hit_tokens: int) -> None:
        """Count one logical lookup toward the hit-rate gauges.  The
        engine calls this only for results it actually USED (and once
        per request, not per deferral retry), so ``hit_rate`` never
        credits tokens that were re-prefilled anyway — discarded
        (over-budget) and raising lookups are recorded as misses."""
        self.lookups += 1
        self.lookup_tokens_total += int(prompt_tokens)
        self.hit_blocks_total += int(hit_tokens) // self.block_size
        self.hit_tokens_total += int(hit_tokens)

    def lookup(self, prompt: Sequence[int], count: bool = True,
               salt: bytes = b"") -> Tuple[int, List[int]]:
        """Longest cached prefix of ``prompt``: ``(n_tokens, block_ids)``.

        Walks the hash chain over whole prompt blocks, stopping at the
        first absent link; capped so at least one prompt token is always
        left for the tail prefill.  Touches every hit entry (LRU refresh)
        but takes NO references — the caller refs the blocks it actually
        admits a sequence onto.  ``count=False`` skips the hit-rate
        counters — the engine counts via :meth:`record_lookup` instead,
        after it has decided whether the result is actually used."""
        prompt = np.asarray(list(prompt), dtype=np.int64).reshape(-1)
        if count:
            self.lookups += 1
            self.lookup_tokens_total += int(prompt.size)
        max_hit = max(0, (int(prompt.size) - 1) // self.block_size)
        block_ids: List[int] = []
        for key in self._keys_for(prompt, max_hit, salt):
            e = self._entries.get(key)
            if e is None:
                break
            e.hits += 1
            self._entries.move_to_end(key)
            block_ids.append(e.block_id)
        if count:
            self.hit_blocks_total += len(block_ids)
            self.hit_tokens_total += len(block_ids) * self.block_size
        return len(block_ids) * self.block_size, block_ids

    def probe(self, prompt: Sequence[int], salt: bytes = b"") -> int:
        """Side-effect-free longest-cached-prefix length in TOKENS: no
        LRU refresh, no hit/lookup counters, no references taken.  The
        fleet router's affinity probe — it may interrogate every
        replica's cache per dispatch, and only the chosen replica's
        recency order and hit-rate gauges should move (they do, at
        admission, through the real :meth:`lookup`)."""
        prompt = np.asarray(list(prompt), dtype=np.int64).reshape(-1)
        max_hit = max(0, (int(prompt.size) - 1) // self.block_size)
        n = 0
        for key in self._keys_for(prompt, max_hit, salt):
            if key not in self._entries:
                break
            n += 1
        return n * self.block_size

    def register(self, prompt: Sequence[int], block_ids: Sequence[int],
                 salt: bytes = b"") -> int:
        """Make ``prompt``'s whole blocks hittable by later requests.

        ``block_ids`` must cover the prompt's full blocks in order (the
        slot's table prefix).  Blocks already registered under the same
        chain key are left as-is (first writer wins — the bytes are
        bitwise-identical by construction); each newly-registered block
        takes one allocator ref on behalf of the cache.  Returns how many
        new entries were created."""
        prompt = np.asarray(list(prompt), dtype=np.int64).reshape(-1)
        n_full = min(int(prompt.size) // self.block_size, len(block_ids))
        created, parent = 0, None
        for depth, key in enumerate(self._keys_for(prompt, n_full, salt)):
            e = self._entries.get(key)
            if e is not None:
                self._entries.move_to_end(key)
                parent = key
                continue
            self._entries[key] = _Entry(
                block_id=int(block_ids[depth]), parent=parent, depth=depth)
            self.allocator.ref(int(block_ids[depth]))
            self.allocator.mark_cached(int(block_ids[depth]))
            if parent is not None:
                self._entries[parent].children += 1
            parent = key
            created += 1
        return created

    # -- eviction ----------------------------------------------------------

    def _evictable(self) -> Optional[bytes]:
        """Oldest leaf entry whose block is idle (cache holds the only
        ref) — evicting anything else would either break a chain or free
        nothing."""
        for key, e in self._entries.items():
            if e.children == 0 and self.allocator.refcount(e.block_id) == 1:
                return key
        return None

    def _evict_one(self, key: bytes) -> None:
        e = self._entries.pop(key)
        if e.parent is not None and e.parent in self._entries:
            self._entries[e.parent].children -= 1
        self.allocator.unmark_cached(e.block_id)
        self.allocator.unref(e.block_id)
        self.evictions += 1

    def _evict_for_alloc(self, n_blocks: int) -> int:
        """Allocator pressure hook: free up to ``n_blocks`` idle cached
        blocks, LRU leaf-first.  Returns how many were freed."""
        freed = 0
        while freed < n_blocks:
            key = self._evictable()
            if key is None:
                break
            self._evict_one(key)
            freed += 1
        return freed

    def bump_epoch(self) -> int:
        """Invalidate every cached block for a weight hot-swap: advance
        the epoch (new lookups/registrations hash in a disjoint domain —
        an old-epoch entry can never prefix-hit again) and drop every
        idle entry so the stale-KV blocks return to the pool.  Entries
        still pinned by live slots keep their refs until those slots
        release — they are unreachable either way.  Returns the new
        epoch."""
        self.epoch += 1
        self.clear()
        return self.epoch

    def clear(self) -> int:
        """Drop every entry (releasing the cache's refs).  Returns the
        number of entries dropped."""
        n = 0
        while self._entries:
            key = self._evictable()
            if key is None:
                # remaining entries are pinned by live slots: drop the
                # cache's view of them anyway (refs released, chains gone)
                key = next(iter(self._entries))
            self._evict_one(key)
            n += 1
        return n

    # -- introspection -----------------------------------------------------

    def __len__(self) -> int:
        return len(self._entries)

    def hit_rate(self) -> float:
        """Fraction of looked-up prompt tokens served from cache."""
        return self.hit_tokens_total / self.lookup_tokens_total \
            if self.lookup_tokens_total else 0.0

    def stats(self) -> dict:
        return {
            "entries": len(self._entries),
            "epoch": self.epoch,
            "lookups": self.lookups,
            "hit_blocks": self.hit_blocks_total,
            "hit_tokens": self.hit_tokens_total,
            "lookup_tokens": self.lookup_tokens_total,
            "hit_rate": round(self.hit_rate(), 4),
            "evictions": self.evictions,
        }
