"""paddle_tpu.serving — the LLM serving engine.

A slot-based continuous-batching serving stack for the flagship causal-LM
families (``models.GPTForCausalLM`` / ``models.LlamaForCausalLM``):

- :class:`KVCache` — preallocated ``[slots, layers, max_seq, kv_heads,
  head_dim]`` key/value storage with per-slot length tracking;
- :class:`Engine` — request queue + slot scheduler, bucketed prefill with a
  compiled-executable cache (zero steady-state recompiles), **on-device**
  greedy/temperature/top-k/top-p sampling (:class:`DeviceSampler` — the
  decode step is one dispatch with zero blocking host transfers; paged
  mode streams K/V blocks through Pallas flash-decoding kernels),
  per-token streaming callbacks;
- :class:`ServingMetrics` — TTFT / inter-token latency / tokens-per-sec /
  queue depth / slot occupancy / compile-cache / failure counters,
  exported as a ``/stats``-style dict and via
  ``paddle_tpu.profiler.serving_stats()``.

Speculative decoding is an opt-in multiplier on the decode loop
(``Engine(speculation=SpecConfig(draft_model=..., k=4))``): a small
draft model proposes k tokens per round, one fixed-shape
``[slots, k+1]`` verify step scores them all through the same cache
machinery, and device-side rejection-sampling acceptance keeps the
longest valid prefix plus a bonus token — greedy output bitwise equal
to plain decoding, seeded sampling distribution-preserving, zero new
host transfers per round — see docs/SERVING.md "Speculative decoding".

The engine degrades per-request, never per-engine: terminal states
``failed | cancelled | rejected`` with recorded errors, wall-clock
deadlines, bounded-queue backpressure (:class:`QueueFull`), bounded step
retry, watchdog-backed hang detection, and ``drain()`` / ``shutdown()`` /
``health()`` lifecycle — see docs/SERVING.md "Failure semantics".

Overload is a first-class regime: request priority classes with
deferral aging, preemption of lower-priority work under slot/block
pressure (cheap resume via the prefix cache, stream restart from token
0), and SLO-aware admission shedding (:class:`ShedReject` with
``retry_after_s``) — see docs/SERVING.md "Overload, priorities &
preemption".

Observability is per-request, not just aggregate: a no-op-by-default
:class:`RequestTracer` records every request's span/event chain
(submitted → queued → admitted → batched decode steps → retired, with
linked preempt/resume, shed, and redispatch spans), the always-on
bounded :class:`FlightRecorder` freezes the last N step summaries when
an engine turns unhealthy or is ejected, and ``paddle_tpu.obs`` exports
Perfetto/Chrome trace JSON, JSONL event logs, and a Prometheus-style
text exposition — see docs/SERVING.md "Tracing & flight recorder".

The deployment also degrades per-process, never per-deployment: an
append-only CRC-per-record :class:`RequestJournal` makes every accepted
request durable (segment rotation, terminal-prefix compaction,
configurable fsync), ``Engine.recover`` / ``Fleet.recover`` rehydrate
non-terminal work after a crash — stream restart from token 0,
``recovered``-marked, bitwise-identical greedy/seeded replays via the
journaled effective seed, terminals exactly once across the crash —
and ``Fleet.update_weights`` rolls new weights through a live fleet
one drained replica at a time (in-place buffer write-through: zero new
compile keys; prefix-cache version epoch: zero stale-weight KV hits) —
see docs/SERVING.md "Durability & hot swap".

Multi-tenancy shares one compiled engine across tenants: per-request
LoRA adapter lanes (:class:`AdapterPool` — stacked low-rank banks
gathered per slot inside the SAME prefill/decode/verify programs, lane
ids as data so one executable serves every tenant; load/unload/hot-swap
at runtime with version epochs salting the prefix cache), per-request
constrained decoding (:class:`GrammarTable` /
:class:`JsonArrayGrammar` — a precompiled DFA mask table indexed by a
per-slot state lane advanced in-graph, composing with every sampling
law and with speculative verify), and per-tenant SLO accounting
(tenant-labelled TTFT/throughput in :class:`ServingMetrics`, tenant
tags in the tracer, adapter/grammar journaled per admission for
bitwise crash replay) — see docs/SERVING.md "Multi-tenant serving".

One level up, the fleet degrades per-replica, never per-fleet:
:class:`Fleet` supervises N engine replicas behind one
submit/stream/cancel surface — prefix-affinity dispatch, health-driven
ejection, bounded request re-dispatch (replay-from-prompt with an
exactly-once terminal contract), and replica rebuild — see
docs/SERVING.md "Fleet".

See ``docs/SERVING.md`` for the architecture and an end-to-end example.
"""
from .kv_cache import KVCache, CacheContext  # noqa: F401
from .paging import (  # noqa: F401
    AllocatorError, BlockAllocator, PagedCacheContext, PagedKVCache,
)
from .prefix_cache import PrefixCache  # noqa: F401
from .sampling import (  # noqa: F401
    DeviceSampler, SamplingParams, device_sample, sample,
)
from .sanitize import SyncSanitizer  # noqa: F401
from .tracing import (  # noqa: F401
    FlightRecorder, NULL_TRACER, NullTracer, RequestTracer,
    validate_trace,
)
from .metrics import ServingMetrics, FleetMetrics  # noqa: F401
from .journal import RequestJournal, JournalCorrupt  # noqa: F401
from .engine import (  # noqa: F401
    Engine, Request, QueueFull, ShedReject, EngineStopped,
    PRIORITY_LOW, PRIORITY_NORMAL, PRIORITY_HIGH,
)
from .spec_decode import SpecConfig, SpecState  # noqa: F401
from .adapters import (  # noqa: F401
    AdapterConfig, AdapterPool, make_lora_weights,
)
from .grammar import GrammarTable, JsonArrayGrammar  # noqa: F401
from .sharding import (  # noqa: F401
    ServingShard, mesh_shape_key, serving_mesh,
)
from .router import Fleet, FleetRequest  # noqa: F401

__all__ = ["KVCache", "CacheContext", "Engine", "Request",
           "SamplingParams", "ServingMetrics", "sample",
           "DeviceSampler", "device_sample",
           "QueueFull", "ShedReject", "EngineStopped",
           "PRIORITY_LOW", "PRIORITY_NORMAL", "PRIORITY_HIGH",
           "BlockAllocator", "PagedKVCache", "PagedCacheContext",
           "PrefixCache", "AllocatorError",
           "Fleet", "FleetRequest", "FleetMetrics", "SyncSanitizer",
           "RequestTracer", "NullTracer", "NULL_TRACER",
           "FlightRecorder", "validate_trace",
           "RequestJournal", "JournalCorrupt",
           "SpecConfig", "SpecState",
           "AdapterConfig", "AdapterPool", "make_lora_weights",
           "GrammarTable", "JsonArrayGrammar",
           "ServingShard", "serving_mesh", "mesh_shape_key"]
