"""paddle_tpu.serving — the LLM serving engine.

A slot-based continuous-batching serving stack for the flagship causal-LM
families (``models.GPTForCausalLM`` / ``models.LlamaForCausalLM``):

- :class:`KVCache` — preallocated ``[slots, layers, max_seq, kv_heads,
  head_dim]`` key/value storage with per-slot length tracking;
- :class:`Engine` — request queue + slot scheduler, bucketed prefill with a
  compiled-executable cache (zero steady-state recompiles), greedy /
  temperature sampling, per-token streaming callbacks;
- :class:`ServingMetrics` — TTFT / inter-token latency / tokens-per-sec /
  queue depth / slot occupancy / compile-cache counters, exported as a
  ``/stats``-style dict and via ``paddle_tpu.profiler.serving_stats()``.

See ``docs/SERVING.md`` for the architecture and an end-to-end example.
"""
from .kv_cache import KVCache, CacheContext  # noqa: F401
from .sampling import SamplingParams, sample  # noqa: F401
from .metrics import ServingMetrics  # noqa: F401
from .engine import Engine, Request  # noqa: F401

__all__ = ["KVCache", "CacheContext", "Engine", "Request",
           "SamplingParams", "ServingMetrics", "sample"]
