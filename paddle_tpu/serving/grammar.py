"""Constrained decoding: precompiled token-class mask tables driven by a
per-slot automaton state lane advanced in-graph.

The serving contract (docs/SERVING.md "Multi-tenant serving") is the same
one every other per-request knob obeys: **which grammar a slot decodes
under is data, never a trace constant**.  A :class:`GrammarTable` stacks
every registered grammar's DFA into two device-resident tables —

- ``trans [G, S_max, V] int32`` — next automaton state per
  (grammar, state, token),
- ``mask  [G, S_max, V] bool``  — token legality per (grammar, state),

row 0 reserved for the **unconstrained** grammar (mask all-True, trans
all-0), so unconstrained slots ride the exact same gathers.  The sampler
carries two extra ``[slots] int32`` lanes (grammar id + automaton state,
lifted into the compiled steps like the temperature lane) and applies
``where(mask[g, s], logits, -1e30)`` before sampling; ``-1e30``
underflows to exactly 0 probability under the f32 softmax AND loses every
``argmax``/Gumbel comparison, so illegal tokens are unreachable under
greedy and seeded sampling alike.  For grammar 0 the all-True mask row
makes the ``where`` a bitwise identity — an engine built with a grammar
table serves unconstrained requests bitwise identically to one without.

State advance is one gather (``trans[g, s, tok]``) executed inside the
compiled step right after sampling — no host round-trip, no shape change,
zero new executable-cache keys (the lanes are lifted state, not
arguments).

Grammars are *finite* by design: the first (and currently only) grammar
is :class:`JsonArrayGrammar`, a bounded-counter DFA over single-character
tokens (token id == character code, matching the tiny configs'
``vocab_size=128`` byte-level tokenizer) that accepts exactly the JSON
arrays of at most ``max_elems`` non-negative integers of at most
``max_digits`` digits (no leading zeros).  Bounding the counters keeps
the automaton total: every non-terminal state has a legal continuation
and the longest accepted string is ``1 + max_elems * (max_digits + 1)``
characters, so any decode budget past that is guaranteed to terminate in
the accepting state (where only EOS is legal).

Deliberately NOT supported (see docs/SERVING.md): CFGs / recursive
grammars (the state lane is a *finite* automaton — JSON objects of
unbounded nesting need a pushdown store), multi-character tokenizers
(masks are per-token-id; a BPE vocab needs token→charset compilation),
and per-step host re-masking (everything lives in-graph).
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["JsonArrayGrammar", "GrammarTable"]

_NEG_INF = np.float32(-1e30)


class JsonArrayGrammar:
    """Bounded JSON-array-of-integers DFA over character-level tokens.

    Accepts exactly ``[]`` and ``[n, n, ...]`` with 1..``max_elems``
    elements, each a non-negative integer of 1..``max_digits`` digits
    with no leading zeros (``"01"`` is not JSON), then requires EOS.
    Token id == ASCII code (``[`` = 91, ``]`` = 93, ``,`` = 44,
    ``0``-``9`` = 48..57), matching the byte-level tiny vocabs.

    States (``n_states = 2 + max_elems * (max_digits + 1)``):

    - 0 ``START``: only ``[`` is legal.
    - 1 ``DONE`` (accepting): only ``eos_token_id`` is legal
      (self-loop, so an engine that checks EOS one step late still
      sits in a legal state).
    - ``ELEM_OPEN(e)``: about to read element ``e``'s first digit;
      ``]`` is also legal iff ``e == 0`` (empty array — and ONLY
      there: no trailing commas).
    - ``IN_NUM(e, d)``: read ``d`` digits of element ``e``; more
      digits while ``d < max_digits``, ``,`` while another element
      fits, ``]`` always.  ``0`` as a *first* digit jumps straight to
      ``IN_NUM(e, max_digits)`` — the no-leading-zero rule.
    """

    def __init__(self, eos_token_id: int, *, max_elems: int = 3,
                 max_digits: int = 2):
        if max_elems < 1 or max_digits < 1:
            raise ValueError("JsonArrayGrammar needs max_elems >= 1 and "
                             "max_digits >= 1")
        self.eos_token_id = int(eos_token_id)
        self.max_elems = int(max_elems)
        self.max_digits = int(max_digits)
        self.n_states = 2 + self.max_elems * (self.max_digits + 1)
        #: longest accepted token stream incl. EOS — a decode budget of
        #: at least this many tokens can always reach DONE
        self.max_tokens = 2 + self.max_elems * (self.max_digits + 1)

    # state-id helpers (host-side; the tables are precomputed)
    _START, _DONE = 0, 1

    def _elem_open(self, e: int) -> int:
        return 2 + e * (self.max_digits + 1)

    def _in_num(self, e: int, d: int) -> int:
        return 2 + e * (self.max_digits + 1) + d

    def build(self, vocab_size: int):
        """Materialize ``(trans [S, V] int32, mask [S, V] bool)``."""
        V = int(vocab_size)
        need = max(93, self.eos_token_id)        # ']' is the largest char
        if V <= need:
            raise ValueError(
                f"JsonArrayGrammar needs vocab_size > {need} (character-"
                f"level token ids + eos {self.eos_token_id}), got {V}")
        LBRACK, RBRACK, COMMA = 91, 93, 44
        digits = list(range(48, 58))
        S = self.n_states
        trans = np.zeros((S, V), dtype=np.int32)
        mask = np.zeros((S, V), dtype=bool)

        def edge(s: int, tok: int, nxt: int) -> None:
            mask[s, tok] = True
            trans[s, tok] = nxt

        edge(self._START, LBRACK, self._elem_open(0))
        edge(self._DONE, self.eos_token_id, self._DONE)
        for e in range(self.max_elems):
            opn = self._elem_open(e)
            if e == 0:
                edge(opn, RBRACK, self._DONE)
            # first digit: '1'-'9' start a number; '0' IS the number
            # (no leading zeros) — jump to the digits-exhausted state
            edge(opn, digits[0], self._in_num(e, self.max_digits))
            for dg in digits[1:]:
                edge(opn, dg, self._in_num(e, 1))
            for d in range(1, self.max_digits + 1):
                s = self._in_num(e, d)
                edge(s, RBRACK, self._DONE)
                if e + 1 < self.max_elems:
                    edge(s, COMMA, self._elem_open(e + 1))
                if d < self.max_digits:
                    for dg in digits:
                        edge(s, dg, self._in_num(e, d + 1))
        return trans, mask

    def accepts(self, token_ids: Sequence[int], vocab_size: int) -> bool:
        """Host-side oracle: walk the DFA over ``token_ids`` (EOS
        included if emitted) and report whether every step was legal and
        the walk ends accepting (DONE, or one legal EOS after DONE)."""
        trans, mask = self.build(vocab_size)
        s = self._START
        for t in token_ids:
            t = int(t)
            if t >= vocab_size or not mask[s, t]:
                return False
            s = int(trans[s, t])
        return s == self._DONE


class GrammarTable:
    """Stacked DFA tables for every registered grammar, as device lanes.

    ``specs`` maps grammar *name* (the string requests carry in
    ``SamplingParams.grammar``) to a grammar spec (currently
    :class:`JsonArrayGrammar`).  Grammar ids are assigned 1..G in sorted
    name order; id 0 is the reserved unconstrained grammar.  The stacked
    ``trans``/``mask`` tensors are persistable — lifted into the compiled
    steps as state, exactly like the sampler's parameter lanes — so
    adding a grammar table changes ZERO executable-cache keys.

    States past a grammar's ``n_states`` pad out with the unconstrained
    row (all-True mask, trans 0); they are unreachable by construction
    but must not produce an all-``-inf`` logits row if ever indexed.
    """

    def __init__(self, vocab_size: int, specs: Dict[str, object]):
        if not specs:
            raise ValueError("GrammarTable needs at least one grammar "
                             "spec (or pass grammars=None to the engine)")
        self.vocab_size = int(vocab_size)
        self.names = {name: gid for gid, name
                      in enumerate(sorted(specs), start=1)}
        self.specs = dict(specs)
        self.max_states = max(int(s.n_states) for s in specs.values())
        G = len(specs) + 1
        trans = np.zeros((G, self.max_states, self.vocab_size),
                         dtype=np.int32)
        mask = np.ones((G, self.max_states, self.vocab_size), dtype=bool)
        for name, gid in self.names.items():
            t, m = specs[name].build(self.vocab_size)
            trans[gid, :t.shape[0]] = t
            mask[gid, :m.shape[0]] = m
        self.trans = Tensor._wrap(jnp.asarray(trans))
        self.mask = Tensor._wrap(jnp.asarray(mask))
        for t in (self.trans, self.mask):
            t.persistable = True

    # -- host side ---------------------------------------------------------

    def gid_of(self, name: Optional[str]) -> int:
        """Grammar id for a request: 0 (unconstrained) for None."""
        if name is None:
            return 0
        try:
            return self.names[name]
        except KeyError:
            raise KeyError(
                f"unknown grammar {name!r}; registered: "
                f"{sorted(self.names)}") from None

    def spec_of(self, name: str):
        self.gid_of(name)                 # the KeyError with the listing
        return self.specs[name]

    # -- traced (inside the compiled steps) --------------------------------

    def mask_rows(self, logits, gids, states):
        """``where(mask[g, s], logits, -1e30)`` — broadcasts over any
        leading shape pairing (``[V]`` row with scalar g/s, ``[S, V]``
        batch with ``[S]`` lanes).  Grammar 0 rows are bitwise identity
        (the select copies the original logits values through)."""
        legal = self.mask._value()[gids, states]
        return jnp.where(legal, logits, _NEG_INF)

    def advance(self, gids, states, tokens):
        """Next automaton state per row: ``trans[g, s, tok]`` (one
        gather, in-graph)."""
        return self.trans._value()[gids, states,
                                   jnp.asarray(tokens, dtype=jnp.int32)]
