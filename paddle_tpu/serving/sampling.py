"""Token sampling for the serving engine: on-device hot path + host oracle.

The hot path samples **inside the compiled decode/prefill step**
(:func:`device_sample`, state in :class:`DeviceSampler`): per-slot
temperature / top-k / top-p / greedy ride as ``[slots]`` arrays, per-slot
``jax.random`` key state is lifted into the program exactly like KV cache
state, and the step returns sampled token ids ``[slots] int32`` that feed
the next step's inputs device-side — no per-token logits pull, which is
what drives the sanitizer's ``serving_decode_host_transfers`` baseline
from 1.0 to 0.0 (ROADMAP item 2).

Speculative decoding rides the same lanes:
:meth:`DeviceSampler.accept_speculative` performs a whole round's
rejection-sampling acceptance in-graph (greedy: accept iff draft ==
target argmax, emit the argmax on rejection — bitwise-equal to plain
decoding; sampling: accept with ``min(1, p_t/p_d)``, resample the
normalized residual — marginally the target law at every position),
advancing the key lanes once per round and syncing both the target and
draft token lanes to the new pending token.

:func:`sample` is retained as the **host reference implementation** — the
parity oracle the on-device path is tested against (greedy must match
bitwise; seeded top-k/top-p statistically).  It is dtype-explicit:
all distribution math runs in float32, matching the compiled step's f32
logits, instead of the previous silent float64 upcast (which made the
"oracle" compute a different softmax than anything the system serves,
and pretended a precision jax only provides under ``jax_enable_x64``).
The final renormalization for ``rng.choice`` happens in float64 purely to
satisfy numpy's probability-sum check — by then the distribution is
already fixed in f32.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["SamplingParams", "sample", "device_sample", "DeviceSampler"]

_NEG_INF = np.float32(-1e30)


@dataclass
class SamplingParams:
    """Per-request decoding strategy.

    ``temperature == 0`` → greedy argmax.  ``top_k > 0`` restricts
    sampling to the k highest-probability tokens; ``top_p < 1`` restricts
    it to the smallest nucleus of tokens whose cumulative probability
    reaches ``top_p`` (applied after top-k, on the tempered distribution).

    Tenancy (docs/SERVING.md "Multi-tenant serving"): ``adapter`` names
    a LoRA adapter loaded in the engine's :class:`~.adapters.AdapterPool`
    (None = the base model); ``grammar`` names a registered constrained-
    decoding grammar in its :class:`~.grammar.GrammarTable` (None =
    unconstrained).  Both are *data* — per-slot lane values, never trace
    constants — and both are journaled in the admit record so crash
    recovery replays the same tenant bitwise.
    """

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: Optional[int] = None
    adapter: Optional[str] = None
    grammar: Optional[str] = None

    def __post_init__(self):
        if self.temperature < 0:
            raise ValueError("temperature must be >= 0")
        if self.top_k < 0:
            raise ValueError("top_k must be >= 0")
        if not (0.0 < self.top_p <= 1.0):
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        for f in ("adapter", "grammar"):
            v = getattr(self, f)
            if v is not None and not isinstance(v, str):
                raise ValueError(f"{f} must be a name (str) or None, "
                                 f"got {type(v).__name__}")


def _host_masked_logits(logits: np.ndarray,
                        params: SamplingParams) -> np.ndarray:
    """Tempered + top-k/top-p-masked logits, float32 throughout — the
    same restriction order as :func:`device_sample`."""
    z = logits / np.float32(params.temperature)
    if params.top_k:
        k = min(params.top_k, z.shape[0])
        kth = np.partition(z, -k)[-k]
        z = np.where(z >= kth, z, _NEG_INF)
    if params.top_p < 1.0:
        zmax = z.max()
        p = np.exp(z - zmax, dtype=np.float32)
        p /= p.sum(dtype=np.float32)
        order = np.argsort(-p, kind="stable")
        csum = np.cumsum(p[order], dtype=np.float32)
        # keep tokens while the cumulative mass BEFORE them is < top_p
        # (always keeps at least the most probable token)
        keep = (csum - p[order]) < np.float32(params.top_p)
        threshold = p[order][keep.sum() - 1]
        z = np.where(p >= threshold, z, _NEG_INF)
    return z


def sample(logits: np.ndarray, params: SamplingParams,
           rng: Optional[np.random.RandomState] = None) -> int:
    """Pick the next token id from a ``[vocab]`` logits row — the host
    reference (parity oracle) for the on-device sampler; float32 math."""
    logits = np.asarray(logits, dtype=np.float32).reshape(-1)
    if params.temperature == 0.0:
        return int(np.argmax(logits))
    z = _host_masked_logits(logits, params)
    z = z - z.max()
    p = np.exp(z, dtype=np.float32)
    p = p.astype(np.float64)          # np.choice's sum-to-1 check only
    p /= p.sum()
    rng = rng or np.random
    return int(rng.choice(p.shape[0], p=p))


def _device_masked_logits(logits, temps, top_ks, top_ps):
    """Tempered + top-k/top-p-masked logits ``[N, V]`` — the traced
    mirror of :func:`_host_masked_logits`, vectorized per row.

    One full-vocab sort total: the top-p pass reuses the descending
    ``z_desc`` (softmax is order-preserving and the top-k rule
    ``z >= kth`` masks the same entries in sorted order).  Rows with
    ``top_p >= 1`` skip the nucleus mask entirely — f32 ``cumsum``
    saturates at 1.0 under a peaked distribution, which would otherwise
    silently truncate the tail the host oracle keeps."""
    V = logits.shape[-1]
    z = logits / temps[:, None]
    k = jnp.where(top_ks > 0, jnp.clip(top_ks, 1, V), V)
    z_desc = jnp.sort(z, axis=-1)[:, ::-1]
    kth = jnp.take_along_axis(z_desc, (k - 1)[:, None], axis=1)
    z = jnp.where(z >= kth, z, _NEG_INF)
    # nucleus membership is computed over the sorted masked z, and the
    # cut is carried back as a *z-space* threshold — exact (the same
    # float values, softmax being order-preserving), where a p-space
    # compare against a separately-computed softmax can miss by 1 ulp
    z_desc = jnp.where(z_desc >= kth, z_desc, _NEG_INF)
    p_desc = jax.nn.softmax(z_desc, axis=-1)
    csum = jnp.cumsum(p_desc, axis=-1)
    keep_n = jnp.sum((csum - p_desc) < top_ps[:, None], axis=-1)
    z_thr = jnp.take_along_axis(z_desc, (keep_n - 1)[:, None], axis=1)
    return jnp.where((z >= z_thr) | (top_ps[:, None] >= 1.0),
                     z, _NEG_INF)


def device_sample(logits, temps, top_ks, top_ps, keys
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Sample one token per row, entirely on device (traced inside the
    compiled decode/prefill step).

    Args:
        logits: ``[N, V]`` float32 final-token logits.
        temps:  ``[N]`` float32 temperatures (``<= 0`` → greedy argmax
                of the raw logits, bitwise equal to the host oracle).
        top_ks: ``[N]`` int32 (``<= 0`` → unrestricted).
        top_ps: ``[N]`` float32 nucleus mass (``>= 1`` → unrestricted).
        keys:   ``[N, 2]`` uint32 per-row jax.random key state.

    Returns:
        ``(tokens [N] int32, new_keys [N, 2] uint32)`` — keys advance
        once per call, so a re-seeded slot replays the same stream
        (the preempt/resume determinism contract).
    """
    logits = logits.astype(jnp.float32)
    greedy = temps <= 0.0
    z = _device_masked_logits(logits, jnp.where(greedy, 1.0, temps),
                              top_ks, top_ps)
    split = jax.vmap(jax.random.split)(keys)         # [N, 2, 2]
    new_keys, subkeys = split[:, 0], split[:, 1]
    drawn = jax.vmap(jax.random.categorical)(subkeys, z)
    tokens = jnp.where(greedy, jnp.argmax(logits, axis=-1),
                       drawn).astype(jnp.int32)
    return tokens, new_keys


class DeviceSampler:
    """Per-slot sampling state threaded through the compiled steps.

    Device state (lifted into programs like KV cache payloads): per-slot
    ``jax.random`` keys, temperature/top-k/top-p parameter lanes, and the
    last sampled token per slot (``tokens`` — the next decode step's
    input ids, read device-side so no host round-trip feeds the loop).
    Host side, the engine **stages** a slot at admission
    (:meth:`stage_slot`): parameters are written into the lanes and the
    key lane is re-seeded from the request's seed — identically on first
    admission and on preempt-resume, which is what makes seeded replay
    bitwise deterministic (the old per-request ``RandomState`` contract,
    re-threaded through device key state).

    Constrained decoding (``grammar`` — a :class:`~.grammar.GrammarTable`
    or None): two more ``[slots] int32`` lanes carry each slot's grammar
    id and automaton state (the state *before* the next token).  Logits
    are grammar-masked BEFORE :func:`device_sample`, so the greedy branch
    argmaxes the masked row and seeded sampling draws from the masked
    law; the state lane advances in-graph right after sampling.  Grammar
    id 0 (unconstrained) masks nothing bitwise, so a sampler built with
    a table serves unconstrained slots identically to one without.
    """

    def __init__(self, num_slots: int, grammar=None):
        self.num_slots = int(num_slots)
        self.grammar = grammar
        self.keys = Tensor._wrap(
            jnp.zeros((self.num_slots, 2), dtype=jnp.uint32))
        self.temps = Tensor._wrap(
            jnp.zeros((self.num_slots,), dtype=jnp.float32))
        self.top_ks = Tensor._wrap(
            jnp.zeros((self.num_slots,), dtype=jnp.int32))
        self.top_ps = Tensor._wrap(
            jnp.ones((self.num_slots,), dtype=jnp.float32))
        self.tokens = Tensor._wrap(
            jnp.zeros((self.num_slots,), dtype=jnp.int32))
        self.grammar_ids = Tensor._wrap(
            jnp.zeros((self.num_slots,), dtype=jnp.int32))
        self.grammar_states = Tensor._wrap(
            jnp.zeros((self.num_slots,), dtype=jnp.int32))
        for t in (self.keys, self.temps, self.top_ks, self.top_ps,
                  self.tokens, self.grammar_ids, self.grammar_states):
            t.persistable = True

    # -- host-side staging (between steps; value-only, never a shape) ------

    def stage_slot(self, slot: int, params: SamplingParams,
                   seed: int) -> None:
        """Write one slot's sampling parameters and re-seed its key lane
        (admission and preempt-resume both land here, so replay streams
        are reconstructible by construction)."""
        self.keys._set_data(self.keys._value().at[slot].set(
            jax.random.PRNGKey(int(seed)).astype(jnp.uint32)))
        self.temps._set_data(self.temps._value().at[slot].set(
            jnp.float32(params.temperature)))
        self.top_ks._set_data(self.top_ks._value().at[slot].set(
            jnp.int32(params.top_k)))
        self.top_ps._set_data(self.top_ps._value().at[slot].set(
            jnp.float32(params.top_p)))
        if self.grammar is not None:
            # grammar id + automaton start state: re-staged identically
            # on preempt-resume/recovery, so a replayed request walks
            # the same automaton path bitwise
            gid = self.grammar.gid_of(params.grammar)
            self.grammar_ids._set_data(
                self.grammar_ids._value().at[slot].set(jnp.int32(gid)))
            self.grammar_states._set_data(
                self.grammar_states._value().at[slot].set(jnp.int32(0)))

    def reset(self) -> None:
        """Forget all slots (warmup scribbles over slot 0)."""
        self.keys._set_data(
            jnp.zeros((self.num_slots, 2), dtype=jnp.uint32))
        self.temps._set_data(
            jnp.zeros((self.num_slots,), dtype=jnp.float32))
        self.top_ks._set_data(
            jnp.zeros((self.num_slots,), dtype=jnp.int32))
        self.top_ps._set_data(
            jnp.ones((self.num_slots,), dtype=jnp.float32))
        self.tokens._set_data(
            jnp.zeros((self.num_slots,), dtype=jnp.int32))
        self.grammar_ids._set_data(
            jnp.zeros((self.num_slots,), dtype=jnp.int32))
        self.grammar_states._set_data(
            jnp.zeros((self.num_slots,), dtype=jnp.int32))

    # -- traced sampling (inside the compiled steps) -----------------------

    def sample_slot(self, slot, logits_row):
        """Prefill-side: sample ONE slot's first token from its ``[V]``
        last-position logits.  ``slot`` may be traced; key and token
        lanes update through scatter writes, so one compiled prefill
        serves every slot."""
        s = jnp.asarray(slot, dtype=jnp.int32).reshape(())
        keys = self.keys._value()
        row = jnp.stack([
            jax.lax.dynamic_index_in_dim(t._value(), s, 0, keepdims=False)
            for t in (self.temps, self.top_ps)])
        top_k = jax.lax.dynamic_index_in_dim(
            self.top_ks._value(), s, 0, keepdims=False)
        key = jax.lax.dynamic_index_in_dim(keys, s, 0, keepdims=False)
        logits_row = logits_row.astype(jnp.float32)
        if self.grammar is not None:
            # grammar-mask BEFORE sampling (the greedy branch argmaxes
            # its input, so masking here constrains greedy too); id 0
            # rows select the original values through, bitwise
            gid = jax.lax.dynamic_index_in_dim(
                self.grammar_ids._value(), s, 0, keepdims=False)
            gst = jax.lax.dynamic_index_in_dim(
                self.grammar_states._value(), s, 0, keepdims=False)
            logits_row = self.grammar.mask_rows(logits_row, gid, gst)
        tok, new_key = device_sample(
            logits_row[None], row[0][None],
            top_k[None], row[1][None], key[None])
        self.keys._set_data(keys.at[s].set(new_key[0]))
        self.tokens._set_data(
            self.tokens._value().at[s].set(tok[0]))
        if self.grammar is not None:
            self.grammar_states._set_data(
                self.grammar_states._value().at[s].set(
                    self.grammar.advance(gid, gst, tok[0])))
        return tok[0]

    def sample_all(self, logits):
        """Decode-side: sample every slot from ``[slots, V]`` logits;
        advances every key lane and rewrites the token lane (idle slots
        sample garbage that is never delivered — their lanes re-seed at
        the next admission)."""
        logits = logits.astype(jnp.float32)
        if self.grammar is not None:
            gids = self.grammar_ids._value()
            gsts = self.grammar_states._value()
            logits = self.grammar.mask_rows(logits, gids, gsts)
        toks, new_keys = device_sample(
            logits, self.temps._value(),
            self.top_ks._value(), self.top_ps._value(),
            self.keys._value())
        self.keys._set_data(new_keys)
        self.tokens._set_data(toks)
        if self.grammar is not None:
            self.grammar_states._set_data(
                self.grammar.advance(gids, gsts, toks))
        return toks

    def _masked_probs(self, logits):
        """Per-slot-masked sampling distributions for a ``[S, W, V]``
        verify window: each slot's temperature/top-k/top-p lanes applied
        to every window position (softmax of the masked, tempered
        logits — exactly the distribution :func:`device_sample` draws
        from, so acceptance ratios price the real proposal/target
        laws).  Grammar masking happens upstream, on the logits both
        models' windows share — see :meth:`accept_speculative`."""
        S, W, V = logits.shape
        temps = jnp.repeat(jnp.where(self.temps._value() <= 0.0, 1.0,
                                     self.temps._value()), W)
        z = _device_masked_logits(
            logits.reshape(S * W, V).astype(jnp.float32), temps,
            jnp.repeat(self.top_ks._value(), W),
            jnp.repeat(self.top_ps._value(), W))
        return jax.nn.softmax(z, axis=-1).reshape(S, W, V)

    def accept_speculative(self, target_logits, draft_logits,
                           draft_tokens, cap, draft_sampler
                           ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Rejection-sampling acceptance of one speculative round,
        entirely in-graph (traced inside the compiled verify step — the
        zero-host-transfer decode invariant extends to speculation).

        Args:
            target_logits: ``[S, W, V]`` target-model logits over the
                verify window (position ``i`` scores the token AFTER
                input ``i``; W = k + 1).
            draft_logits:  ``[S, W, V]`` draft-model logits over the
                same window (recomputed in the verify step, so the
                acceptance ratio uses exactly the law the proposals
                were drawn from — and the draft KV for the window is
                complete even on full acceptance).
            draft_tokens:  ``[S, k]`` the round's draft proposals.
            cap:           ``[S]`` int32 — per-slot emission cap
                (token budget / cache capacity, computed host-side);
                the emission stream is truncated to it, which is
                distribution-preserving (every emitted position is
                marginally the target law).
            draft_sampler: the draft model's :class:`DeviceSampler`
                (its param lanes define the proposal distribution; its
                token lane is synced to the new pending token so the
                next round's first draft step feeds device-side).

        Returns:
            ``(emitted [S, W] int32, m [S] int32)`` — ``emitted[:m]``
            is the round's delivered stream (accepted draft prefix plus
            one bonus/resample token, ``1 <= m <= min(W, cap)``);
            entries past ``m`` are junk the host never reads.

        Greedy slots (temperature 0) accept a draft token iff it equals
        the target argmax and emit the target argmax on rejection — so
        every emitted token IS the target argmax and greedy speculative
        output is bitwise identical to non-speculative decoding.
        Sampling slots follow standard speculative rejection sampling
        (accept with ``min(1, p_t/p_d)``, resample the normalized
        residual ``max(p_t - p_d, 0)`` on rejection, plain target draw
        for the bonus position) — marginally the target distribution at
        every position.  Key lanes advance once per round; re-seeding a
        slot replays the identical round stream (the preempt-resume /
        crash-recovery determinism contract)."""
        S, W, V = target_logits.shape
        k = W - 1
        greedy = self.temps._value() <= 0.0                   # [S]
        target_logits = target_logits.astype(jnp.float32)
        draft_logits = draft_logits.astype(jnp.float32)
        if self.grammar is not None:
            # Grammar masks apply IDENTICALLY to the draft and target
            # laws at every window position — both renormalize on the
            # same legal support, so the acceptance proof (min(1,
            # pt/pd) accept + max(pt-pd, 0) residual, whose support is
            # a subset of pt's) is preserved verbatim.  Window state j
            # is the round-start lane state folded through the draft
            # proposals — exactly the states the draft sampler held
            # when it drew proposal j, so pd prices the law the
            # proposals actually came from.
            gids = self.grammar_ids._value()
            g_start = self.grammar_states._value()
            st = g_start
            wmask = []
            for j in range(W):
                wmask.append(self.grammar.mask._value()[gids, st])
                if j < k:
                    st = self.grammar.advance(gids, st,
                                              draft_tokens[:, j])
            gmask = jnp.stack(wmask, axis=1)              # [S, W, V]
            target_logits = jnp.where(gmask, target_logits, _NEG_INF)
            draft_logits = jnp.where(gmask, draft_logits, _NEG_INF)
        pt = self._masked_probs(target_logits)                # [S, W, V]
        pd = draft_sampler._masked_probs(draft_logits)        # [S, W, V]
        # position k carries no proposal: zero its draft mass so the
        # "residual" there is the plain target distribution (the bonus
        # draw) — one formula covers reject-resample AND bonus
        pd = pd.at[:, k, :].set(0.0)
        g = jnp.argmax(target_logits.astype(jnp.float32),
                       axis=-1).astype(jnp.int32)             # [S, W]
        # accept test per draft position
        pt_d = jnp.take_along_axis(
            pt[:, :k, :], draft_tokens[..., None], axis=2)[..., 0]
        pd_d = jnp.take_along_axis(
            pd[:, :k, :], draft_tokens[..., None], axis=2)[..., 0]
        keys = self.keys._value()
        split = jax.vmap(lambda kk: jax.random.split(kk, 2 + W))(keys)
        new_keys, ukeys, ckeys = split[:, 0], split[:, 1], split[:, 2:]
        u = jax.vmap(lambda kk: jax.random.uniform(kk, (k,)))(ukeys)
        ratio = pt_d / jnp.maximum(pd_d, jnp.float32(1e-30))
        accept = jnp.where(greedy[:, None],
                           draft_tokens == g[:, :k],
                           u < jnp.minimum(ratio, 1.0))       # [S, k]
        n_acc = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1),
                        axis=1)                               # [S]
        # replacement token per position: residual resample (sampling)
        # or target argmax (greedy); identical target/draft laws leave
        # an all-zero residual — fall back to the target law itself
        res = jnp.maximum(pt - pd, 0.0)
        res = jnp.where(
            (jnp.sum(res, axis=-1) <= 0.0)[..., None], pt, res)
        rep = jax.vmap(jax.vmap(jax.random.categorical))(
            ckeys, jnp.log(res)).astype(jnp.int32)            # [S, W]
        rep = jnp.where(greedy[:, None], g, rep)
        # emission stream: accepted draft prefix, then the replacement
        d_pad = jnp.concatenate(
            [draft_tokens.astype(jnp.int32),
             jnp.zeros((S, 1), dtype=jnp.int32)], axis=1)
        idx = jnp.arange(W, dtype=jnp.int32)[None, :]
        emitted = jnp.where(idx < n_acc[:, None], d_pad, rep)
        m = jnp.clip(n_acc.astype(jnp.int32) + 1, 1,
                     jnp.maximum(cap.astype(jnp.int32), 1))
        pend = jnp.take_along_axis(emitted, (m - 1)[:, None],
                                   axis=1)[:, 0]
        self.keys._set_data(new_keys)
        self.tokens._set_data(pend)
        # the draft chains off the same pending token next round
        draft_sampler.tokens._set_data(pend)
        if self.grammar is not None:
            # fold the automaton over the round's ACTUAL emissions
            # (accepted prefix + replacement, truncated to m) and sync
            # BOTH samplers' state lanes — next round's draft steps and
            # verify window start from the same state, in lockstep
            st = g_start
            for j in range(W):
                nxt = self.grammar.advance(gids, st, emitted[:, j])
                st = jnp.where(j < m, nxt, st)
            self.grammar_states._set_data(st)
            draft_sampler.grammar_states._set_data(st)
        return emitted, m
