"""Token sampling for the serving engine.

Sampling runs host-side on the final-token logits (which cross to the host
anyway for streaming callbacks and stop conditions), keeping the compiled
decode step deterministic and RNG-state-free — one executable serves greedy
and every temperature at once.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

__all__ = ["SamplingParams", "sample"]


@dataclass
class SamplingParams:
    """Per-request decoding strategy.

    ``temperature == 0`` → greedy argmax.  ``top_k > 0`` restricts sampling
    to the k highest-probability tokens.
    """

    temperature: float = 0.0
    top_k: int = 0
    seed: Optional[int] = None

    def __post_init__(self):
        if self.temperature < 0:
            raise ValueError("temperature must be >= 0")
        if self.top_k < 0:
            raise ValueError("top_k must be >= 0")


def sample(logits: np.ndarray, params: SamplingParams,
           rng: Optional[np.random.RandomState] = None) -> int:
    """Pick the next token id from a ``[vocab]`` logits row."""
    logits = np.asarray(logits, dtype=np.float64).reshape(-1)
    if params.temperature == 0.0:
        return int(np.argmax(logits))
    z = logits / params.temperature
    if params.top_k:
        k = min(params.top_k, z.shape[0])
        kth = np.partition(z, -k)[-k]
        z = np.where(z >= kth, z, -np.inf)
    z = z - z.max()
    p = np.exp(z)
    p /= p.sum()
    rng = rng or np.random
    return int(rng.choice(p.shape[0], p=p))
