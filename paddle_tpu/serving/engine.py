"""Continuous-batching serving engine: slot scheduler over a KV cache.

The TPU-idiomatic serving loop (XLA recompiles on every new shape, so the
engine is built so that NO shape ever depends on request content):

- **prefill**: each admitted request's prompt is padded to a power-of-two
  bucket and run through the model's causal forward once, writing K/V into
  the request's slot.  One executable per bucket; the slot index and true
  prompt length are *arguments*, so all slots share the executables.
- **decode**: every step runs ONE fixed-shape program over all slots
  (``[slots, 1]`` tokens + ``[slots]`` active mask), each active slot
  extending its sequence by one token via ``ops.cached_attention``.
  Admitting or retiring a request only changes argument *values* —
  steady-state serving triggers zero recompiles (asserted by tests via the
  executable cache's own hit/miss counters).

Requests are admitted into free slots as they arrive and retired the step
they finish (eos / token budget / cache capacity), in the spirit of
fine-grained compute/host-scheduling overlap (T3, arXiv:2401.16677) —
host-side sampling and scheduling happen while the next step's arguments
are assembled.
"""
from __future__ import annotations

import itertools
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, to_tensor
from .kv_cache import KVCache, CacheContext
from .metrics import ServingMetrics
from .sampling import SamplingParams, sample

__all__ = ["Engine", "Request", "SamplingParams"]

_engine_counter = itertools.count()


@dataclass(eq=False)           # a live handle: identity, not field equality
class Request:
    """One generation request moving through the engine."""

    prompt_ids: np.ndarray
    max_new_tokens: int = 16
    sampling: SamplingParams = field(default_factory=SamplingParams)
    eos_token_id: Optional[int] = None
    stream_cb: Optional[Callable[[int, "Request"], None]] = None
    request_id: int = -1

    # lifecycle (engine-managed)
    state: str = "queued"            # queued | running | finished
    slot: Optional[int] = None
    output_ids: List[int] = field(default_factory=list)
    prefill_bucket: int = 0
    t_enqueue: float = 0.0
    t_first_token: Optional[float] = None
    t_finish: Optional[float] = None
    _rng: Optional[np.random.RandomState] = None
    _seq_len: int = 0                # prompt + emitted tokens in the cache

    @property
    def finished(self) -> bool:
        return self.state == "finished"

    @property
    def ttft_s(self) -> Optional[float]:
        if self.t_first_token is None:
            return None
        return self.t_first_token - self.t_enqueue

    def _emit(self, token: int, now: float) -> None:
        if self.t_first_token is None:
            self.t_first_token = now
        self.output_ids.append(int(token))
        if self.stream_cb is not None:
            self.stream_cb(int(token), self)


class Engine:
    """Slot-based continuous-batching engine over a causal-LM model.

    Args:
        model: ``GPTForCausalLM`` / ``LlamaForCausalLM`` (any Layer whose
            forward accepts ``cache_ctx`` works).  Switched to eval mode.
        num_slots: fixed decode batch width.
        max_seq: per-slot cache capacity (prompt + generated); defaults to
            the model's ``max_position_embeddings``.
        min_bucket: smallest prefill bucket; buckets are powers of two up
            to ``max_seq``.
        cache_dtype: KV cache dtype (default: the model's param dtype).
    """

    def __init__(self, model, *, num_slots: int = 4,
                 max_seq: Optional[int] = None, min_bucket: int = 8,
                 cache_dtype=None, name: Optional[str] = None):
        cfg = getattr(model, "config", None)
        if cfg is None:
            raise TypeError("Engine needs a model carrying a .config "
                            "(GPTForCausalLM / LlamaForCausalLM)")
        self.model = model
        self.model.eval()
        self.config = cfg
        max_pos = getattr(cfg, "max_position_embeddings", None)
        if max_seq is None and max_pos is None:
            raise ValueError("max_seq is required: the model config has no "
                             "max_position_embeddings to default to")
        self.max_seq = int(max_seq or max_pos)
        if max_pos is not None and self.max_seq > max_pos:
            raise ValueError(
                f"max_seq {self.max_seq} exceeds the model's "
                f"max_position_embeddings {max_pos}")
        self.num_slots = int(num_slots)
        self.min_bucket = int(min_bucket)
        if self.min_bucket < 1:
            raise ValueError(f"min_bucket must be >= 1, got {min_bucket}")
        self.buckets = self._make_buckets()
        kv_heads = getattr(cfg, "n_kv_heads", None) or cfg.num_attention_heads
        if cache_dtype is None:
            params = model.parameters()
            cache_dtype = params[0].dtype if params else "float32"
        self.cache = KVCache(
            num_slots=self.num_slots, num_layers=cfg.num_hidden_layers,
            max_seq=self.max_seq, num_kv_heads=kv_heads,
            head_dim=cfg.head_dim, dtype=cache_dtype)
        self.name = name or f"engine-{next(_engine_counter)}"
        self.metrics = ServingMetrics(self.name, num_slots=self.num_slots)
        self.queue: deque = deque()
        self.running: Dict[int, Request] = {}
        self.free_slots: List[int] = list(range(self.num_slots))
        self._last_token = np.zeros((self.num_slots,), dtype=np.int64)
        self._req_counter = itertools.count()
        self._prefill_fn = None
        self._decode_fn = None

    # -- compiled steps ----------------------------------------------------

    def _make_buckets(self) -> List[int]:
        b, out = self.min_bucket, []
        while b < self.max_seq:
            out.append(b)
            b *= 2
        out.append(self.max_seq)
        return out

    def bucket_for(self, prompt_len: int) -> int:
        if prompt_len > self.max_seq:
            raise ValueError(f"prompt length {prompt_len} exceeds cache "
                             f"capacity max_seq={self.max_seq}")
        for b in self.buckets:
            if prompt_len <= b:
                return b
        return self.max_seq

    def _build_steps(self) -> None:
        """Compile-cached prefill/decode programs.  Built lazily so the
        engine can be constructed before any backend is touched."""
        from .. import jit as jit_mod

        model, cache = self.model, self.cache

        def prefill_step(input_ids, slot, length):
            ctx = CacheContext(cache, "prefill", slot=slot, length=length)
            logits = model(input_ids, cache_ctx=ctx)
            cache.set_length(slot, length)
            arr = logits._value()                       # [1, S, V]
            last = jax.lax.dynamic_index_in_dim(
                arr[0], length._value().astype(jnp.int32) - 1,
                axis=0, keepdims=False)
            return Tensor._wrap(last.astype(jnp.float32))

        def decode_step(tokens, active):
            ctx = CacheContext(cache, "decode", active=active)
            logits = model(tokens, cache_ctx=ctx)
            cache.advance(active)
            return Tensor._wrap(
                logits._value()[:, -1, :].astype(jnp.float32))

        self._prefill_fn = jit_mod.to_static(prefill_step)
        self._decode_fn = jit_mod.to_static(decode_step)

    def _call_counted(self, fn, *args):
        """Run a compiled step, feeding the executable cache's own state
        into the hit/miss counters (a new program in the cache == one XLA
        compile == one miss)."""
        from ..core.autograd import no_grad

        before = len(fn.program_cache)
        with no_grad():
            out = fn(*args)
        self.metrics.on_compile(miss=len(fn.program_cache) > before)
        return out

    # -- public API --------------------------------------------------------

    @classmethod
    def from_config(cls, config, **engine_kwargs) -> "Engine":
        """Predictor-compatible entry: build an Engine from a model config
        (``GPTConfig``/``LlamaConfig``), a registry name (``"gpt:tiny"``,
        ``"llama:llama2-7b"``), or a ready model Layer."""
        from ..nn.layer_base import Layer
        from ..models import (
            GPT_CONFIGS, GPTConfig, GPTForCausalLM,
            LLAMA_CONFIGS, LlamaConfig, LlamaForCausalLM,
        )

        if isinstance(config, Layer):
            return cls(config, **engine_kwargs)
        if isinstance(config, GPTConfig):
            return cls(GPTForCausalLM(config), **engine_kwargs)
        if isinstance(config, LlamaConfig):
            return cls(LlamaForCausalLM(config), **engine_kwargs)
        if isinstance(config, str):
            family, _, which = config.partition(":")
            reg = {"gpt": (GPT_CONFIGS, GPTForCausalLM),
                   "llama": (LLAMA_CONFIGS, LlamaForCausalLM)}.get(family)
            if reg is None or (which or "tiny") not in reg[0]:
                raise KeyError(
                    f"unknown model spec {config!r}; want "
                    f"'gpt:<{'|'.join(GPT_CONFIGS)}>' or "
                    f"'llama:<{'|'.join(LLAMA_CONFIGS)}>'")
            cfgs, cls_ = reg
            return cls(cls_(cfgs[which or "tiny"]()), **engine_kwargs)
        raise TypeError(
            f"Engine.from_config: unsupported config {type(config).__name__}"
            " — pass a GPTConfig/LlamaConfig, a 'family:size' name, or a "
            "model Layer.  (jit.save artifacts have no cache-aware forward;"
            " serve those through inference.Predictor instead.)")

    def add_request(self, prompt_ids: Sequence[int], *,
                    max_new_tokens: int = 16,
                    sampling: Optional[SamplingParams] = None,
                    temperature: Optional[float] = None,
                    eos_token_id: Optional[int] = None,
                    stream_cb: Optional[Callable] = None) -> Request:
        """Enqueue a prompt; it is admitted into a slot by a later
        ``step()``.  Returns the live Request handle."""
        prompt = np.asarray(list(prompt_ids), dtype=np.int64).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if prompt.size > self.max_seq:
            raise ValueError(f"prompt length {prompt.size} exceeds "
                             f"max_seq={self.max_seq}")
        if int(max_new_tokens) < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if sampling is None:
            sampling = SamplingParams(temperature=temperature or 0.0)
        req = Request(prompt_ids=prompt, max_new_tokens=int(max_new_tokens),
                      sampling=sampling, eos_token_id=eos_token_id,
                      stream_cb=stream_cb,
                      request_id=next(self._req_counter))
        req.t_enqueue = time.perf_counter()
        req._rng = np.random.RandomState(
            sampling.seed if sampling.seed is not None
            else (req.request_id + 1) * 7919)
        self.queue.append(req)
        self.metrics.on_enqueue(len(self.queue))
        return req

    def warmup(self, buckets: Optional[Sequence[int]] = None) -> dict:
        """Pre-compile the decode step and every prefill bucket with dummy
        traffic, then reset the cache — so live serving starts with a hot
        executable cache and zero steady-state misses."""
        if self.running or self.queue:
            raise RuntimeError("warmup() must run before serving traffic "
                               "(it scribbles over slot 0 and resets all "
                               "slot lengths)")
        if self._prefill_fn is None:
            self._build_steps()
        for b in (buckets or self.buckets):
            ids = np.zeros((1, int(b)), dtype=np.int64)
            self._call_counted(
                self._prefill_fn, to_tensor(ids),
                to_tensor(np.int32(0)), to_tensor(np.int32(1)))
        toks = np.zeros((self.num_slots, 1), dtype=np.int64)
        idle = np.zeros((self.num_slots,), dtype=np.int32)
        self._call_counted(self._decode_fn, to_tensor(toks), to_tensor(idle))
        self.cache.reset()
        return {"buckets": list(buckets or self.buckets),
                "compile_misses": self.metrics.compile_misses}

    # -- scheduling --------------------------------------------------------

    def _admit(self, req: Request, slot: int) -> None:
        L = int(req.prompt_ids.size)
        bucket = self.bucket_for(L)
        ids = np.zeros((1, bucket), dtype=np.int64)
        ids[0, :L] = req.prompt_ids
        t0 = time.perf_counter()
        last = self._call_counted(
            self._prefill_fn, to_tensor(ids),
            to_tensor(np.int32(slot)), to_tensor(np.int32(L)))
        logits = last.numpy()
        now = time.perf_counter()
        self.metrics.prefill_time_s += now - t0
        req.state, req.slot, req.prefill_bucket = "running", slot, bucket
        req._seq_len = L
        self.metrics.on_admit(bucket, L, len(self.queue))
        tok = sample(logits, req.sampling, req._rng)
        req._emit(tok, now)
        self.metrics.on_first_token(req.ttft_s)
        self.running[slot] = req
        self._last_token[slot] = tok
        if self._done_after_emit(req):
            self._retire(req)

    def _done_after_emit(self, req: Request) -> bool:
        if len(req.output_ids) >= req.max_new_tokens:
            return True
        if req.eos_token_id is not None and \
                req.output_ids[-1] == req.eos_token_id:
            return True
        # the NEXT decode would write at position _seq_len; the emitted
        # token itself still needs a cache line to attend from
        if req._seq_len + 1 > self.max_seq:
            return True
        return False

    def _retire(self, req: Request) -> None:
        slot = req.slot
        req.state = "finished"
        req.t_finish = time.perf_counter()
        self.running.pop(slot, None)
        self.free_slots.append(slot)
        self.metrics.on_complete()

    def _decode(self) -> None:
        toks = np.zeros((self.num_slots, 1), dtype=np.int64)
        active = np.zeros((self.num_slots,), dtype=np.int32)
        for slot in self.running:
            toks[slot, 0] = self._last_token[slot]
            active[slot] = 1
        t0 = time.perf_counter()
        out = self._call_counted(
            self._decode_fn, to_tensor(toks), to_tensor(active))
        logits = out.numpy()                     # [slots, V]
        now = time.perf_counter()
        self.metrics.on_decode_step(len(self.running), now - t0)
        for slot, req in list(self.running.items()):
            req._seq_len += 1                    # token written this step
            tok = sample(logits[slot], req.sampling, req._rng)
            req._emit(tok, now)
            self._last_token[slot] = tok
            if self._done_after_emit(req):
                self._retire(req)

    def step(self) -> bool:
        """One scheduler tick: admit queued requests into free slots, then
        run one decode step for all running slots.  Returns True while
        there is in-flight or queued work."""
        if self._prefill_fn is None:
            self._build_steps()
        while self.free_slots and self.queue:
            self._admit(self.queue.popleft(), self.free_slots.pop())
        self.metrics.on_slots(len(self.running))
        if self.running:
            self._decode()
        return bool(self.running or self.queue)

    def run(self, max_steps: Optional[int] = None) -> None:
        """Drive ``step()`` until idle (or ``max_steps``)."""
        n = 0
        while self.step():
            n += 1
            if max_steps is not None and n >= max_steps:
                break

    def generate(self, prompts: Sequence[Sequence[int]], *,
                 max_new_tokens: int = 16, **request_kwargs
                 ) -> List[List[int]]:
        """Synchronous convenience: serve a batch of prompts through the
        continuous-batching loop; returns generated ids per prompt."""
        reqs = [self.add_request(p, max_new_tokens=max_new_tokens,
                                 **request_kwargs) for p in prompts]
        self.run()
        return [r.output_ids for r in reqs]

    def stats(self) -> dict:
        """``/stats``-style snapshot (also exported through
        ``paddle_tpu.profiler.serving_stats()``)."""
        self.metrics._slots_busy = len(self.running)
        self.metrics.queue_depth = len(self.queue)
        return self.metrics.snapshot()
