"""Continuous-batching serving engine: slot scheduler over a KV cache.

The TPU-idiomatic serving loop (XLA recompiles on every new shape, so the
engine is built so that NO shape ever depends on request content):

- **prefill**: each admitted request's prompt is padded to a power-of-two
  bucket and run through the model's causal forward once, writing K/V into
  the request's slot.  One executable per bucket; the slot index and true
  prompt length are *arguments*, so all slots share the executables.
- **decode**: every step runs ONE fixed-shape program over all slots
  (``[slots, 1]`` tokens + ``[slots]`` active mask), each active slot
  extending its sequence by one token via ``ops.cached_attention``.
  Admitting or retiring a request only changes argument *values* —
  steady-state serving triggers zero recompiles (asserted by tests via the
  executable cache's own hit/miss counters).

Requests are admitted into free slots as they arrive and retired the step
they finish (eos / token budget / cache capacity), in the spirit of
fine-grained compute/host-scheduling overlap (T3, arXiv:2401.16677).

Decode hot path (docs/SERVING.md "Decode hot path"): a decode step is ONE
device dispatch with ZERO blocking host transfers.  Sampling runs inside
the compiled step (``serving.sampling.DeviceSampler``: per-slot
temperature/top-k/top-p lanes and ``jax.random`` key state lifted like KV
cache state), the sampled token ids feed the next step's inputs
device-side through the sampler's token lane, and in paged mode the
attention itself consumes the block table inside a Pallas flash-decoding
kernel (``kernel="pallas"``, the default; ``"reference"`` keeps the jnp
gather oracle).  The host touches only the tiny ``[slots] int32`` token
array — for stream delivery and stop checks, pulled AFTER the sanitizer's
blocking-transfer window closes — so the sanitizer's measured
``serving_decode_host_transfers`` is 0.0 (down from the 1.0 logits-pull
baseline PR 7 priced).

Resilience (docs/SERVING.md "Failure semantics"): the scheduler degrades
per-request, never per-engine.  Requests own terminal states
``finished | failed | cancelled | rejected`` plus an ``error`` record;
every exit path funnels through ``_retire`` so a slot (and its cache
length) can never leak.  A raising ``stream_cb`` or sampling failure fails
only its request; a failed compiled step retries once with backoff before
failing only the implicated requests.  Admission is bounded
(``max_queue`` + reject/block policy), deadlines are wall-clock and
enforced in ``step()``, and ``drain()``/``shutdown()``/``health()`` give
the engine an explicit lifecycle.  None of this changes any compiled
shape: deadlines, cancellation, and retirement only alter argument
values, so the zero-recompile steady state survives every failure path.

Overload (docs/SERVING.md "Overload, priorities & preemption"): sustained
pressure is a first-class regime, not a failure mode.  Requests carry a
**priority class** (``PRIORITY_LOW|NORMAL|HIGH`` or any int); the queue
is served highest-effective-priority first with **deferral aging**
(``priority_aging_s`` — a waiting request's effective priority rises over
time, so low-priority work is never starved).  When no slot — or, in
paged mode, no KV block — can serve a higher-priority admission, the
scheduler **preempts** the lowest-priority running victim: its prompt
blocks are registered in the prefix cache *before* its slot releases
(resume becomes a cheap prefix hit), and it requeues replay-from-prompt
with ``preempted``/``preemptions`` set and its stream restarting from
token 0 — the fleet redispatch stream contract, one level down.  At most
``max_preemptions`` evictions per request; past the budget a request is
immune.  **SLO-aware shedding** rejects at admission (``ShedReject``,
with ``retry_after_s``) any deadline-carrying request whose estimated
queue wait already exceeds its deadline, instead of prefilling doomed
work.  All of it is host-side bookkeeping: preemption and resume reuse
the existing prefill buckets and add ZERO executable-cache keys
(provable against tools/shape_manifest.json).
"""
from __future__ import annotations

import itertools
import os
import time
import weakref
from collections import OrderedDict, deque
from contextlib import nullcontext
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, to_tensor
from .kv_cache import KVCache, CacheContext
from .metrics import ServingMetrics
from .sampling import DeviceSampler, SamplingParams
from .sanitize import SyncSanitizer
from .tracing import NULL_TRACER, FlightRecorder, RequestTracer

__all__ = ["Engine", "Request", "SamplingParams", "QueueFull",
           "ShedReject", "EngineStopped",
           "PRIORITY_LOW", "PRIORITY_NORMAL", "PRIORITY_HIGH"]

_engine_counter = itertools.count()

#: Request states a request can never leave.
TERMINAL_STATES = frozenset({"finished", "failed", "cancelled", "rejected"})

#: Priority classes (any int works; higher serves first).
PRIORITY_LOW, PRIORITY_NORMAL, PRIORITY_HIGH = 0, 1, 2

_PRIORITY_NAMES = {"low": PRIORITY_LOW, "normal": PRIORITY_NORMAL,
                   "high": PRIORITY_HIGH}


def _as_priority(priority) -> int:
    """Normalize a priority class: ``"low"|"normal"|"high"`` or any int
    (higher = served first)."""
    if isinstance(priority, str):
        try:
            return _PRIORITY_NAMES[priority.lower()]
        except KeyError:
            raise ValueError(
                f"unknown priority {priority!r}; want one of "
                f"{sorted(_PRIORITY_NAMES)} or an int") from None
    return int(priority)


def _resolve_weights(state_or_path):
    """Normalize ``update_weights`` input to a flat state dict: a dict
    passes through, a ``.npz`` path loads its arrays, a directory loads
    a ``distributed.checkpoint.save_state_dict`` checkpoint (the
    fault-tolerant training stack's output format)."""
    if isinstance(state_or_path, dict):
        return state_or_path
    if isinstance(state_or_path, (str, os.PathLike)):
        p = os.fspath(state_or_path)
        if os.path.isdir(p):
            from ..distributed.checkpoint import load_state_dict

            return load_state_dict(p)
        if p.endswith(".npz"):
            with np.load(p) as z:
                return {k: z[k] for k in z.files}
        raise ValueError(
            f"update_weights: {p!r} is neither a checkpoint directory "
            "nor an .npz file")
    raise TypeError(
        "update_weights wants a state dict, a checkpoint directory, or "
        f"an .npz path, got {type(state_or_path).__name__}")


def _write_state_dict(model, sd, what: str = "update_weights") -> None:
    """Write ``sd`` through ``model``'s existing buffers and insist on
    full coverage — the one shared coverage check for every weight-swap
    write site (a partial write would serve a frankenmodel)."""
    missing, unexpected = model.set_state_dict(sd)
    if missing or unexpected:
        raise ValueError(
            f"{what}: state dict does not cover the model "
            f"(missing={missing[:5]}, unexpected={unexpected[:5]})")


class QueueFull(RuntimeError):
    """Admission rejected by backpressure: the request queue is at
    ``max_queue`` (and, under the ``block`` policy, stayed full past the
    block timeout).  Carries the observed ``depth`` and the engine's
    estimated ``retry_after_s`` (machine-readable; also mirrored on the
    rejected handle's ``Request.error_ctx``)."""

    def __init__(self, msg: str, depth: int,
                 retry_after_s: Optional[float] = None):
        super().__init__(msg)
        self.depth = depth
        self.retry_after_s = retry_after_s


class ShedReject(QueueFull):
    """SLO-aware admission shed: the request carries a wall-clock
    deadline its estimated queue wait already exceeds — prefilling it
    would burn a compiled prefill on work that is doomed to miss its
    SLO.  Subclasses :class:`QueueFull` so backpressure-aware callers
    (the fleet router included) handle both identically; ``retry_after_s``
    says when the backlog is expected to have cleared."""


class EngineStopped(RuntimeError):
    """``add_request`` after ``drain()``/``shutdown()`` (or on an
    unhealthy engine): the engine no longer admits work."""


@dataclass(eq=False)           # a live handle: identity, not field equality
class Request:
    """One generation request moving through the engine.

    State machine: ``queued → running → finished | failed | cancelled``;
    malformed or backpressured requests go straight to ``rejected`` at
    enqueue time and are never admitted.  ``error`` records why a request
    ended ``failed``/``rejected``.
    """

    prompt_ids: np.ndarray
    max_new_tokens: int = 16
    sampling: SamplingParams = field(default_factory=SamplingParams)
    eos_token_id: Optional[int] = None
    stream_cb: Optional[Callable[[int, "Request"], None]] = None
    request_id: int = -1
    deadline_s: Optional[float] = None   # wall-clock budget from enqueue
    #: priority class (``PRIORITY_LOW|NORMAL|HIGH`` or any int; higher
    #: serves first).  Queue ordering uses the *effective* priority —
    #: this plus the deferral-aging boost — while preemption rights
    #: compare base classes only.
    priority: int = PRIORITY_NORMAL

    # lifecycle (engine-managed)
    state: str = "queued"
    _defers: int = 0                     # paged admissions deferred so far
    #: set when the scheduler evicted this request mid-flight to serve a
    #: higher-priority admission; the stream restarted from token 0 on
    #: resume (``preemptions`` counts the evictions)
    preempted: bool = False
    preemptions: int = 0
    #: durable identity in the request journal (``Engine(journal=...)``);
    #: stable across preemption, redispatch, AND process crashes — the
    #: exactly-once terminal audit keys on it
    journal_id: Optional[str] = None
    #: set when this admission is a crash-recovery replay rehydrated
    #: from the journal: the stream restarted from token 0 (the
    #: redispatch contract, one process-death further out)
    recovered: bool = False
    #: weight version the serving engine held when this request was
    #: admitted (bumped by rolling hot-swaps; 0 = initial weights)
    model_version: int = 0
    #: tenant label for SLO accounting: the adapter name if the request
    #: selects one, else ``"grammar:<name>"`` for grammar-only requests,
    #: else ``"base"`` — threaded into metrics and the tracer
    tenant: str = "base"
    #: adapter version pinned at enqueue (None when no adapter): a
    #: hot-swap or unload of that adapter fails this request rather than
    #: serving a torn hybrid, and recovery refuses to replay onto any
    #: other version
    adapter_version: Optional[int] = None
    error: Optional[str] = None
    #: machine-readable context for backpressure/shed rejections
    #: (``{"depth": int, "retry_after_s": float}``)
    error_ctx: Optional[dict] = None
    #: who a failure implicates: ``"request"`` (this request's own prompt,
    #: callback, sampling, or deadline — retrying elsewhere would fail the
    #: same way) vs ``"replica"`` (the engine's compiled step / lifecycle
    #: failed under it — a fleet supervisor may replay it on a survivor)
    error_kind: str = "request"
    slot: Optional[int] = None
    output_ids: List[int] = field(default_factory=list)
    prefill_bucket: int = 0
    t_enqueue: float = 0.0
    t_first_token: Optional[float] = None
    t_finish: Optional[float] = None
    _seq_len: int = 0                # prompt + emitted tokens in the cache
    _cancel: bool = False
    _engine: Optional[object] = field(default=None, repr=False)

    @property
    def finished(self) -> bool:
        return self.state == "finished"

    @property
    def done(self) -> bool:
        return self.state in TERMINAL_STATES

    @property
    def ttft_s(self) -> Optional[float]:
        if self.t_first_token is None:
            return None
        return self.t_first_token - self.t_enqueue

    def cancel(self) -> bool:
        """Ask the engine to stop this request.  Honored immediately while
        queued; a running request is retired ``cancelled`` at the next
        step boundary (before its next decode).  Returns False if the
        request is already terminal."""
        if self.done:
            return False
        self._cancel = True
        eng = self._engine() if self._engine is not None else None
        if eng is not None:
            eng._on_cancel(self)
        elif self.state == "queued":
            self.state = "cancelled"
        return True


class Engine:
    """Slot-based continuous-batching engine over a causal-LM model.

    Args:
        model: ``GPTForCausalLM`` / ``LlamaForCausalLM`` (any Layer whose
            forward accepts ``cache_ctx`` works).  Switched to eval mode.
        num_slots: fixed decode batch width.
        max_seq: per-slot cache capacity (prompt + generated); defaults to
            the model's ``max_position_embeddings``.
        min_bucket: smallest prefill bucket; buckets are powers of two up
            to ``max_seq``.
        cache_dtype: KV cache dtype (default: the model's param dtype).
        max_queue: bound on queued (not-yet-admitted) requests; ``None``
            (default) is unbounded.
        queue_policy: what a full queue does to ``add_request``:
            ``"reject"`` raises :class:`QueueFull` immediately; ``"block"``
            drives ``step()`` until space frees or ``block_timeout_s``
            elapses (then raises :class:`QueueFull`).
        block_timeout_s: default wait budget for the ``block`` policy.
        default_deadline_s: wall-clock deadline applied to requests that
            set none themselves (``None`` = no deadline).
        max_step_retries: how many times a failed compiled prefill/decode
            call is retried (with exponential backoff) before the
            implicated requests are failed.  Safe because compiled-state
            writeback happens only after a step returns successfully.
        retry_backoff_s: base backoff before the first retry.
        step_timeout_s: arm a ``StepWatchdog`` around every compiled step;
            a call exceeding the deadline dumps all thread stacks and
            flips the engine to the ``unhealthy`` state (visible via
            ``health()``) instead of wedging silently.
        fault_plan: a ``ServingFaultPlan`` for chaos testing; defaults to
            the env-armed plan (``PADDLE_TPU_FT_SERVING_FAULTS``).
        kv_layout: ``"contiguous"`` (default — one ``max_seq`` stripe per
            slot) or ``"paged"`` (block-pool KV storage addressed through
            per-slot block tables, with refcounted cross-request prefix
            reuse — see docs/SERVING.md "Paged KV cache").
        kernel: paged attention path — ``"auto"`` (default: the Pallas
            flash-decoding/fused-prefill kernels that consume the block
            table in-kernel; interpret mode off-TPU so CPU runs the same
            code path), ``"pallas"`` to force them, or ``"reference"``
            for the jnp gather + masked-softmax oracle.  Ignored by the
            contiguous layout.  Selection never changes a compiled
            shape — see docs/SERVING.md "Decode hot path".
        block_size: tokens per KV block in paged mode; must divide
            ``min_bucket`` (and therefore every prefill bucket).
        num_kv_blocks: paged pool size; default
            ``num_slots * max_seq / block_size + 1`` (contiguous-parity
            capacity plus the reserved scratch block).
        enable_prefix_cache: paged mode only — hash whole prompt blocks
            host-side and serve repeated prefixes from refcounted shared
            blocks, shrinking the prefill to the uncached tail bucket.
        prefix_lookup_timeout_s: classifier for a degraded prefix cache:
            a lookup that took longer than this (the lookup is
            synchronous, so the time is already spent) is treated as a
            failed subsystem — its result is discarded, the admission
            proceeds as a plain miss, and ``paging.prefix_lookup_errors``
            is counted — keeping degraded-mode behavior deterministic
            (the same contract as a *raising* lookup).
        max_preemptions: how many times one request may be evicted
            mid-flight to make room for a higher-priority admission;
            past the budget it is immune to further preemption.  0
            disables preemption entirely.
        priority_aging_s: deferral-aging interval — a queued request's
            effective priority rises by one class per this many seconds
            of wait, so sustained high-priority traffic can never starve
            lower classes (``None`` disables aging).  Aging affects
            queue *ordering* only; preemption rights always compare base
            priority classes, so equal-priority workloads never churn.
        tracer: a :class:`~.tracing.RequestTracer` recording this
            engine's per-request lifecycle span chain (share ONE tracer
            across a fleet's replicas for the cross-replica story).
            Default: the env-armed tracer (``PADDLE_TPU_TRACE=1``) or
            the no-op :data:`~.tracing.NULL_TRACER` — tracing off costs
            nothing on the decode hot path.
        flight_recorder_steps: ring capacity of the always-on
            :class:`~.tracing.FlightRecorder` (the last N step
            summaries, dumped automatically when ``health()`` flips
            unhealthy or the fleet ejects this replica).
        journal: a :class:`~.journal.RequestJournal` — every accepted
            request is journaled durably (admission with the full
            replay recipe, batched per-step token records, terminal
            record) so a fresh process can ``recover()`` it after a
            crash.  Default None: no journaling, no overhead.  Share
            ONE journal across a fleet (fleet-managed there).
        model_version: initial weight version tag (bumped in place by
            ``update_weights``; each request records the version that
            served it).
        speculation: a :class:`~.spec_decode.SpecConfig` opting this
            engine into speculative decoding (draft-model propose, one
            bucketed ``[slots, k+1]`` verify step, device-side
            rejection-sampling accept).  Off (None) by default — the
            decode loop is unchanged.  When on, ``step()`` becomes
            round-based: k draft steps + one verify step per scheduler
            tick, emitting 1..k+1 tokens per slot per round.  Greedy
            output stays bitwise identical to non-speculative decoding;
            seeded sampling stays distribution-preserving — see
            docs/SERVING.md "Speculative decoding".
        adapters: an :class:`~.adapters.AdapterConfig` (or its kwargs as
            a dict) opting this engine into multi-LoRA serving: stacked
            per-target adapter lanes + a per-slot adapter-id lane, all
            lifted compiled-step state (ZERO new cache keys), with
            requests selecting a loaded adapter via
            ``SamplingParams.adapter``.  None (default) attaches no
            hooks — the model trace is byte-identical to pre-tenancy.
            See docs/SERVING.md "Multi-tenant serving".
        grammars: a dict mapping grammar name →
            :class:`~.grammar.JsonArrayGrammar`-style spec (or a ready
            :class:`~.grammar.GrammarTable`) opting this engine into
            constrained decoding: requests select a grammar via
            ``SamplingParams.grammar`` and the sampler masks illegal
            tokens in-graph, composing with greedy/temperature/top-k/
            top-p AND speculative verify.  None (default) = no grammar
            lanes.
    """

    def __init__(self, model, *, num_slots: int = 4,
                 max_seq: Optional[int] = None, min_bucket: int = 8,
                 cache_dtype=None, name: Optional[str] = None,
                 max_queue: Optional[int] = None,
                 queue_policy: str = "reject",
                 block_timeout_s: float = 30.0,
                 default_deadline_s: Optional[float] = None,
                 max_step_retries: int = 1,
                 retry_backoff_s: float = 0.05,
                 step_timeout_s: Optional[float] = None,
                 fault_plan=None,
                 kv_layout: str = "contiguous",
                 kernel: str = "auto",
                 block_size: int = 16,
                 num_kv_blocks: Optional[int] = None,
                 enable_prefix_cache: bool = True,
                 prefix_lookup_timeout_s: float = 0.25,
                 max_preemptions: int = 2,
                 priority_aging_s: Optional[float] = 5.0,
                 tracer=None,
                 flight_recorder_steps: int = 256,
                 journal=None,
                 model_version: int = 0,
                 speculation=None,
                 adapters=None,
                 grammars=None,
                 mesh=None):
        cfg = getattr(model, "config", None)
        if cfg is None:
            raise TypeError("Engine needs a model carrying a .config "
                            "(GPTForCausalLM / LlamaForCausalLM)")
        self.model = model
        self.model.eval()
        self.config = cfg
        max_pos = getattr(cfg, "max_position_embeddings", None)
        if max_seq is None and max_pos is None:
            raise ValueError("max_seq is required: the model config has no "
                             "max_position_embeddings to default to")
        self.max_seq = int(max_seq or max_pos)
        if max_pos is not None and self.max_seq > max_pos:
            raise ValueError(
                f"max_seq {self.max_seq} exceeds the model's "
                f"max_position_embeddings {max_pos}")
        self.num_slots = int(num_slots)
        self.min_bucket = int(min_bucket)
        if self.min_bucket < 1:
            raise ValueError(f"min_bucket must be >= 1, got {min_bucket}")
        if queue_policy not in ("reject", "block"):
            raise ValueError(f"queue_policy must be 'reject' or 'block', "
                             f"got {queue_policy!r}")
        if max_queue is not None and int(max_queue) < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        if max_step_retries < 0:
            raise ValueError("max_step_retries must be >= 0")
        if step_timeout_s is not None and step_timeout_s <= 0:
            raise ValueError("step_timeout_s must be > 0")
        if max_preemptions < 0:
            raise ValueError("max_preemptions must be >= 0")
        if priority_aging_s is not None and priority_aging_s <= 0:
            raise ValueError("priority_aging_s must be > 0 (or None to "
                             "disable aging)")
        self.buckets = self._make_buckets()
        kv_heads = getattr(cfg, "n_kv_heads", None) or cfg.num_attention_heads
        if cache_dtype is None:
            params = model.parameters()
            cache_dtype = params[0].dtype if params else "float32"
        if kv_layout not in ("contiguous", "paged"):
            raise ValueError(f"kv_layout must be 'contiguous' or 'paged', "
                             f"got {kv_layout!r}")
        if kernel not in ("auto", "pallas", "reference"):
            raise ValueError(f"kernel must be 'auto', 'pallas' or "
                             f"'reference', got {kernel!r}")
        self.kv_layout = kv_layout
        # the Pallas paged kernels are the default paged path (interpret
        # mode off-TPU keeps CPU tier-1 on the same code); contiguous
        # has only the jnp oracle
        self.kernel = ("reference" if kv_layout == "contiguous"
                       else ("pallas" if kernel == "auto" else kernel))
        self.block_size = int(block_size)
        self.prefix_cache = None
        self.prefix_lookup_timeout_s = float(prefix_lookup_timeout_s)
        if kv_layout == "paged":
            from .paging import PagedKVCache
            from .prefix_cache import PrefixCache

            if self.min_bucket % self.block_size != 0:
                raise ValueError(
                    f"block_size {self.block_size} must divide "
                    f"min_bucket {self.min_bucket} (so every prefill "
                    f"bucket is whole blocks)")
            if self.max_seq % self.block_size != 0:
                raise ValueError(
                    f"block_size {self.block_size} must divide "
                    f"max_seq {self.max_seq}")
            self.cache = PagedKVCache(
                num_slots=self.num_slots, num_layers=cfg.num_hidden_layers,
                max_seq=self.max_seq, num_kv_heads=kv_heads,
                head_dim=cfg.head_dim, dtype=cache_dtype,
                block_size=self.block_size, num_blocks=num_kv_blocks,
                kernel=self.kernel)
            if enable_prefix_cache:
                self.prefix_cache = PrefixCache(self.cache.allocator,
                                                self.block_size)
        else:
            self.cache = KVCache(
                num_slots=self.num_slots, num_layers=cfg.num_hidden_layers,
                max_seq=self.max_seq, num_kv_heads=kv_heads,
                head_dim=cfg.head_dim, dtype=cache_dtype)
        self.name = name or f"engine-{next(_engine_counter)}"
        self.metrics = ServingMetrics(self.name, num_slots=self.num_slots)
        self.metrics.health_cb = self.health
        if self.kv_layout == "paged":
            self.metrics.paging_cb = self._paging_snapshot
        self.queue: deque = deque()
        self.running: Dict[int, Request] = {}
        self.free_slots: List[int] = list(range(self.num_slots))
        # constrained decoding (opt-in, docs/SERVING.md "Multi-tenant
        # serving"): stacked per-grammar automaton tables the sampler
        # masks logits with in-graph; None = no grammar lanes
        self.grammar_table = None
        if grammars is not None:
            from .grammar import GrammarTable

            self.grammar_table = (
                grammars if isinstance(grammars, GrammarTable)
                else GrammarTable(cfg.vocab_size, grammars))
        # on-device sampling state: per-slot params/key/token lanes,
        # lifted into the compiled steps like KV cache state — the token
        # lane IS the next decode step's input ids (no host round-trip)
        self.sampler = DeviceSampler(self.num_slots,
                                     grammar=self.grammar_table)
        # multi-LoRA serving (opt-in, docs/SERVING.md "Multi-tenant
        # serving"): stacked per-target adapter lanes + the per-slot
        # adapter-id lane, hooked into every Column/Row parallel linear;
        # None attaches no hooks (trace byte-identical to pre-tenancy)
        self.adapter_pool = None
        if adapters is not None:
            from .adapters import AdapterConfig, AdapterPool

            acfg = (adapters if isinstance(adapters, AdapterConfig)
                    else AdapterConfig(**dict(adapters)))
            self.adapter_pool = AdapterPool(
                self.model, self.num_slots,
                max_adapters=acfg.max_adapters, rank=acfg.rank,
                dtype=cache_dtype)
        # speculative decoding (opt-in, docs/SERVING.md "Speculative
        # decoding"): the draft model + its KV pool + proposal lanes;
        # None keeps the plain one-token decode loop
        self.spec = None
        if speculation is not None:
            from .spec_decode import SpecState

            self.spec = SpecState(self, speculation)
            self.metrics.spec_cb = self.spec.snapshot
        # tensor-parallel sharded serving (docs/SERVING.md "Sharded
        # serving"): weights shard over the `model` mesh axis via their
        # Megatron-TP specs, the KV pool by kv_heads (GQA groups stay
        # shard-local), the sampler lanes / block tables / lengths
        # replicate — one logical decision stream drives all shards.
        # None keeps today's single-chip engine byte for byte.
        self.shard = None
        if mesh is not None:
            from .sharding import ServingShard

            self.shard = ServingShard(
                mesh, kv_heads=kv_heads,
                num_heads=cfg.num_attention_heads)
            self.shard.place_model(self.model)
            self.shard.place_state(self)
        #: mesh-shape key ("model=2") journaled per admission and
        #: validated by recover() — None for an unsharded engine
        self.mesh_shape = self.shard.key if self.shard else None
        self._req_counter = itertools.count()
        self._prefill_fn = None
        self._decode_fn = None
        self._draft_prefill_fn = None
        self._draft_decode_fn = None
        self._verify_fn = None
        #: registered compiled program sets: ``(name, warm_fn)`` —
        #: ``warmup()`` drives every entry so no registered program
        #: (target OR draft/verify) is ever a cold compile in serving
        self._warmers: List[tuple] = []
        # resilience / lifecycle
        self.max_queue = None if max_queue is None else int(max_queue)
        self.queue_policy = queue_policy
        self.block_timeout_s = float(block_timeout_s)
        self.default_deadline_s = default_deadline_s
        self.max_step_retries = int(max_step_retries)
        self.retry_backoff_s = float(retry_backoff_s)
        self.step_timeout_s = step_timeout_s
        # overload regime (priorities / preemption / shedding)
        self.max_preemptions = int(max_preemptions)
        self.priority_aging_s = None if priority_aging_s is None \
            else float(priority_aging_s)
        if fault_plan is None:
            from ..distributed.fault_tolerance.injection import \
                ServingFaultPlan

            fault_plan = ServingFaultPlan.from_env()
        self.fault_plan = fault_plan
        # sync-point sanitizer (docs/ANALYSIS.md): PADDLE_TPU_SANITIZE=1
        # counts+attributes host transfers per decode step, =strict also
        # forbids d2h inside the compiled step; None = zero overhead
        self.sanitizer = SyncSanitizer.from_env()
        # request-lifecycle tracer (docs/SERVING.md "Tracing & flight
        # recorder"): host-side span/event chain per request, no-op by
        # default; plus the always-on bounded flight recorder
        if tracer is None:
            tracer = RequestTracer.from_env() or NULL_TRACER
        self.tracer = tracer
        self.flight = FlightRecorder(flight_recorder_steps,
                                     name=self.name)
        # durable request journal (docs/SERVING.md "Durability & hot
        # swap"): a RequestJournal WAL of admission/token/terminal
        # records — None (default) journals nothing and costs nothing.
        # All journal writes are host-side file I/O outside the
        # hot-path dispatch functions.
        self.journal = journal
        #: weight version this engine serves (bumped by update_weights;
        #: every admission tags its request with the current value)
        self.model_version = int(model_version)
        self.state = "active"    # active | draining | stopped | unhealthy
        self._unhealthy_reason: Optional[str] = None
        #: devices this engine lost (simulated via the
        #: ``serving.shard_fail`` fault point, or recorded by host-side
        #: device-loss detection): read by the fleet's degraded rebuild
        #: to carve the surviving devices into a smaller viable mesh
        self.lost_devices: List = []
        self._consecutive_failures = 0
        self._step_counter = 0
        self._last_step_t: Optional[float] = None
        self._watchdog = None
        self._arm_counter = 0

    # -- compiled steps ----------------------------------------------------

    def _make_buckets(self) -> List[int]:
        b, out = self.min_bucket, []
        while b < self.max_seq:
            out.append(b)
            b *= 2
        out.append(self.max_seq)
        return out

    def bucket_for(self, prompt_len: int) -> int:
        if prompt_len > self.max_seq:
            raise ValueError(f"prompt length {prompt_len} exceeds cache "
                             f"capacity max_seq={self.max_seq}")
        for b in self.buckets:
            if prompt_len <= b:
                return b
        return self.max_seq

    def _build_steps(self) -> None:
        """Compile-cached prefill/decode programs.  Built lazily so the
        engine can be constructed before any backend is touched."""
        from .. import jit as jit_mod

        model, cache, sampler = self.model, self.cache, self.sampler
        pool = self.adapter_pool

        def _prefill_rows(slot):
            # this prefill's slot selects its adapter lane: a [1] row id
            # read from the lifted id lane (data, never a trace constant)
            return jax.lax.dynamic_index_in_dim(
                pool.adapter_ids._value(),
                slot._value().astype(jnp.int32), axis=0, keepdims=True)

        if self.kv_layout == "paged":
            from .paging import PagedCacheContext

            def prefill_step(input_ids, slot, length, start):
                # tail-bucket prefill: tokens are the UNCACHED tail of the
                # prompt, sitting at absolute positions start..; the last
                # real token is at tail index (length - start - 1)
                ctx = PagedCacheContext(cache, "prefill", slot=slot,
                                        length=length, start=start)
                if pool is not None:
                    pool.set_rows(_prefill_rows(slot))
                try:
                    logits = model(input_ids, cache_ctx=ctx)
                finally:
                    if pool is not None:
                        pool.clear_rows()
                cache.set_length(slot, length)
                arr = logits._value()                   # [1, S, V]
                idx = (length._value() - start._value()).astype(
                    jnp.int32) - 1
                last = jax.lax.dynamic_index_in_dim(
                    arr[0], idx, axis=0, keepdims=False)
                # first token sampled on-device from the slot's staged
                # lanes; key + token lanes update in-program
                tok = sampler.sample_slot(slot._value(),
                                          last.astype(jnp.float32))
                return Tensor._wrap(tok)
        else:
            def prefill_step(input_ids, slot, length):
                ctx = CacheContext(cache, "prefill", slot=slot,
                                   length=length)
                if pool is not None:
                    pool.set_rows(_prefill_rows(slot))
                try:
                    logits = model(input_ids, cache_ctx=ctx)
                finally:
                    if pool is not None:
                        pool.clear_rows()
                cache.set_length(slot, length)
                arr = logits._value()                   # [1, S, V]
                last = jax.lax.dynamic_index_in_dim(
                    arr[0], length._value().astype(jnp.int32) - 1,
                    axis=0, keepdims=False)
                tok = sampler.sample_slot(slot._value(),
                                          last.astype(jnp.float32))
                return Tensor._wrap(tok)

        def decode_step(active):
            # input ids come from the sampler's device-side token lane
            # (the previous step's sampled tokens — no host round-trip);
            # the CacheContext decode surface is layout-agnostic, and the
            # paged cache may route attention through the Pallas
            # flash-decoding kernel instead of a materializing gather
            tokens = Tensor._wrap(sampler.tokens._value()[:, None])
            ctx = CacheContext(cache, "decode", active=active)
            if pool is not None:
                # all slots decode at once: the full [slots] id lane
                pool.set_rows(pool.adapter_ids._value())
            try:
                logits = model(tokens, cache_ctx=ctx)
            finally:
                if pool is not None:
                    pool.clear_rows()
            cache.advance(active)
            toks = sampler.sample_all(
                logits._value()[:, -1, :].astype(jnp.float32))
            return Tensor._wrap(toks)

        self._prefill_fn = jit_mod.to_static(prefill_step)
        self._warmers = [("prefill", self._warm_prefill)]
        if self.spec is None:
            self._decode_fn = jit_mod.to_static(decode_step)
            self._warmers.append(("decode", self._warm_decode))
        else:
            # round-based speculative serving replaces the plain decode
            # program entirely: draft prefill per bucket, ONE draft
            # decode (proposal column j is an argument), ONE verify
            self._draft_prefill_fn = jit_mod.to_static(
                self.spec.make_draft_prefill(self))
            self._draft_decode_fn = jit_mod.to_static(
                self.spec.make_draft_decode(self))
            self._verify_fn = jit_mod.to_static(
                self.spec.make_verify(self))
            self._warmers.extend([
                ("draft_prefill", self._warm_draft_prefill),
                ("draft_decode", self._warm_draft_decode),
                ("verify", self._warm_verify),
            ])

    # -- warmup routines (one per registered program set) ------------------

    def _warm_prefill(self, buckets) -> None:
        for b in buckets:
            ids = np.zeros((1, int(b)), dtype=np.int64)
            if self.kv_layout == "paged":
                # dummy admission into slot 0: real block assignment so
                # the traced table reads see representative state, then
                # released — warmup registers nothing in the prefix cache
                if not self.cache.begin_sequence(0, [], 0, int(b)):
                    raise RuntimeError(
                        f"warmup: pool of {self.cache.num_blocks} blocks "
                        f"cannot hold one bucket-{b} prefill")
                try:
                    self._call_counted(
                        self._prefill_fn, to_tensor(ids),
                        to_tensor(np.int32(0)), to_tensor(np.int32(1)),
                        to_tensor(np.int32(0)))
                finally:
                    self.cache.release_slot(0)
            else:
                self._call_counted(
                    self._prefill_fn, to_tensor(ids),
                    to_tensor(np.int32(0)), to_tensor(np.int32(1)))

    def _warm_decode(self, buckets) -> None:
        idle = np.zeros((self.num_slots,), dtype=np.int32)
        self._call_counted(self._decode_fn, to_tensor(idle))

    def _warm_draft_prefill(self, buckets) -> None:
        for b in buckets:
            ids = np.zeros((1, int(b)), dtype=np.int64)
            self._call_counted(
                self._draft_prefill_fn, to_tensor(ids),
                to_tensor(np.int32(0)), to_tensor(np.int32(1)))

    def _warm_draft_decode(self, buckets) -> None:
        idle = np.zeros((self.num_slots,), dtype=np.int32)
        self._call_counted(self._draft_decode_fn, to_tensor(idle),
                           to_tensor(np.int32(0)))

    def _warm_verify(self, buckets) -> None:
        idle = np.zeros((self.num_slots,), dtype=np.int32)
        cap = np.ones((self.num_slots,), dtype=np.int32)
        self._call_counted(self._verify_fn, to_tensor(idle),
                           to_tensor(cap))

    def _call_counted(self, fn, *args):
        """Run a compiled step, feeding the executable cache's own state
        into the hit/miss counters (a new program in the cache == one XLA
        compile == one miss).

        This is the single choke point every compiled call (warmup AND
        serving) passes through, so it is also where a sharded engine
        installs its mesh as the global mesh: the model forwards'
        ``mark_sharding`` and the TP layers read it during tracing, and
        the save/restore keeps co-resident engines (fleet shard groups
        on disjoint device subsets) from seeing each other's mesh."""
        from contextlib import nullcontext

        from ..core.autograd import no_grad

        mesh_ctx = (self.shard.context() if self.shard is not None
                    else nullcontext())
        before = len(fn.program_cache)
        with mesh_ctx, no_grad():
            out = fn(*args)
        self.metrics.on_compile(miss=len(fn.program_cache) > before)
        return out

    # -- resilience plumbing -----------------------------------------------

    def _fault(self, point: str) -> None:
        if self.fault_plan is not None and self.fault_plan.armed:
            self.fault_plan.check(point)

    def _mark_wedged(self) -> None:
        # runs on the watchdog thread; the stalled call may still return
        # later, but the engine is permanently visible as unhealthy
        self._unhealthy_reason = (
            f"step watchdog fired: no step completion within "
            f"{self.step_timeout_s}s (stacks dumped to stderr)")
        self.state = "unhealthy"
        # post-mortem: freeze the last-N-steps ring while it still shows
        # the lead-up (safe from this thread — the scheduler is stalled)
        self.flight.dump(self._unhealthy_reason)
        self.tracer.on_unhealthy(self.name, self._unhealthy_reason)

    def _mark_shard_lost(self, reason) -> None:
        """Device loss on a sharded engine (the ``serving.shard_fail``
        fault point): deterministically "lose" the highest-index device
        of this engine's mesh, record it in ``lost_devices`` for the
        fleet's degraded rebuild, and go sticky-unhealthy exactly like a
        watchdog wedge — ejection, flight dump, and supervision all
        reuse the existing unhealthy machinery."""
        lost = list(self.shard.mesh.devices.flat)[-1]
        self.lost_devices = [lost]
        self._unhealthy_reason = (
            f"shard failure: lost device {lost} of mesh "
            f"{self.mesh_shape!r} ({reason})")
        self.state = "unhealthy"
        self.flight.dump(self._unhealthy_reason)
        self.tracer.on_unhealthy(self.name, self._unhealthy_reason)

    def _arm_watchdog(self) -> None:
        if self.step_timeout_s is None:
            return
        if self._watchdog is None:
            from ..distributed.fault_tolerance.watchdog import StepWatchdog

            # the watchdog thread must not pin the engine (model + KV
            # cache): route on_timeout through a weakref, and let the
            # thread exit on its own if the engine is GC'd without
            # drain()/shutdown() (Event.set is safe in a finalizer;
            # joining is not)
            wref = weakref.ref(self)

            def _on_timeout():
                eng = wref()
                if eng is not None:
                    eng._mark_wedged()

            self._watchdog = StepWatchdog(
                self.step_timeout_s, hard_exit=False,
                on_timeout=_on_timeout)
            self._watchdog.start()
            weakref.finalize(self, self._watchdog.request_stop)
        self._arm_counter += 1
        self._watchdog.notify(self._arm_counter)

    def _disarm_watchdog(self) -> None:
        if self._watchdog is not None:
            self._watchdog.pause()

    def _step_call(self, point: str, fn, *args):
        """One compiled step with watchdog arming, fault injection, and a
        bounded retry.  Retry is state-safe: ``jit`` writes cache state
        back only after a call returns, so a failed attempt left the KV
        cache and lengths untouched."""
        last_err = None
        for attempt in range(self.max_step_retries + 1):
            if attempt:
                self.metrics.on_retry(point)
                time.sleep(self.retry_backoff_s * (2 ** (attempt - 1)))
            try:
                self._arm_watchdog()
                try:
                    self._fault(point)
                    out = self._call_counted(fn, *args)
                finally:
                    self._disarm_watchdog()
                self._consecutive_failures = 0
                return out
            except Exception as e:       # noqa: BLE001 — isolated upstream
                last_err = e
                self._consecutive_failures += 1
                self.metrics.on_step_failure(point)
        raise last_err

    # -- public API --------------------------------------------------------

    @staticmethod
    def resolve_model(config):
        """Turn anything ``from_config`` accepts into a model Layer: a
        ready Layer passes through; a ``GPTConfig``/``LlamaConfig`` or a
        registry name (``"gpt:tiny"``, ``"llama:llama2-7b"``) builds the
        model.  Shared with ``serving.router.Fleet``, which builds ONE
        model and fans it across replicas."""
        from ..nn.layer_base import Layer
        from ..models import (
            GPT_CONFIGS, GPTConfig, GPTForCausalLM,
            LLAMA_CONFIGS, LlamaConfig, LlamaForCausalLM,
        )

        if isinstance(config, Layer):
            return config
        if isinstance(config, GPTConfig):
            return GPTForCausalLM(config)
        if isinstance(config, LlamaConfig):
            return LlamaForCausalLM(config)
        if isinstance(config, str):
            family, _, which = config.partition(":")
            reg = {"gpt": (GPT_CONFIGS, GPTForCausalLM),
                   "llama": (LLAMA_CONFIGS, LlamaForCausalLM)}.get(family)
            if reg is None or (which or "tiny") not in reg[0]:
                raise KeyError(
                    f"unknown model spec {config!r}; want "
                    f"'gpt:<{'|'.join(GPT_CONFIGS)}>' or "
                    f"'llama:<{'|'.join(LLAMA_CONFIGS)}>'")
            cfgs, cls_ = reg
            return cls_(cfgs[which or "tiny"]())
        raise TypeError(
            f"Engine.from_config: unsupported config {type(config).__name__}"
            " — pass a GPTConfig/LlamaConfig, a 'family:size' name, or a "
            "model Layer.  (jit.save artifacts have no cache-aware forward;"
            " serve those through inference.Predictor instead.)")

    @classmethod
    def from_config(cls, config, **engine_kwargs) -> "Engine":
        """Predictor-compatible entry: build an Engine from a model config
        (``GPTConfig``/``LlamaConfig``), a registry name (``"gpt:tiny"``,
        ``"llama:llama2-7b"``), or a ready model Layer."""
        return cls(cls.resolve_model(config), **engine_kwargs)

    def _validate(self, req: Request) -> Optional[str]:
        """Enqueue-time validation: a malformed request is ``rejected``
        here, never admitted (where a failure would waste a prefill)."""
        if req.prompt_ids.size == 0:
            return "empty prompt"
        if req.prompt_ids.size > self.max_seq:
            return (f"prompt length {req.prompt_ids.size} exceeds "
                    f"max_seq={self.max_seq}")
        if req.max_new_tokens < 1:
            return f"max_new_tokens must be >= 1, got {req.max_new_tokens}"
        if req.deadline_s is not None and req.deadline_s <= 0:
            return f"deadline_s must be > 0, got {req.deadline_s}"
        if self.kv_layout == "paged":
            # worst case (no prefix hit) the prompt prefills a full bucket
            # of fresh blocks; a prompt that can never fit the pool is
            # rejected up front instead of deferring forever
            need = self.bucket_for(req.prompt_ids.size) // self.block_size
            usable = self.cache.num_blocks - self.cache.allocator.reserved
            if need > usable:
                return (f"prompt needs {need} KV blocks "
                        f"(bucket {self.bucket_for(req.prompt_ids.size)}, "
                        f"block_size {self.block_size}) but the pool "
                        f"holds {usable}")
        s = req.sampling
        if s.adapter is not None:
            if self.adapter_pool is None:
                return (f"sampling.adapter={s.adapter!r} but this engine "
                        "has no adapter pool (Engine(adapters=...))")
            try:
                self.adapter_pool.resolve(s.adapter)
            except KeyError as e:
                return e.args[0]
        if s.grammar is not None:
            if self.grammar_table is None:
                return (f"sampling.grammar={s.grammar!r} but this engine "
                        "has no grammar table (Engine(grammars=...))")
            try:
                spec = self.grammar_table.spec_of(s.grammar)
            except KeyError as e:
                return e.args[0]
            g_eos = getattr(spec, "eos_token_id", None)
            if (g_eos is not None and req.eos_token_id is not None
                    and req.eos_token_id != g_eos):
                return (f"grammar {s.grammar!r} terminates on eos token "
                        f"{g_eos} but the request sets "
                        f"eos_token_id={req.eos_token_id}")
        return None

    def _reject(self, req: Request, reason: str) -> None:
        req.state, req.error = "rejected", reason
        req.t_finish = time.perf_counter()
        self.metrics.on_reject()
        self.tracer.on_retired(req, self.name, "rejected", reason)

    @staticmethod
    def _seed_for(req: Request) -> int:
        """The request's sampling seed, reconstructible: every admission
        (first and preempt-resume alike) re-seeds the slot's device key
        lane with this value, so seeded sampling replays bitwise
        deterministically (greedy ignores the key stream)."""
        return (req.sampling.seed if req.sampling.seed is not None
                else (req.request_id + 1) * 7919)

    def add_request(self, prompt_ids: Sequence[int], *,
                    max_new_tokens: int = 16,
                    sampling: Optional[SamplingParams] = None,
                    temperature: Optional[float] = None,
                    eos_token_id: Optional[int] = None,
                    stream_cb: Optional[Callable] = None,
                    deadline_s: Optional[float] = None,
                    block_timeout_s: Optional[float] = None,
                    priority=PRIORITY_NORMAL) -> Request:
        """Enqueue a prompt; it is admitted into a slot by a later
        ``step()``.  Returns the live Request handle.

        Malformed requests are marked ``rejected`` and raise ``ValueError``
        (the rejected handle rides on the exception's ``.request``).  A
        full queue raises :class:`QueueFull` under the ``reject`` policy,
        or blocks (driving ``step()``) up to ``block_timeout_s`` under
        ``block``.  ``deadline_s`` is this request's wall-clock budget
        from enqueue (default: the engine's ``default_deadline_s``); a
        deadline-carrying request whose estimated queue wait already
        exceeds that budget is shed at admission (:class:`ShedReject`,
        with ``retry_after_s``) instead of being prefilled doomed.
        ``priority`` is the request's class (``"low"|"normal"|"high"`` or
        any int; higher serves first, may preempt strictly lower)."""
        prio = _as_priority(priority)
        if self.state != "active":
            raise EngineStopped(
                f"engine {self.name!r} is {self.state}: not admitting "
                "new requests")
        prompt = np.asarray(list(prompt_ids), dtype=np.int64).reshape(-1)
        if sampling is None:
            sampling = SamplingParams(temperature=temperature or 0.0)
        req = Request(prompt_ids=prompt, max_new_tokens=int(max_new_tokens),
                      sampling=sampling, eos_token_id=eos_token_id,
                      stream_cb=stream_cb,
                      deadline_s=(deadline_s if deadline_s is not None
                                  else self.default_deadline_s),
                      priority=prio,
                      request_id=next(self._req_counter))
        # tenant label for SLO accounting (adapter > grammar > base)
        req.tenant = (sampling.adapter if sampling.adapter is not None
                      else (f"grammar:{sampling.grammar}"
                            if sampling.grammar is not None else "base"))
        if (sampling.grammar is not None and req.eos_token_id is None
                and self.grammar_table is not None):
            # a grammar terminates on ITS eos token; default the
            # request's stop condition to match (mismatch is rejected
            # in _validate)
            try:
                spec = self.grammar_table.spec_of(sampling.grammar)
                req.eos_token_id = getattr(spec, "eos_token_id", None)
            except KeyError:
                pass                     # unknown grammar → _validate
        req.t_enqueue = time.perf_counter()
        origin_wall = None
        jr = self.journal
        if jr is not None:
            # durable identity, consumed BEFORE the admission-control
            # checks: the router/recovery may have armed an adoption
            # (fleet-scoped id, recovered flag), and a recovered replay
            # must be exempt from SLO shedding below — it was accepted
            # once already, before the crash.  Otherwise the id is
            # engine-scoped, uniquified across process restarts by the
            # journal's boot marker.
            pend = jr.take_pending()
            if pend is not None:
                req.journal_id, req.recovered, origin_wall = pend
            else:
                req.journal_id = \
                    f"{self.name}:b{jr.boot}:r{req.request_id}"
        problem = self._validate(req)
        if problem is not None:
            self._reject(req, problem)
            err = ValueError(problem)
            err.request = req
            raise err
        if sampling.adapter is not None:
            # pin the adapter version at enqueue: unload/hot-swap of
            # this name fails the request instead of serving a torn
            # hybrid, and recovery refuses any other version
            req.adapter_version = self.adapter_pool.resolve(
                sampling.adapter)[1]
        wait = None if req.recovered else self._shed_wait_s(req)
        if wait is not None:
            depth = len(self.queue)
            msg = (f"shed: estimated queue wait {wait:.3f}s exceeds "
                   f"deadline {req.deadline_s}s (depth={depth}, "
                   f"retry_after_s={wait:.3f})")
            req.error_ctx = {"depth": depth,
                             "retry_after_s": round(wait, 3)}
            self.metrics.on_shed()
            self.tracer.on_shed(req, self.name, wait)
            self._reject(req, msg)
            err = ShedReject(msg, depth, retry_after_s=round(wait, 3))
            err.request = req
            raise err
        if self.max_queue is not None and len(self.queue) >= self.max_queue:
            if self.queue_policy == "block":
                budget = self.block_timeout_s if block_timeout_s is None \
                    else float(block_timeout_s)
                t_end = time.perf_counter() + budget
                while len(self.queue) >= self.max_queue:
                    if time.perf_counter() >= t_end:
                        break
                    self.step()          # drain: admit/decode in-flight work
            if len(self.queue) >= self.max_queue:
                depth = len(self.queue)
                retry = round(self.estimate_queue_wait_s(req.priority), 3)
                msg = (f"queue full: {depth} >= max_queue={self.max_queue} "
                       f"(policy={self.queue_policy}, "
                       f"retry_after_s={retry})")
                req.error_ctx = {"depth": depth, "retry_after_s": retry}
                self._reject(req, msg)
                err = QueueFull(msg, depth, retry_after_s=retry)
                err.request = req
                raise err
        req._engine = weakref.ref(self)
        if jr is not None:
            # WAL discipline: the admission record commits BEFORE the
            # request enters the queue.  A failing journal write (disk
            # full, closed file) must not leave the engine serving a
            # request its caller was told failed — reject the handle
            # and surface the storage error instead.
            s = req.sampling
            samp = {"temperature": s.temperature, "top_k": s.top_k,
                    "top_p": s.top_p, "seed": s.seed}
            # tenancy keys ride only when set: pre-tenancy records (and
            # base-tenant admissions) stay byte-identical
            if s.adapter is not None:
                samp["adapter"] = s.adapter
            if s.grammar is not None:
                samp["grammar"] = s.grammar
            try:
                jr.record_admission(
                    req.journal_id, prompt_ids=req.prompt_ids,
                    sampling=samp,
                    seed_effective=self._seed_for(req),
                    priority=req.priority, deadline_s=req.deadline_s,
                    max_new_tokens=req.max_new_tokens,
                    eos_token_id=req.eos_token_id, engine=self.name,
                    model_version=self.model_version,
                    recovered=req.recovered,
                    mesh_shape=self.mesh_shape,
                    adapter_version=req.adapter_version)
            except Exception as e:       # noqa: BLE001 — storage failure
                req.journal_id = None    # nothing durable to audit
                self._reject(req, f"journal admission write failed: "
                                  f"{type(e).__name__}: {e}")
                try:
                    e.request = req      # the rejection-path convention
                except Exception:        # noqa: BLE001 — exotic exc type
                    pass
                raise
        self.queue.append(req)
        self.metrics.on_enqueue(len(self.queue))
        self.tracer.on_queued(req, self.name)
        if jr is not None and req.recovered:
            self.metrics.on_recovered()
            self.tracer.on_recovered(req, self.name, origin_wall,
                                     journal_id=req.journal_id)
        return req

    def warmup(self, buckets: Optional[Sequence[int]] = None) -> dict:
        """Pre-compile EVERY registered compiled program set with dummy
        traffic, then reset all per-slot state — so live serving starts
        with a hot executable cache and zero steady-state misses.

        The registry (``_warmers``, built by ``_build_steps``) covers
        the target's prefill buckets and decode step AND, with
        speculation on, the draft model's prefill buckets, the draft
        decode step, and the verify step — so the first speculative
        round is never a cold compile (assert it via
        ``stats()["compile_cache"]``: the miss counter must not move
        after warmup)."""
        if self.running or self.queue:
            raise RuntimeError("warmup() must run before serving traffic "
                               "(it scribbles over slot 0 and resets all "
                               "slot lengths)")
        if self.state != "active":
            raise EngineStopped(f"engine {self.name!r} is {self.state}")
        if self._prefill_fn is None:
            self._build_steps()
        use = list(buckets or self.buckets)
        for _name, warm in self._warmers:
            warm(use)
        self.cache.reset()
        self.sampler.reset()             # warmup scribbled slot 0's lanes
        if self.adapter_pool is not None:
            self.adapter_pool.reset_slots()
        if self.spec is not None:
            self.spec.reset()
        if self.shard is not None:
            # the resets replaced the device arrays with fresh host
            # zeros — re-pin them to the mesh so serving's first step
            # sees the same shardings the warmup programs compiled for
            self.shard.place_state(self)
        return {"buckets": use,
                "programs": [name for name, _ in self._warmers],
                "compile_misses": self.metrics.compile_misses}

    # -- scheduling --------------------------------------------------------

    def _deadline_expired(self, req: Request, now: float) -> bool:
        return req.deadline_s is not None and \
            (now - req.t_enqueue) > req.deadline_s

    def _fail_deadline(self, req: Request) -> None:
        self.metrics.on_deadline()
        self._retire(req, "failed",
                     error=f"deadline of {req.deadline_s}s exceeded")

    # -- overload: priorities, preemption, shedding ------------------------

    def _effective_priority(self, req: Request, now: float) -> int:
        """Base priority class plus the deferral-aging boost (+1 class
        per ``priority_aging_s`` of queue wait) — the no-starvation
        ordering: sustained higher-priority arrivals cannot hold a
        waiting request back forever."""
        if self.priority_aging_s is None:
            return req.priority
        return req.priority + int(
            max(0.0, now - req.t_enqueue) / self.priority_aging_s)

    def _best_queued_index(self, now: float) -> Optional[int]:
        """Index of the next request to admit: highest effective
        priority, FIFO within a class (the first maximum wins, and the
        deque keeps arrival order)."""
        best_i, best_eff = None, None
        for i, q in enumerate(self.queue):
            eff = self._effective_priority(q, now)
            if best_eff is None or eff > best_eff:
                best_i, best_eff = i, eff
        return best_i

    def _best_preempting_candidate(self, now: float):
        """With every slot busy, the queued request that should preempt:
        highest effective priority among those for which a victim
        exists.  The effective head of the queue may hold NO preemption
        rights (aging grants queue position, never eviction — e.g. an
        aged low ahead of a fresh high over all-normal slots); it keeps
        its position for the next natural retirement while the
        entitled request evicts past it.  Returns
        ``(index, request, victim)`` or ``(None, None, None)``."""
        best, best_eff = (None, None, None), None
        for i, q in enumerate(self.queue):
            if q.done:
                continue
            eff = self._effective_priority(q, now)
            if best_eff is not None and eff <= best_eff:
                continue
            victim = self._pick_victim(q)
            if victim is not None:
                best, best_eff = (i, q, victim), eff
        return best

    def estimate_queue_wait_s(self,
                              priority: int = PRIORITY_NORMAL) -> float:
        """Estimated wall-clock wait before a fresh request of
        ``priority`` reaches a slot: the backlog it must wait behind
        (running requests' remaining token budgets plus queued requests
        at >= its effective priority) priced at the measured mean
        inter-token latency, spread over the decode batch width.

        Advisory and conservative by construction: a cold engine (no
        decode measurements yet) estimates 0.0 — admission never sheds
        on a guess — and a request the free slots can absorb this step
        waits 0.0.  Shared by SLO shedding and the fleet router's
        ``retry_after_s``."""
        if not self.metrics.itl_s:
            return 0.0
        now = time.perf_counter()
        queued_ahead = [q for q in self.queue
                        if self._effective_priority(q, now)
                        >= int(priority)]
        if len(queued_ahead) < len(self.free_slots):
            return 0.0
        itl = sum(self.metrics.itl_s) / len(self.metrics.itl_s)
        tokens = sum(max(0, r.max_new_tokens - len(r.output_ids))
                     for r in self.running.values())
        tokens += sum(q.max_new_tokens for q in queued_ahead)
        return tokens * itl / max(self.num_slots, 1)

    def _shed_wait_s(self, req: Request) -> Optional[float]:
        """SLO shed decision at admission: the estimated queue wait when
        it already exceeds the request's wall-clock deadline (the
        request could not finish in time even if decode were free), else
        None.  Deadline-less requests are never shed.  Preemption
        entitlement trumps the backlog estimate: a request that would
        evict its way into a slot on its first scheduling pass does not
        wait behind the running backlog, so it is never shed on it.  A
        queued request contends for that entitlement only if it could
        WIN the preemption pass — effective priority at >= this class
        AND a victim of its own (mirroring
        ``_best_preempting_candidate``: an aged victimless head never
        blocks the entitled preemptor there, so it must not force a
        shed here either)."""
        if req.deadline_s is None:
            return None
        now = time.perf_counter()
        if self._pick_victim(req) is not None and not any(
                not q.done and self._effective_priority(q, now)
                >= req.priority and self._pick_victim(q) is not None
                for q in self.queue):
            return None
        wait = self.estimate_queue_wait_s(req.priority)
        return wait if wait > req.deadline_s else None

    def _pick_victim(self, candidate: Request) -> Optional[Request]:
        """The preemption policy: among running requests of a strictly
        LOWER base priority class than the candidate's (aging never
        grants preemption rights — equal-priority workloads must not
        churn) with eviction budget left, evict the lowest class first,
        least progress (fewest emitted tokens) next, youngest last —
        minimizing the decode work thrown away."""
        if self.max_preemptions <= 0:
            return None
        cands = [r for r in self.running.values()
                 if r.priority < candidate.priority
                 and r.preemptions < self.max_preemptions]
        if not cands:
            return None
        return min(cands, key=lambda r: (r.priority, len(r.output_ids),
                                         -r.request_id))

    def _preempt(self, victim: Request) -> None:
        """Evict a running request so a higher-priority admission can
        take its slot (or, in paged mode, its blocks).  NOT a terminal
        transition — the victim requeues replay-from-prompt under the
        redispatch stream contract: ``preempted``/``preemptions`` set
        and ``output_ids`` reset BEFORE the replay's token 0, stream
        restarting from token 0 on resume.

        Resume is cheap by construction: the victim's whole prompt
        blocks are (re-)registered in the prefix cache *before* its slot
        releases, so the replay prefill hits the cached prefix and pays
        only the uncached tail bucket — reusing existing prefill
        executables, never adding a compile key."""
        slot = victim.slot
        if self.kv_layout == "paged" and self.prefix_cache is not None:
            try:
                self.prefix_cache.register(victim.prompt_ids,
                                           self.cache.owned_blocks(slot),
                                           salt=self._tenant_salt(victim))
            except Exception:            # noqa: BLE001 — isolation boundary
                self.metrics.on_prefix_register_error()
        self.running.pop(slot, None)
        if slot not in self.free_slots:
            self.free_slots.append(slot)
        if self.kv_layout == "paged":
            try:
                self.cache.release_slot(slot)
            except Exception as e:       # noqa: BLE001 — accounting bug
                self._mark_block_corruption(
                    f"release_slot({slot}) failed on preemption: "
                    f"{type(e).__name__}: {e}")
        if self.spec is not None:
            # draft KV is never resumed — the replay-from-prompt resume
            # re-prefills it (draft state is deliberately not durable)
            self.spec.release_slot(slot)
        victim.slot = None
        victim.state = "queued"
        victim.preempted = True
        victim.preemptions += 1
        victim.output_ids = []
        victim.t_first_token = None
        victim._seq_len = 0
        victim._defers = 0
        # deterministic replay: the device key lane re-seeds from
        # _seed_for at re-admission (stage_slot), not here — the victim
        # holds no slot until then
        self.queue.append(victim)        # aging runs from its original
        self.metrics.on_preempt(len(self.queue))     # t_enqueue
        self.tracer.on_preempt(victim, self.name)
        if self.journal is not None and victim.journal_id is not None:
            # the journaled stream restarts too: tokens before this
            # record are superseded by the resume's replay from token 0
            self.journal.record_restart(victim.journal_id, "preempt")

    def _on_cancel(self, req: Request) -> None:
        """Queued requests leave immediately; running ones are retired at
        the next step boundary (their slot's cache state is untouched
        mid-step — retirement only changes argument values)."""
        if req.state != "queued":
            return
        try:
            self.queue.remove(req)
        except ValueError:
            # already claimed by the scheduler (popped for admission, or
            # reaped): leave the flag — _admit/_reap honor it.  Retiring
            # here would free a slot the scheduler just assigned.
            return
        self._retire(req, "cancelled")
        self.metrics.queue_depth = len(self.queue)

    def _reap(self, now: float) -> None:
        """Honor cancellations and deadlines before building this step's
        batches, for queued and running requests alike."""
        for req in list(self.queue):
            if not (req.done or req._cancel or
                    self._deadline_expired(req, now)):
                continue
            try:
                self.queue.remove(req)
            except ValueError:
                continue     # a concurrent cancel() already removed it
            if req.done:
                continue
            if req._cancel:
                self._retire(req, "cancelled")
            else:
                self._fail_deadline(req)
        for req in list(self.running.values()):
            if req._cancel:
                self._retire(req, "cancelled")
            elif self._deadline_expired(req, now):
                self._fail_deadline(req)
        self.metrics.queue_depth = len(self.queue)

    def _emit_token(self, req: Request, tok: int, now: float) -> bool:
        """Record one emitted token and run the stream callback.  A
        raising callback fails THIS request only: the error is recorded on
        the request and counted, never propagated into the batch."""
        if req.t_first_token is None:
            req.t_first_token = now
        req.output_ids.append(int(tok))
        if req.stream_cb is not None:
            try:
                self._fault("serving.stream_cb")
                req.stream_cb(int(tok), req)
            except Exception as e:       # noqa: BLE001 — isolation boundary
                self.metrics.on_callback_error()
                self._retire(req, "failed",
                             error=f"stream_cb raised: "
                                   f"{type(e).__name__}: {e}")
                return False
        return True

    def _tenant_salt(self, req: Request) -> bytes:
        """Prefix-cache tenant salt for ``req``'s adapter (``b""`` for
        the base tenant): folded into the chain-hash root so tenant KV
        never cross-hits across adapters or versions.  An
        unloaded-but-versioned name still salts uniquely, so a dying
        tenant cannot poison anyone else's lookups."""
        a = req.sampling.adapter
        if a is None or self.adapter_pool is None:
            return b""
        try:
            return self.adapter_pool.salt(a)
        except KeyError:
            v = self.adapter_pool.last_version(a)
            return f"{a}@v{v}#unloaded".encode()

    def _prefix_lookup(self, req: Request):
        """Longest cached prefix of the prompt, ``(n_tokens, block_ids)``.
        A raising or over-budget lookup degrades to a miss: the request
        still completes with a full prefill, the error is only counted
        (``paging.prefix_lookup_errors``), and no block was referenced.
        Hit-rate accounting happens in ``_paged_prefill`` AFTER the
        partial-hit cap, so the gauge only ever credits blocks that are
        actually reused — a discarded (raising/over-budget) result is
        recorded as a plain miss there."""
        if self.prefix_cache is None:
            return 0, []
        t0 = time.perf_counter()
        try:
            self._fault("serving.prefix_lookup")
            hit_tokens, blocks = self.prefix_cache.lookup(
                req.prompt_ids, count=False,
                salt=self._tenant_salt(req))
        except Exception:                # noqa: BLE001 — isolation boundary
            self.metrics.on_prefix_lookup_error()
            return 0, []
        if time.perf_counter() - t0 > self.prefix_lookup_timeout_s:
            # over-budget = degraded subsystem: discard the (late) result
            # and serve a deterministic plain miss, same as a raising
            # lookup (the stall itself is sunk cost — the lookup is
            # synchronous and cannot be pre-empted)
            self.metrics.on_prefix_lookup_error()
            return 0, []
        return hit_tokens, blocks

    def _prefill_call(self, req: Request, *args):
        """One compiled prefill with the bounded retry; exhausted retries
        retire ``req`` as failed and return None (shared by both KV
        layouts so the retire semantics cannot diverge)."""
        try:
            return self._step_call("serving.prefill", self._prefill_fn,
                                   *args)
        except Exception as e:           # noqa: BLE001 — isolation boundary
            n = self.max_step_retries
            self._retire(req, "failed",
                         error=f"prefill failed after {n} "
                               f"retr{'y' if n == 1 else 'ies'}: "
                               f"{type(e).__name__}: {e}",
                         kind="replica")
            return None

    def _paged_prefill(self, req: Request, L: int):
        """Paged admission: prefix lookup, block assignment, tail-bucket
        prefill.  Returns ``(status, first_token, bucket, prefix_hit)``
        with status ``"ok" | "deferred" | "failed"`` (``deferred`` = the
        pool cannot supply the tail blocks right now and the slot was
        left untouched; ``failed`` = the request was already retired);
        ``first_token`` is the on-device-sampled first token (a scalar
        int32 device handle); ``prefix_hit`` is the reused prefix length
        in tokens."""
        P, shared = self._prefix_lookup(req)
        bucket = self.bucket_for(L - P)
        # a PARTIAL hit can push prefix + padded tail past the slot's
        # block table (e.g. hit 8 of a 32-token prompt with buckets
        # {8,16,32}: 1 + 32/8 = 5 blocks on a 4-block table) — drop hit
        # blocks from the end until the padded tail fits; the remaining
        # hit is still a contiguous prefix
        while shared and (len(shared) + bucket // self.block_size
                          > self.cache.max_blocks_per_slot):
            shared = shared[:-1]
            P -= self.block_size
            bucket = self.bucket_for(L - P)
        if self.prefix_cache is not None and req._defers == 0:
            # one logical lookup per request (deferral retries re-look-up
            # for freshness but don't re-count), credited with only the
            # hit span that is ACTUALLY reused post-cap — discarded and
            # raising lookups land here as P == 0, i.e. a plain miss
            self.prefix_cache.record_lookup(L, P)
        if not self.cache.begin_sequence(req.slot, shared, P, bucket):
            return "deferred", None, bucket, P
        ids = np.zeros((1, bucket), dtype=np.int64)
        ids[0, :L - P] = req.prompt_ids[P:]
        last = self._prefill_call(
            req, to_tensor(ids), to_tensor(np.int32(req.slot)),
            to_tensor(np.int32(L)), to_tensor(np.int32(P)))
        if last is None:
            return "failed", None, bucket, P
        if self.prefix_cache is not None:
            # make this prompt's whole blocks hittable by later requests
            # (hit blocks are refreshed, new full tail blocks registered)
            try:
                self.prefix_cache.register(
                    req.prompt_ids, self.cache.owned_blocks(req.slot),
                    salt=self._tenant_salt(req))
            except Exception:            # noqa: BLE001 — isolation boundary
                self.metrics.on_prefix_register_error()
        return "ok", last, bucket, P

    # tpulint: hot-path
    def _admit(self, req: Request) -> Optional[bool]:
        """Prefill ``req`` into its pre-assigned slot.  Never raises for
        request-level problems — a prefill/sampling/callback failure fails
        this request only (``_retire`` reclaims the slot).  Returns False
        when paged admission must be deferred (no KV blocks free); the
        scheduler re-queues the request with its slot returned."""
        if req._cancel:                  # cancelled between pop and prefill
            self._retire(req, "cancelled")
            return None
        if self._deadline_expired(req, time.perf_counter()):
            # expired while queued (possibly during an earlier admission
            # this very step): retire as a deadline failure WITHOUT
            # paying a compiled prefill for work that is already dead
            self._fail_deadline(req)
            return None
        L = int(req.prompt_ids.size)
        t0 = time.perf_counter()
        prefix_hit = 0
        # stage the slot's device sampling lanes (params + key re-seed)
        # BEFORE the prefill dispatch: the compiled step samples the
        # first token on-device from exactly this state
        self.sampler.stage_slot(req.slot, req.sampling,
                                self._seed_for(req))
        if self.adapter_pool is not None:
            # stage the slot's adapter lane id; a request whose adapter
            # vanished (unload) or moved on (hot-swap bumped the
            # version) between enqueue and admission fails here with
            # machine-readable context instead of decoding under the
            # wrong weights
            a = req.sampling.adapter
            try:
                if a is not None and req.adapter_version is not None:
                    _, v = self.adapter_pool.resolve(a)
                    if v != req.adapter_version:
                        raise KeyError(
                            f"adapter {a!r} was hot-swapped to v{v} "
                            f"(request pinned v{req.adapter_version})")
                self.adapter_pool.stage_slot(req.slot, a)
            except KeyError as e:
                req.error_ctx = {
                    "adapter": a,
                    "version": (req.adapter_version
                                if req.adapter_version is not None
                                else self.adapter_pool.last_version(a)),
                }
                self._retire(req, "failed", error=str(e.args[0]))
                return None
        if self.kv_layout == "paged":
            status, tok_t, bucket, prefix_hit = self._paged_prefill(req, L)
            if status == "deferred":
                return False
            if status == "failed":
                return None
        else:
            bucket = self.bucket_for(L)
            ids = np.zeros((1, bucket), dtype=np.int64)
            ids[0, :L] = req.prompt_ids
            tok_t = self._prefill_call(
                req, to_tensor(ids), to_tensor(np.int32(req.slot)),
                to_tensor(np.int32(L)))
            if tok_t is None:
                return None
        if self.spec is not None and not self._spec_admit(req, L):
            return None
        now = time.perf_counter()
        self.metrics.prefill_time_s += now - t0
        req.state, req.prefill_bucket = "running", bucket
        req.model_version = self.model_version
        req._seq_len = L
        self.running[req.slot] = req
        self.metrics.on_admit(bucket, L, len(self.queue))
        self.tracer.on_admitted(req, self.name, bucket, req.slot,
                                prefix_hit)
        self._deliver_first_token(req, tok_t, now)

    def _spec_admit(self, req: Request, L: int) -> bool:
        """Draft-side half of a speculating admission: stage the draft
        sampler lanes (params + salt-derived seed — identically on
        first admission, preempt-resume, and crash-recovery replay, the
        determinism contract) and prefill the prompt into the draft
        cache.  The draft always prefills its full-prompt bucket — it
        keeps no prefix cache; draft KV is cheap and deliberately not
        durable.  Failure retires the request (replica-implicated, like
        any compiled-step failure) and returns False."""
        self.spec.stage_slot(req.slot, req.sampling, self._seed_for(req))
        bucket = self.bucket_for(L)
        ids = np.zeros((1, bucket), dtype=np.int64)
        ids[0, :L] = req.prompt_ids
        try:
            self._step_call("serving.spec_draft_prefill",
                            self._draft_prefill_fn, to_tensor(ids),
                            to_tensor(np.int32(req.slot)),
                            to_tensor(np.int32(L)))
        except Exception as e:           # noqa: BLE001 — isolation boundary
            n = self.max_step_retries
            self._retire(req, "failed",
                         error=f"draft prefill failed after {n} "
                               f"retr{'y' if n == 1 else 'ies'}: "
                               f"{type(e).__name__}: {e}",
                         kind="replica")
            return False
        return True

    def _deliver_first_token(self, req: Request, tok_t, now: float
                             ) -> None:
        """Stream delivery of the admission's on-device-sampled first
        token.  The only host copy is the token scalar itself — a
        per-admission (never per-decode-step) pull, outside the
        hot-path dispatch functions."""
        tok = int(tok_t.numpy())
        if self.journal is not None and req.journal_id is not None:
            # journal BEFORE the user-visible emit: delivery is
            # at-least-once across a crash by contract
            self.journal.record_tokens(self.name, self._step_counter,
                                       {req.journal_id: tok})
        if not self._emit_token(req, tok, now):
            return
        self.metrics.on_first_token(req.ttft_s, tenant=req.tenant)
        if self._done_after_emit(req):
            self._retire(req)

    def _done_after_emit(self, req: Request) -> bool:
        if len(req.output_ids) >= req.max_new_tokens:
            return True
        if req.eos_token_id is not None and \
                req.output_ids[-1] == req.eos_token_id:
            return True
        # the NEXT decode would write at position _seq_len; the emitted
        # token itself still needs a cache line to attend from
        if req._seq_len + 1 > self.max_seq:
            return True
        return False

    def _retire(self, req: Request, state: str = "finished",
                error: Optional[str] = None,
                kind: Optional[str] = None) -> None:
        """THE single exit path: every terminal transition funnels here,
        so the slot is reclaimed exactly once on every outcome.
        Idempotent — a request already terminal is left untouched.
        ``kind`` tags who the failure implicates (``Request.error_kind``)
        so a fleet supervisor can tell replayable replica faults from
        request-fatal ones."""
        if req.done:
            return
        req.state = state
        if error is not None:
            req.error = error
        if kind is not None:
            req.error_kind = kind
        req.t_finish = time.perf_counter()
        slot = req.slot
        if slot is not None:
            self.running.pop(slot, None)
            if slot not in self.free_slots:
                self.free_slots.append(slot)
            if self.kv_layout == "paged":
                # drop the slot's block refs (idempotent); blocks also
                # registered in the prefix cache stay alive on its ref
                try:
                    self.cache.release_slot(slot)
                except Exception as e:   # noqa: BLE001 — accounting bug
                    self._mark_block_corruption(
                        f"release_slot({slot}) failed: "
                        f"{type(e).__name__}: {e}")
            if self.spec is not None:
                self.spec.release_slot(slot)
        if state == "finished":
            self.metrics.on_complete(tenant=req.tenant,
                                     n_tokens=len(req.output_ids))
        elif state == "cancelled":
            self.metrics.on_cancel()
        elif state == "failed":
            self.metrics.on_fail(tenant=req.tenant)
        self.tracer.on_retired(req, self.name, state, req.error)
        if self.journal is not None and req.journal_id is not None:
            # fleet-owned requests end their ATTEMPT here; the router's
            # exactly-once _finish writes the one FINAL end (mirror of
            # the tracer's final-event ownership)
            self.journal.record_end(
                req.journal_id, state,
                final=not self.journal.is_fleet_owned(req.journal_id),
                error=req.error, n_tokens=len(req.output_ids),
                engine=self.name)

    def _mark_block_corruption(self, reason: str) -> None:
        """A block-accounting violation is engine-fatal for trust (not
        for liveness): surface it sticky via health() instead of
        corrupting the pool silently."""
        if self.state != "unhealthy":
            self.state = "unhealthy"
            self._unhealthy_reason = f"KV block accounting: {reason}"
            self.flight.dump(self._unhealthy_reason)
            self.tracer.on_unhealthy(self.name, self._unhealthy_reason)

    def _prepare_decode_paged(self) -> None:
        """Host-side block maintenance before a paged decode step: each
        running slot's next write position must land on a block it owns
        exclusively — growing sequences get a fresh block, shared blocks
        are copied-on-extend.  A slot the pool cannot serve fails (the
        engine and its batch continue)."""
        for slot, req in list(self.running.items()):
            try:
                ok = self.cache.ensure_capacity(slot, req._seq_len)
            except Exception as e:       # noqa: BLE001 — accounting bug
                self._mark_block_corruption(
                    f"ensure_capacity({slot}) failed: "
                    f"{type(e).__name__}: {e}")
                ok = False
            if not ok:
                self.tracer.on_block_pressure(req, self.name,
                                              kind="pool_exhausted",
                                              position=req._seq_len)
                self._retire(req, "failed",
                             error="KV block pool exhausted: no block "
                                   f"free for position {req._seq_len} "
                                   "(even after prefix-cache eviction)")

    def _decode(self) -> None:
        """One decode step (or, with speculation on, one ROUND: k draft
        steps + one verify step).  The *dispatch* (``_decode_body`` /
        ``_spec_round_body``) runs under the sanitizer's counting window
        when armed (``PADDLE_TPU_SANITIZE``): every framework-level host
        coercion inside is counted and attributed to its source line —
        0.0 since ROADMAP item 2 moved sampling on-device (the PR 7
        baseline was the 1.0 per-step logits pull), and speculation
        keeps it 0.0 (proposals chain device-side, acceptance is
        in-graph).  Stream *delivery* — pulling the sampled ``[slots]``
        (or per-round ``[slots, k+2]``) int32 array for callbacks and
        stop checks — happens after the window closes: the next step's
        inputs already live on device (the sampler token lanes), so the
        pull is not on the dispatch critical path."""
        san = self.sanitizer
        if self.spec is not None:
            with (nullcontext() if san is None else san.decode_window()):
                res = self._spec_round_body()
            if res is not None:
                self._deliver_spec(*res)
            return
        with (nullcontext() if san is None else san.decode_window()):
            res = self._decode_body()
        if res is not None:
            self._deliver_tokens(*res)

    # tpulint: hot-path
    def _decode_body(self):
        """Dispatch one compiled decode step; device handles only — no
        d2h coercion belongs here (tpulint TPL106 enforces it, with ZERO
        suppressions since on-device sampling landed).  Returns
        ``(token_tensor, t0)`` or None (nothing ran / batch failed)."""
        if self.kv_layout == "paged":
            self._prepare_decode_paged()
            if not self.running:
                return None
        active = np.zeros((self.num_slots,), dtype=np.int32)
        for slot in self.running:
            active[slot] = 1
        t0 = time.perf_counter()
        san = self.sanitizer
        try:
            # the compiled step itself must not round-trip to host: the
            # sanitizer arms jax.transfer_guard_device_to_host around it
            # (log, or disallow in strict mode — backend-enforced on TPU)
            with (nullcontext() if san is None else san.compiled_guard()):
                out = self._step_call("serving.decode", self._decode_fn,
                                      to_tensor(active))
        except Exception as e:           # noqa: BLE001 — isolation boundary
            # retry budget exhausted: every request in THIS batch is
            # implicated; fail them (reclaiming their slots) and keep the
            # engine alive for queued work
            # the guard's exact phrasing (jaxlib guard_lib), not a loose
            # "transfer" substring — ordinary step failures that happen
            # to mention buffers/transfers must not count as violations
            if san is not None and "device-to-host transfer" in str(e):
                san.guard_violations += 1
            msg = (f"decode step failed after {self.max_step_retries} "
                   f"retr{'y' if self.max_step_retries == 1 else 'ies'}: "
                   f"{type(e).__name__}: {e}")
            for req in list(self.running.values()):
                self._retire(req, "failed", error=msg, kind="replica")
            return None
        if san is not None:
            san.note_step()             # the compiled step actually ran
        return out, t0

    def _deliver_tokens(self, out, t0: float) -> None:
        """Post-dispatch host half of a decode step: pull the sampled
        token ids (ONE tiny ``[slots] int32`` array — stream delivery
        and stop checks are host work by nature, and the pull sits
        outside both the sanitizer window and the hot-path dispatch),
        then run callbacks and retirement checks."""
        toks = out.numpy()                       # [slots] int32
        now = time.perf_counter()
        if self.journal is not None:
            # ONE batched record per engine step covering every active
            # slot (never one record per token) — the same batching
            # discipline as the tracer's decode_step event
            tokmap = {r.journal_id: int(toks[s])
                      for s, r in self.running.items()
                      if r.journal_id is not None}
            if tokmap:
                self.journal.record_tokens(self.name, self._step_counter,
                                           tokmap)
        self.metrics.on_decode_step(len(self.running), now - t0)
        tr = self.tracer
        if tr.enabled:
            # ONE batched event per engine step, never one per token
            tr.on_decode_step(self.name, self._step_counter,
                              list(self.running), now - t0)
        for slot, req in list(self.running.items()):
            req._seq_len += 1                    # token written this step
            if not self._emit_token(req, int(toks[slot]), now):
                continue
            if req.done:                 # cancelled from inside its cb
                continue
            if self._done_after_emit(req):
                self._retire(req)

    def _prepare_spec_paged(self) -> None:
        """Host-side block maintenance before a speculative round: each
        running slot must exclusively own the blocks covering its whole
        verify window ``[len, len+k]`` (the fixed-shape verify writes
        all k+1 positions regardless of acceptance) — fresh blocks
        appended, shared covering blocks copied-on-extend, exactly the
        per-position ``ensure_capacity`` contract the plain decode path
        uses, applied across the window.  Over-the-end positions of a
        near-capacity slot are excluded (the verify write masks them to
        scratch).  A slot the pool cannot serve fails its request; the
        engine and the rest of the batch continue."""
        k = self.spec.k
        for slot, req in list(self.running.items()):
            ok = True
            try:
                last = min(req._seq_len + k, self.max_seq - 1)
                for pos in range(req._seq_len, last + 1):
                    if not self.cache.ensure_capacity(slot, pos):
                        ok = False
                        break
            except Exception as e:       # noqa: BLE001 — accounting bug
                self._mark_block_corruption(
                    f"ensure_capacity({slot}) failed: "
                    f"{type(e).__name__}: {e}")
                ok = False
            if not ok:
                self.tracer.on_block_pressure(req, self.name,
                                              kind="pool_exhausted",
                                              position=req._seq_len)
                self._retire(req, "failed",
                             error="KV block pool exhausted: no block "
                                   "free for the verify window at "
                                   f"position {req._seq_len} (even "
                                   "after prefix-cache eviction)")

    # tpulint: hot-path
    def _spec_round_body(self):
        """Dispatch one speculative ROUND: k draft-decode steps (the
        proposals chain through the draft sampler's device token lane)
        and one bucketed ``[slots, k+1]`` verify step with in-graph
        acceptance.  Device handles only — no d2h coercion belongs here
        (tpulint TPL106; the sanitizer window covers this dispatch, so
        the measured per-round host transfers stay 0.0).  Returns
        ``(round_tensor, t0)`` or None (nothing ran / round failed)."""
        spec = self.spec
        if self.kv_layout == "paged":
            self._prepare_spec_paged()
        if not self.running:
            return None
        active = np.zeros((self.num_slots,), dtype=np.int32)
        cap = np.ones((self.num_slots,), dtype=np.int32)
        for slot, req in self.running.items():
            active[slot] = 1
            # per-slot emission cap: token budget and cache capacity,
            # host ints only — the in-graph acceptance clamps to it
            # (truncating the emission stream is distribution-safe:
            # every emitted position is marginally the target law).
            # Both terms are >= 1 for any request still running —
            # _done_after_emit retires at the budget/capacity boundary
            # before the next round — so the max(1, ...) is a floor for
            # the in-graph clip's domain, never a behavior change.
            cap[slot] = max(1, min(spec.k + 1,
                                   req.max_new_tokens
                                   - len(req.output_ids),
                                   self.max_seq - req._seq_len))
        t0 = time.perf_counter()
        san = self.sanitizer
        try:
            with (nullcontext() if san is None else san.compiled_guard()):
                act_t = to_tensor(active)
                for j in range(spec.k):
                    self._step_call("serving.spec_draft",
                                    self._draft_decode_fn, act_t,
                                    to_tensor(np.int32(j)))
                out = self._step_call("serving.spec_verify",
                                      self._verify_fn, act_t,
                                      to_tensor(cap))
        except Exception as e:           # noqa: BLE001 — isolated upstream
            if san is not None and "device-to-host transfer" in str(e):
                san.guard_violations += 1
            msg = (f"speculative round failed after "
                   f"{self.max_step_retries} "
                   f"retr{'y' if self.max_step_retries == 1 else 'ies'}: "
                   f"{type(e).__name__}: {e}")
            for req in list(self.running.values()):
                self._retire(req, "failed", error=msg, kind="replica")
            return None
        if san is not None:
            san.note_step()             # one round == one counted step
        return out, t0

    def _deliver_spec(self, out, t0: float) -> None:
        """Post-dispatch host half of a speculative round: pull the ONE
        ``[slots, k+2]`` int32 round result (per-slot emitted count +
        emission stream — the same shape-class pull as non-speculative
        stream delivery, outside the sanitizer window and the hot-path
        dispatch), then do the host bookkeeping the in-graph acceptance
        cannot: paged block-table truncation past the accepted length,
        journal/metrics/tracer records (one batched record per ROUND —
        the decode_step discipline), stream callbacks, and retirement
        checks."""
        arr = out.numpy()                # [slots, k+2] int32
        now = time.perf_counter()
        spec = self.spec
        running = list(self.running.items())
        step_s = now - t0
        delivered: Dict[int, List[int]] = {}
        accepted_total = 0
        for slot, req in running:
            m = int(arr[slot, 0])
            accepted_total += max(0, m - 1)
            toks = [int(t) for t in arr[slot, 1:1 + m]]
            if req.eos_token_id is not None and req.eos_token_id in toks:
                # the round ran past the stop token; everything after
                # it is never delivered (matching the non-speculative
                # loop, which would have stopped there)
                toks = toks[:toks.index(req.eos_token_id) + 1]
            delivered[slot] = toks
        if self.journal is not None:
            # ONE batched record per ROUND, each jid carrying its whole
            # delivered burst (journal BEFORE the user-visible emits:
            # at-least-once delivery across a crash, unchanged)
            tokmap = {r.journal_id: delivered[s]
                      for s, r in running
                      if r.journal_id is not None and delivered[s]}
            if tokmap:
                self.journal.record_tokens(self.name, self._step_counter,
                                           tokmap)
        self.metrics.on_spec_round(
            step_s, draft_steps=spec.k,
            proposed=spec.k * len(running), accepted=accepted_total,
            delivered=[len(delivered[s]) for s, _ in running])
        tr = self.tracer
        if tr.enabled:
            # ONE batched event per ROUND, never one per token — the
            # decode_step discipline with the round's (proposed,
            # accepted) pair riding along
            tr.on_verify_step(self.name, self._step_counter,
                              [s for s, _ in running], step_s,
                              proposed=spec.k * len(running),
                              accepted=accepted_total)
        for slot, req in running:
            m = int(arr[slot, 0])
            req._seq_len += m            # the in-graph advance, mirrored
            if self.kv_layout == "paged":
                # rollback bookkeeping: drop table blocks past the
                # accepted length (no copy — refcounts + table writes)
                try:
                    self.cache.truncate_blocks(slot, req._seq_len)
                except Exception as e:   # noqa: BLE001 — accounting bug
                    self._mark_block_corruption(
                        f"truncate_blocks({slot}) failed: "
                        f"{type(e).__name__}: {e}")
            finished = False
            for tok in delivered[slot]:
                if not self._emit_token(req, tok, now):
                    finished = True      # callback failure retired it
                    break
                if req.done:             # cancelled from inside its cb
                    finished = True
                    break
                if len(req.output_ids) >= req.max_new_tokens or \
                        (req.eos_token_id is not None
                         and req.output_ids[-1] == req.eos_token_id):
                    self._retire(req)
                    finished = True
                    break
            if not finished and not req.done \
                    and req._seq_len + 1 > self.max_seq:
                # cache capacity: checked once per round (the cap
                # already bounded the burst to fit)
                self._retire(req)

    def step(self) -> bool:
        """One scheduler tick: reap cancellations/deadlines, admit queued
        requests into free slots, then run one decode step for all running
        slots (one speculative ROUND when speculation is on).  Returns
        True while there is in-flight or queued work.
        Raises ``EngineStopped`` once the watchdog has marked the engine
        unhealthy."""
        if self.state == "unhealthy":
            raise EngineStopped(
                f"engine {self.name!r} is unhealthy: "
                f"{self._unhealthy_reason}")
        if self.shard is not None and self.fault_plan is not None \
                and self.fault_plan.armed:
            # simulated device loss (serving.shard_fail@N): the engine
            # loses one device of its mesh and goes sticky-unhealthy —
            # the fleet's supervision ejects it and rebuilds the group
            # DEGRADED at a smaller viable mp on the survivors
            from ..distributed.fault_tolerance.injection import \
                InjectedFault
            try:
                self.fault_plan.check("serving.shard_fail")
            except InjectedFault as e:
                self._mark_shard_lost(e)
                raise EngineStopped(
                    f"engine {self.name!r} is unhealthy: "
                    f"{self._unhealthy_reason}") from e
        if self._prefill_fn is None:
            self._build_steps()
        self._reap(time.perf_counter())
        while self.queue:
            now_a = time.perf_counter()
            i = self._best_queued_index(now_a)
            req = self.queue[i]
            if req.done:                 # cancelled/expired while queued
                del self.queue[i]
                continue
            if not self.free_slots:
                # slot-table pressure: the entitled queued request (not
                # necessarily the effective head — aging grants queue
                # position, never eviction rights) may evict the
                # lowest-priority running victim; otherwise the queue
                # waits for a natural retirement
                i, req, victim = self._best_preempting_candidate(now_a)
                if victim is None:
                    break
                del self.queue[i]
                self._preempt(victim)
            else:
                del self.queue[i]
            req.slot = self.free_slots.pop()
            try:
                deferred = self._admit(req) is False
            except BaseException:
                # _admit isolates request-level failures itself; this is
                # the guarantee that even an engine-level bug (or
                # KeyboardInterrupt mid-prefill) cannot leak the slot
                if not req.done:
                    self._retire(req, "failed",
                                 error="admission aborted by engine error")
                raise
            if deferred:
                # paged mode: the pool has no blocks for this prompt
                # right now — hand the slot back.  A higher-priority
                # admission may evict a lower-priority victim (freeing
                # its blocks) and retry immediately; otherwise requeue
                # at the head and retry once running work retires.  With
                # nothing running, no block can ever free (eviction was
                # already attempted inside alloc), so fail instead of
                # spinning forever.
                self.free_slots.append(req.slot)
                req.slot = None
                req._defers += 1
                self.tracer.on_block_pressure(req, self.name,
                                              defers=req._defers)
                victim = self._pick_victim(req)
                if victim is not None:
                    self._preempt(victim)
                    self.queue.appendleft(req)
                    continue
                if self.running:
                    self.queue.appendleft(req)
                else:
                    self._retire(req, "failed",
                                 error="KV block pool exhausted: prompt "
                                       "needs more free blocks than the "
                                       "pool can supply")
                break
        self.metrics.on_slots(len(self.running))
        if self.running:
            self._decode()
        self._step_counter += 1
        self._last_step_t = time.perf_counter()
        # always-on flight recorder: one compact host-side summary per
        # step into the bounded ring (the post-mortem tail)
        if self.kv_layout == "paged":
            self.flight.record(step=self._step_counter,
                               running=len(self.running),
                               queued=len(self.queue),
                               free_blocks=self.cache.allocator
                               .free_blocks)
        else:
            self.flight.record(step=self._step_counter,
                               running=len(self.running),
                               queued=len(self.queue))
        return bool(self.running or self.queue)

    def run(self, max_steps: Optional[int] = None) -> None:
        """Drive ``step()`` until idle (or ``max_steps``)."""
        n = 0
        while self.step():
            n += 1
            if max_steps is not None and n >= max_steps:
                break

    def generate(self, prompts: Sequence[Sequence[int]], *,
                 max_new_tokens: int = 16, **request_kwargs
                 ) -> List[List[int]]:
        """Synchronous convenience: serve a batch of prompts through the
        continuous-batching loop; returns generated ids per prompt."""
        reqs = [self.add_request(p, max_new_tokens=max_new_tokens,
                                 **request_kwargs) for p in prompts]
        self.run()
        return [r.output_ids for r in reqs]

    # -- lifecycle ---------------------------------------------------------

    def drain(self, max_steps: Optional[int] = None) -> dict:
        """Stop admitting new requests, finish all queued and in-flight
        work, and return the final stats snapshot.  The engine ends in the
        ``stopped`` state (``add_request`` raises ``EngineStopped``)."""
        if self.state == "active":
            self.state = "draining"
        n = 0
        while (self.running or self.queue) and self.state == "draining":
            try:
                self.step()
            except EngineStopped:
                break                    # wedged mid-drain: sticky unhealthy
            n += 1
            if max_steps is not None and n >= max_steps:
                break
        if self.state == "draining" and not (self.running or self.queue):
            self.state = "stopped"
            self._stop_watchdog()
        return self.stats()

    def shutdown(self, timeout_s: Optional[float] = None) -> dict:
        """Drain with a wall-clock budget, then cancel whatever work is
        still unfinished and stop the engine.  ``timeout_s=None`` waits
        for all work (equivalent to ``drain()`` + final cleanup)."""
        if self.state == "active":
            self.state = "draining"
        deadline = None if timeout_s is None \
            else time.perf_counter() + float(timeout_s)
        while (self.running or self.queue) and self.state == "draining":
            if deadline is not None and time.perf_counter() >= deadline:
                break
            try:
                self.step()
            except EngineStopped:
                break                    # wedged mid-drain: cancel the rest
        for req in list(self.queue) + list(self.running.values()):
            # lifecycle cancellation implicates the ENGINE, not the
            # request — a fleet supervisor may replay these elsewhere
            self._retire(req, "cancelled", error="engine shutdown",
                         kind="replica")
        self.queue.clear()
        self.metrics.queue_depth = 0
        if self.state != "unhealthy":
            self.state = "stopped"
        self._stop_watchdog()
        return self.stats()

    # -- fleet-supervisor hooks --------------------------------------------

    def export_requests(self) -> List[Request]:
        """Strip every non-terminal request off this engine for
        re-dispatch elsewhere — the ejection hook of the fleet
        supervisor (``serving.router.Fleet``).

        Queued AND in-flight requests are returned in scheduling order
        (queue first, then running slots) after being retired here as
        ``cancelled`` with ``error_kind="replica"`` — the single retire
        path reclaims their slots (and paged blocks) even on an engine
        mid-corruption, so the exported handles carry no live engine
        state.  The caller replays each from its original prompt; this
        engine is then safe to shut down or discard."""
        out = [r for r in self.queue if not r.done]
        out.extend(r for r in self.running.values() if not r.done)
        self.queue.clear()
        for req in out:
            self._retire(req, "cancelled",
                         error=f"exported from engine {self.name!r} "
                               "on replica ejection",
                         kind="replica")
        self.metrics.queue_depth = 0
        return out

    def prefix_probe(self, prompt_ids: Sequence[int],
                     adapter: Optional[str] = None) -> int:
        """Longest prompt prefix (in tokens) this engine's prefix cache
        already holds — side-effect-free (no LRU refresh, no counters,
        no refs).  0 for the contiguous layout or a disabled/failing
        cache; the fleet router's affinity signal.  ``adapter`` probes
        under that tenant's salt (cached KV is tenant-keyed; a base
        probe can never see adapter blocks and vice versa)."""
        if self.prefix_cache is None:
            return 0
        salt = b""
        if adapter is not None and self.adapter_pool is not None:
            try:
                salt = self.adapter_pool.salt(adapter)
            except KeyError:
                return 0                 # unloaded → no cached KV here
        try:
            return self.prefix_cache.probe(prompt_ids, salt=salt)
        except Exception:                # noqa: BLE001 — advisory only
            return 0

    # -- durability: crash recovery & weight hot-swap ----------------------

    def recover(self, journal=None, *, cross_mesh: bool = True) -> dict:
        """Crash-consistent recovery: rehydrate every non-terminal
        journaled request (admission recorded, no final end) and
        re-enqueue it as a replay-from-prompt under the stream-restart
        contract — ``recovered`` flag set, stream restarting at token
        0, the slot's device key lane re-seeded from the JOURNALED
        effective seed so greedy and seeded outputs are bitwise
        identical to an uninterrupted run.  Pre-crash terminal
        outcomes are banked into the metrics so the counters stay
        monotone across the restart.

        **Cross-mesh replay** (``cross_mesh=True``, the default): a
        request journaled at a DIFFERENT mesh shape replays here anyway
        — sharded decoding is bitwise identical across viable ``mp``
        (the tier-1 parity suite proves it), so a degraded rebuild at a
        smaller mesh serves the same tokens the original shape
        promised.  Each shape change is journaled as a ``mesh_reshard``
        record (old → new shape, per-request disposition) so
        ``audit()`` spans the degradation exactly-once.
        ``cross_mesh=False`` restores the strict contract: a
        shape-mismatched admission fails finally instead of replaying.

        Call on a fresh engine AFTER ``warmup()`` and before any
        traffic.  ``journal`` defaults to the engine's own; passing one
        here also attaches it.  Returns ``{"replayed", "requests",
        "invalid", "cross_mesh", "outcomes"}``."""
        journal = journal if journal is not None else self.journal
        if journal is None:
            raise ValueError("recover() needs a RequestJournal (pass "
                             "journal= here or to the Engine)")
        if self.running or self.queue:
            raise RuntimeError("recover() must run before serving "
                               "traffic (the journal's replay order is "
                               "the recovered queue order)")
        if self.journal is not None and journal is not self.journal:
            raise ValueError(
                "recover(journal=...) does not match the journal this "
                "engine records into — replaying one journal while "
                "recording into another leaves the replayed journal's "
                "pending set non-converging")
        self.journal = journal
        outcomes = journal.outcomes()
        self.metrics.bank_outcomes(outcomes)
        replayed, invalid = [], []
        # cross-shape dispositions, grouped by the journaled old shape:
        # one mesh_reshard record per shape spans the degradation
        cross: "OrderedDict[Optional[str], OrderedDict[str, str]]" = \
            OrderedDict()
        saved_max_queue, self.max_queue = self.max_queue, None
        try:
            for jid, rec in journal.pending().items():
                want = rec.get("mesh_shape")
                shape_changed = want != self.mesh_shape
                if shape_changed and not cross_mesh:
                    # strict mode: a request admitted sharded carries
                    # its mesh-shape key, and a recovering engine of a
                    # different shape fails that replay finally rather
                    # than serve it on a topology the journal never
                    # promised
                    journal.record_end(
                        jid, "failed", final=True,
                        error=f"recovery replay rejected: journaled "
                              f"mesh shape {want!r} != this engine's "
                              f"{self.mesh_shape!r}",
                        engine=self.name)
                    invalid.append(jid)
                    continue
                s = journal.replay_sampling(rec)
                journal.begin_attempt(jid, recovered=True,
                                      origin_wall=rec.get("wall"))
                try:
                    self._validate_replay_tenancy(rec, s)
                    r = self.add_request(
                        rec["prompt_ids"],
                        max_new_tokens=rec["max_new_tokens"],
                        sampling=SamplingParams(**s),
                        eos_token_id=rec["eos_token_id"],
                        deadline_s=rec["deadline_s"],
                        priority=rec["priority"])
                except ValueError as e:
                    # a replay this engine cannot validate (e.g. the
                    # restart shrank max_seq): fail THAT request with a
                    # final end so the journal converges instead of
                    # wedging every future recover() on the same jid —
                    # and keep replaying the rest
                    journal.record_end(jid, "failed", final=True,
                                       error=f"recovery replay "
                                             f"rejected: {e}",
                                       engine=self.name)
                    invalid.append(getattr(e, "request", None) or jid)
                    if shape_changed:
                        cross.setdefault(want, OrderedDict())[jid] = \
                            "failed"
                    continue
                finally:
                    journal.end_attempt()
                replayed.append(r)
                if shape_changed:
                    cross.setdefault(want, OrderedDict())[jid] = \
                        "replayed"
        finally:
            self.max_queue = saved_max_queue
        for old_shape, requests in cross.items():
            journal.record_mesh_reshard(
                self.name, old_shape, self.mesh_shape, requests)
        return {"replayed": len(replayed), "requests": replayed,
                "invalid": invalid,
                "cross_mesh": sum(len(v) for v in cross.values()),
                "outcomes": outcomes}

    def _validate_replay_tenancy(self, rec: dict, s: dict) -> None:
        """Bitwise-replay gate for a journaled tenant request: the
        adapter must still be loaded AT THE JOURNALED VERSION (replaying
        onto other weights would silently serve different tokens than
        the crash-interrupted run promised) and the grammar must exist.
        Raises ValueError — the caller's invalid-replay isolation path
        fails THIS request finally and keeps replaying the rest."""
        a = s.get("adapter")
        if a is not None:
            err_ctx = None
            if self.adapter_pool is None:
                err_ctx = {"adapter": a, "version": rec.get(
                    "adapter_version")}
                msg = (f"journaled adapter {a!r} but this engine has "
                       "no adapter pool")
            else:
                try:
                    _, v = self.adapter_pool.resolve(a)
                except KeyError:
                    v = None
                want = rec.get("adapter_version")
                if v is None:
                    err_ctx = {"adapter": a, "version": want}
                    msg = (f"journaled adapter {a!r} (v{want}) is not "
                           "loaded on the recovering engine")
                elif want is not None and v != want:
                    err_ctx = {"adapter": a, "version": want}
                    msg = (f"journaled adapter {a!r} v{want} != loaded "
                           f"v{v}: bitwise replay impossible")
            if err_ctx is not None:
                e = ValueError(msg)
                e.error_ctx = err_ctx
                raise e
        g = s.get("grammar")
        if g is not None:
            if self.grammar_table is None:
                raise ValueError(f"journaled grammar {g!r} but this "
                                 "engine has no grammar table")
            try:
                self.grammar_table.spec_of(g)
            except KeyError as e:
                raise ValueError(e.args[0]) from None

    def update_weights(self, state_or_path, *,
                       version: Optional[int] = None) -> int:
        """Hot-swap the model weights IN PLACE on an idle engine.

        The write goes *through* the existing parameter buffers
        (``set_state_dict`` ``_set_data`` write-through), so every
        warmed executable and its lifted state stay valid — zero new
        compile keys, pinned by the shape manifest.  The prefix-cache
        **version epoch** is bumped so no later request can prefix-hit
        KV blocks prefilled under the old weights, and
        ``model_version`` advances so every admission records which
        weights served it.

        The engine must be idle (no queued or running work): an
        in-flight request's KV was computed under the old weights and
        decoding it under new ones would serve a torn hybrid.  The
        fleet's rolling ``update_weights`` guarantees that by draining
        one replica at a time.  Accepts a state dict, an ``.npz`` path,
        or a ``distributed.checkpoint.save_state_dict`` directory.
        Returns the new version."""
        if self.running or self.queue:
            raise RuntimeError(
                f"engine {self.name!r} has in-flight work "
                f"({len(self.running)} running, {len(self.queue)} "
                "queued): drain before update_weights — decoding KV "
                "prefilled under old weights with new weights would "
                "serve a torn response")
        sd = _resolve_weights(state_or_path)
        _write_state_dict(self.model, sd)
        if self.shard is not None:
            # set_state_dict's _set_data write-through landed host
            # arrays in the parameter buffers — re-place them under
            # their TP specs so the warmed executables keep their
            # shardings (same specs as at construction: no new keys)
            self.shard.place_model(self.model)
        return self._mark_weights_swapped(version)

    def _mark_weights_swapped(self, version: Optional[int] = None) -> int:
        """The per-engine half of a weight swap — prefix-epoch bump,
        version tag, metrics/tracer/journal — split out so a fleet
        whose replicas SHARE one parameter set (the stop-the-world
        fallback) can write the state dict once and still give every
        engine its own epoch/version bookkeeping."""
        if self.prefix_cache is not None:
            self.prefix_cache.bump_epoch()
        self.model_version = (int(version) if version is not None
                              else self.model_version + 1)
        self.metrics.on_weight_swap(self.model_version)
        self.tracer.on_weight_swap(self.name, self.model_version)
        if self.journal is not None:
            self.journal.record_weight_swap(self.name, self.model_version)
        return self.model_version

    # -- multi-LoRA adapter lifecycle --------------------------------------

    def _fail_adapter_inflight(self, name: str, why: str) -> int:
        """Fail every queued and running request pinned to adapter
        ``name`` with machine-readable ``error_ctx`` — the unload /
        hot-swap contract: a lane about to be zeroed or overwritten in
        place must never keep serving a request that pinned the old
        version (that would be a torn hybrid).  Returns how many
        requests were failed."""
        v = self.adapter_pool.last_version(name)
        failed = 0
        hit = [q for q in list(self.queue)
               if q.sampling.adapter == name]
        for q in hit:
            try:
                self.queue.remove(q)
            except ValueError:
                continue                 # claimed by a concurrent path
            q.error_ctx = {"adapter": name, "version": v}
            self._retire(q, "failed",
                         error=f"adapter {name!r} {why} while queued "
                               f"(was v{v})")
            failed += 1
        for r in [r for r in list(self.running.values())
                  if r.sampling.adapter == name]:
            r.error_ctx = {"adapter": name, "version": v}
            self._retire(r, "failed",
                         error=f"adapter {name!r} {why} mid-flight "
                               f"(was v{v})")
            failed += 1
        self.metrics.queue_depth = len(self.queue)
        return failed

    def load_adapter(self, name: str, weights, *,
                     scale: float = 1.0) -> int:
        """Load (or hot-swap) LoRA adapter ``name`` into a pool lane.
        A hot swap (load over an already-loaded name) first FAILS that
        adapter's in-flight requests — the lane is overwritten in place,
        and a request that pinned the old version must not decode under
        a torn mix of both.  Bumps the name's version (retiring its old
        prefix-cache salt) and returns it."""
        if self.adapter_pool is None:
            raise RuntimeError(
                f"engine {self.name!r} has no adapter pool "
                "(construct with Engine(adapters=...))")
        if name in self.adapter_pool.loaded:
            self._fail_adapter_inflight(name, "hot-swapped")
        _lane, version = self.adapter_pool.load(name, weights,
                                                scale=scale)
        if self.shard is not None:
            # the _set_data writes landed host arrays — re-pin the lane
            # tensors under their TP specs (same specs: no new keys)
            self.shard.place_adapters(self.adapter_pool)
        self.metrics.on_adapter_load(name, version)
        self.tracer.on_adapter_load(self.name, name, version)
        return version

    def unload_adapter(self, name: str) -> int:
        """Unload adapter ``name``: fail its in-flight requests (with
        ``error_ctx = {"adapter", "version"}``), zero and free its lane.
        The name's version counter survives for a later reload, so the
        unloaded version's prefix-cache salt can never be minted again.
        Returns the unloaded version."""
        if self.adapter_pool is None:
            raise RuntimeError(
                f"engine {self.name!r} has no adapter pool "
                "(construct with Engine(adapters=...))")
        self.adapter_pool.resolve(name)  # KeyError if not loaded
        self._fail_adapter_inflight(name, "unloaded")
        version = self.adapter_pool.unload(name)
        if self.shard is not None:
            self.shard.place_adapters(self.adapter_pool)
        self.metrics.on_adapter_unload(name, version)
        self.tracer.on_adapter_unload(self.name, name, version)
        return version

    def _stop_watchdog(self) -> None:
        """Join and drop the watchdog thread so a drained/stopped engine
        holds no thread alive (its bound-method callback would otherwise
        pin the engine — model and KV cache included — forever)."""
        if self._watchdog is not None:
            self._watchdog.stop()
            self._watchdog = None

    def _paging_snapshot(self) -> dict:
        """The paged-KV observability payload (``stats()["paging"]`` and
        ``profiler.serving_paging()``): block-pool occupancy, eviction and
        copy-on-extend counters, and the prefix-cache hit counters."""
        al = self.cache.allocator.stats()
        return {
            "kv_layout": "paged",
            "kernel": self.kernel,
            "block_size": self.block_size,
            "max_blocks_per_slot": self.cache.max_blocks_per_slot,
            "blocks": al,
            "blocks_in_use": al["used"] + al["cached"],
            "copy_on_extends": self.cache.copy_on_extends,
            "prefix": (self.prefix_cache.stats()
                       if self.prefix_cache is not None else None),
        }

    def health(self) -> dict:
        """Liveness snapshot: engine state, last-step age, consecutive
        compiled-step failures, and capacity gauges — the probe a load
        balancer or the profiler surface polls.  In paged mode it also
        audits the block allocator's invariants (free + used + cached ==
        total − reserved, no negative refcounts, no slot holding a freed
        block) and flips the engine ``unhealthy`` on any violation
        instead of letting the pool corrupt silently."""
        paged_extra = {}
        if self.kv_layout == "paged":
            violations = self.cache.check_invariants()
            if violations:
                # health() may be polled from a monitor thread while the
                # scheduler is mid-way through a multi-op accounting
                # change (block popped, refcount not yet set): confirm on
                # a re-read before declaring the pool corrupt — a
                # transient snapshot clears, real corruption persists
                violations = self.cache.check_invariants()
            if violations:
                self._mark_block_corruption("; ".join(violations))
            al = self.cache.allocator.stats()
            paged_extra = {
                "kv_blocks": {k: al[k] for k in
                              ("total", "reserved", "free", "used",
                               "cached")},
                "kv_block_invariants": violations or "ok",
            }
        now = time.perf_counter()
        return {
            **paged_extra,
            "state": self.state,
            "reason": self._unhealthy_reason,
            "steps": self._step_counter,
            "last_step_age_s": None if self._last_step_t is None
            else round(now - self._last_step_t, 3),
            "consecutive_step_failures": self._consecutive_failures,
            "queue_depth": len(self.queue),
            "slots_free": len(self.free_slots),
            "slots_total": self.num_slots,
            # armed = hang detection is actually protecting future steps:
            # configured, engine still stepping, monitor thread not yet
            # fired/stopped (it is started lazily at the first step)
            "watchdog_armed": bool(
                self.step_timeout_s is not None
                and self.state in ("active", "draining")
                and (self._watchdog is None or self._watchdog.alive)),
        }

    def stats(self) -> dict:
        """``/stats``-style snapshot (also exported through
        ``paddle_tpu.profiler.serving_stats()``)."""
        self.metrics._slots_busy = len(self.running)
        self.metrics.queue_depth = len(self.queue)
        snap = self.metrics.snapshot()
        if self.adapter_pool is not None or self.grammar_table is not None:
            snap["tenancy"] = {
                "adapters": (self.adapter_pool.loaded
                             if self.adapter_pool is not None else {}),
                "adapter_lanes": (self.adapter_pool.max_adapters
                                  if self.adapter_pool is not None
                                  else 0),
                "grammars": (list(self.grammar_table.names)
                             if self.grammar_table is not None else []),
            }
        if self.shard is not None:
            snap["sharding"] = {"mesh_shape": self.mesh_shape,
                                "model_parallel": self.shard.mp}
        if self.journal is not None:
            snap["durability"]["journal"] = self.journal.stats()
        if self.sanitizer is not None:
            snap["sanitizer"] = self.sanitizer.report()
        if self.tracer.enabled:
            snap["tracing"] = self.tracer.snapshot()
        return snap
