"""Tensor-parallel sharded serving — mesh plumbing and state placement.

``Engine(mesh=serving_mesh(mp))`` turns the single-chip engine into a
model-parallel one without touching a single compiled step body.  The
pieces and why they compose (docs/SERVING.md "Sharded serving"):

- **Weights shard over the ``model`` axis for free.**  The flagship
  models are already built from the Megatron-TP layers
  (``ColumnParallelLinear`` / ``RowParallelLinear`` /
  ``VocabParallelEmbedding``), whose parameters carry ``PartitionSpec``
  annotations and whose forwards ``mark_sharding`` their activations.
  Both are inert without a mesh; :meth:`ServingShard.place_model` places
  every parameter under its spec and :meth:`ServingShard.context`
  installs the serving mesh as the global mesh for the scope of each
  compiled call, so the SAME model code the single-chip engine traces
  becomes a GSPMD tensor-parallel program.

- **The KV pool shards by ``kv_heads``.**  Both cache layouts are 5-D
  with kv_heads at dim 3 (contiguous ``[slots, layers, max_seq,
  kv_heads, head_dim]``, paged ``[blocks, layers, block_size, kv_heads,
  head_dim]``), and attention is head-batched: every contraction is
  independent per head, so a shard holding ``kv_heads/mp`` whole heads
  (GQA groups stay local — ``kv_heads % mp == 0`` is validated up
  front) runs paged/contiguous ``decode_attention`` with ZERO
  cross-shard traffic.  Only the per-layer TP collectives (row-parallel
  out-proj/fc2) cross chips.

- **Everything host-side stays replicated metadata.**  The block
  allocator, prefix cache, scheduler, journal, and the
  :class:`DeviceSampler` param/key/token lanes describe ONE logical
  decision stream driving all shards — the lanes, block tables, and
  length vectors are placed replicated (``P()``) so every shard holds
  the same values and the compiled steps read them without collectives.

- **The executable-cache key space is UNCHANGED.**  ``to_static``'s
  program cache keys on shape/dtype only, never sharding — a sharded
  engine compiles exactly the manifest's program set per mesh shape
  (``tools/shape_manifest.json`` gains one section per mesh-shape key),
  and zero steady-state recompiles carries over verbatim.

- **Mesh size 1 degenerates exactly.**  ``_filter_spec`` drops size-1
  axes, so every placement is ``P()`` and every constraint a no-op —
  ``Engine(mesh=serving_mesh(1))`` is bitwise the unsharded engine.

Placement is write-through (``_set_data`` on the existing tensors), so
it must be re-applied wherever host-side code replaces device arrays
wholesale: after ``warmup()``'s state reset and after
``update_weights``'s state-dict write — :meth:`ServingShard.place_state`
/ :meth:`ServingShard.place_model` are idempotent re-pinning calls, not
one-shot constructors.

CPU tier-1 verifies all of this on a host-device mesh
(``XLA_FLAGS=--xla_force_host_platform_device_count``), the same trick
the TP training tests use.
"""
from __future__ import annotations

from contextlib import contextmanager
from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, PartitionSpec as P

from ..distributed import mesh as mesh_mod
from ..distributed.sharding_spec import (
    MODEL_AXIS, _divisible, _filter_spec, place_array,
)

__all__ = ["ServingShard", "serving_mesh", "mesh_shape_key",
           "viable_ladder", "degrade_step", "KV_POOL_SPEC"]

#: KV pools are 5-D with kv_heads at dim 3 in BOTH layouts:
#: contiguous ``[slots, layers, max_seq, kv_heads, head_dim]`` and
#: paged ``[blocks, layers, block_size, kv_heads, head_dim]`` — heads
#: split over the model axis, every other dim (and the block tables /
#: lengths / sampler lanes) replicated.
KV_POOL_SPEC = P(None, None, None, MODEL_AXIS, None)


def serving_mesh(model_parallel: int,
                 devices: Optional[Sequence] = None) -> Mesh:
    """A one-axis serving mesh ``{"model": mp}`` over ``devices``
    (default: the first ``mp`` of ``jax.devices()``).

    The serving mesh deliberately carries ONLY the model axis: batch
    ("data"/"sharding") and sequence ("sep") constraints inside the
    model forwards filter to no-ops, so a serving step is pure TP —
    the fleet provides data parallelism as shard *groups*, one engine
    per group, each on its own disjoint mesh.
    """
    mp = int(model_parallel)
    if mp < 1:
        raise ValueError(f"serving_mesh: model_parallel must be >= 1, "
                         f"got {model_parallel}")
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if len(devices) < mp:
        raise ValueError(
            f"serving_mesh: model_parallel={mp} needs {mp} devices, "
            f"have {len(devices)} (on CPU set XLA_FLAGS="
            f"--xla_force_host_platform_device_count before jax import)")
    return mesh_mod.build_mesh({MODEL_AXIS: mp}, devices[:mp])


def viable_ladder(kv_heads: int, num_heads: int,
                  max_mp: Optional[int] = None) -> list:
    """The ascending list of viable model-parallel degrees for a model:
    every ``mp`` with ``mp | kv_heads`` AND ``mp | num_heads`` (the same
    two divisibility rules :class:`ServingShard` enforces), optionally
    capped at ``max_mp``.  ``1`` is always viable — the degraded-mode
    floor is the unsharded engine.

    This is the **viability ladder** degraded serving walks down: when a
    shard group loses devices, the fleet rebuilds it at the LARGEST
    rung that still fits on the survivors (:func:`degrade_step`)."""
    kv, nh = int(kv_heads), int(num_heads)
    if kv < 1 or nh < 1:
        raise ValueError(f"viable_ladder: kv_heads={kv_heads} and "
                         f"num_heads={num_heads} must be >= 1")
    top = min(kv, nh) if max_mp is None else int(max_mp)
    return [mp for mp in range(1, top + 1)
            if kv % mp == 0 and nh % mp == 0]


def degrade_step(kv_heads: int, num_heads: int,
                 survivors: int) -> Optional[int]:
    """The largest viable ``mp'`` that fits on ``survivors`` devices —
    the degraded-rebuild target after a shard group loses devices.
    ``None`` when not even ``mp'=1`` fits (zero survivors): the group
    is dead until hardware returns."""
    ladder = viable_ladder(kv_heads, num_heads, max_mp=survivors)
    return ladder[-1] if ladder else None


def mesh_shape_key(mesh: Optional[Mesh]) -> Optional[str]:
    """Canonical string for a mesh's SHAPE (``"model=2"``) — the key the
    journal records per admission, recovery validates against, and the
    shape manifest sections on.  Device identities are deliberately NOT
    part of the key: recovery replays bitwise onto any mesh of the same
    shape (a restart rarely gets the same physical chips)."""
    if mesh is None:
        return None
    return ",".join(f"{name}={mesh.shape[name]}"
                    for name in mesh.axis_names)


class ServingShard:
    """One engine's sharding plan: the mesh, its shape key, and the
    idempotent placement of every piece of lifted device state."""

    def __init__(self, mesh: Mesh, *, kv_heads: int, num_heads: int):
        if MODEL_AXIS not in mesh.shape:
            raise ValueError(
                f"Engine(mesh=...) needs a '{MODEL_AXIS}' axis, got "
                f"axes {tuple(mesh.axis_names)} (build it with "
                f"serving.sharding.serving_mesh)")
        self.mesh = mesh
        self.mp = int(mesh.shape[MODEL_AXIS])
        self.key = mesh_shape_key(mesh)
        if self.mp > 1 and int(kv_heads) % self.mp != 0:
            raise ValueError(
                f"model axis size {self.mp} must divide kv_heads "
                f"{kv_heads}: the KV pool shards whole GQA groups so "
                f"decode attention stays shard-local")
        if self.mp > 1 and int(num_heads) % self.mp != 0:
            raise ValueError(
                f"model axis size {self.mp} must divide "
                f"num_attention_heads {num_heads}")

    @contextmanager
    def context(self):
        """Install the serving mesh as the GLOBAL mesh for the scope of
        one compiled call and restore whatever was there.  The model
        forwards' ``mark_sharding`` and the TP layers read the global
        mesh — the save/restore keeps a sharded engine from leaking its
        mesh into co-resident engines (fleet shard groups each carry a
        DIFFERENT device subset) or the training stack."""
        prev = mesh_mod.get_global_mesh()
        mesh_mod.set_global_mesh(self.mesh)
        try:
            yield
        finally:
            mesh_mod.set_global_mesh(prev)

    # -- placement (idempotent, write-through) ----------------------------

    def _pin(self, t, spec: P = P()) -> None:
        """(Re-)place one state tensor under ``spec`` on this mesh,
        writing through ``_set_data`` so the compiled steps' lifted
        state keeps pointing at the same Tensor objects."""
        arr = t._value()
        fspec = _filter_spec(spec, self.mesh)
        if not _divisible(arr.shape, fspec, self.mesh):
            fspec = P()
        t._set_data(place_array(arr, self.mesh, fspec))

    def place_model(self, model) -> None:
        """Place every parameter/buffer under its Megatron-TP spec
        (unannotated ones replicate).  Re-run after any state-dict
        write-through (``update_weights``): ``_set_data`` with a host
        array resets placement to single-device."""
        from ..distributed.fleet.meta_parallel.tensor_parallel import (
            place_parameters,
        )
        with self.context():
            place_parameters(model, self.mesh)

    def place_cache(self, cache) -> None:
        """KV pool k/v shard on the kv_heads dim; lengths (and the paged
        block tables) replicate — they are host-driven metadata every
        shard must agree on."""
        self._pin(cache.k, KV_POOL_SPEC)
        self._pin(cache.v, KV_POOL_SPEC)
        self._pin(cache.lengths)
        bt = getattr(cache, "block_tables", None)
        if bt is not None:
            self._pin(bt)

    def place_sampler(self, sampler) -> None:
        """All sampling lanes replicate: one logical decision stream
        drives all shards (the lanes are values, never shapes).  The
        tenancy lanes (grammar id/state) ride the same placement, as do
        the grammar DFA tables — tiny, read-only, identical per shard."""
        for lane in (sampler.keys, sampler.temps, sampler.top_ks,
                     sampler.top_ps, sampler.tokens,
                     sampler.grammar_ids, sampler.grammar_states):
            self._pin(lane)
        if sampler.grammar is not None:
            self._pin(sampler.grammar.trans)
            self._pin(sampler.grammar.mask)

    def place_adapters(self, pool) -> None:
        """Adapter factors shard over the model axis exactly like the
        weights they modify: a column target (out-dim sharded) shards
        ``B``'s out dim, a row target (in-dim sharded) shards ``A``'s
        in dim; the other factor and the slot id lane replicate.
        Re-run after every ``load``/``unload`` — their ``_set_data``
        writes land host arrays (same write-through contract as
        ``update_weights``/``place_model``)."""
        for bank in pool.banks.values():
            if bank.kind == "column":
                self._pin(bank.A)
                self._pin(bank.B, P(None, None, MODEL_AXIS))
            else:
                self._pin(bank.A, P(None, MODEL_AXIS, None))
                self._pin(bank.B)
        self._pin(pool.adapter_ids)

    def place_state(self, engine) -> None:
        """(Re-)place every piece of lifted device state the compiled
        steps close over — the target cache and sampler plus, with
        speculation on, the draft model/cache/sampler and the proposals
        lane, and, with tenancy on, the adapter lanes.  Called at
        construction and again after ``warmup()``'s reset (which
        replaces the arrays with fresh host zeros)."""
        self.place_cache(engine.cache)
        self.place_sampler(engine.sampler)
        pool = getattr(engine, "adapter_pool", None)
        if pool is not None:
            self.place_adapters(pool)
        spec = getattr(engine, "spec", None)
        if spec is not None:
            self.place_model(spec.model)
            # the draft's contiguous cache shards by ITS kv_heads when
            # divisible; _pin falls back to replicated otherwise (a
            # draft is small by construction — replicating it is the
            # documented degradation, not an error)
            self.place_cache(spec.cache)
            self.place_sampler(spec.sampler)
            self._pin(spec.proposals)
