"""Multi-LoRA serving: per-request low-rank adapter lanes.

One deployment serves N fine-tunes of one base model (ROADMAP item 4a).
The design rides the same economics as every other per-request knob in
the serving stack (docs/SERVING.md "Multi-tenant serving"):

- **Which adapter a slot decodes under is data, never a trace constant.**
  An :class:`AdapterPool` stacks up to ``max_adapters`` adapters' (A, B)
  factor pairs into per-target-linear device lanes —
  ``A [L, in, rank]`` / ``B [L, rank, out]`` with lane 0 the reserved
  all-zero *base* adapter — plus one ``adapter_ids [slots] int32`` lane.
  All of it is persistable lifted state (like the KV cache and sampler
  lanes), so one compiled prefill/decode/verify program serves every
  tenant and adding the pool changes ZERO executable-cache keys.
- **The low-rank math lives inside the compiled step.**  Each
  tensor-parallel linear (the Megatron Column/Row layers every GPT/Llama
  projection is built from) gathers its slot's factor pair and adds
  ``scale * (x @ A) @ B`` to its output in-graph.  A pure add would
  break bitwise base parity for lane 0 (``-0.0 + 0.0 == +0.0``), so the
  hook selects: ``where(adapter_id > 0, out + delta, out)`` — slots on
  the base adapter are bitwise untouched.
- **Host side is a tiny registry.**  ``load``/``unload``/hot-swap write
  lane rows through ``_set_data`` between steps (value-only, never a
  shape).  Each adapter *name* carries a monotonically increasing
  **version** (bumped on every load of that name, surviving unload), and
  ``salt(name) == b"name@vN"`` feeds the prefix cache's chain-hash root
  so tenant KV never cross-hits another tenant — or a stale version of
  itself — by construction.

Sharding (serving.sharding.ServingShard): adapter factors shard over the
``model`` mesh axis exactly like the weights they modify — a column
target (out-dim sharded) shards ``B``'s out dim, a row target (in-dim
sharded) shards ``A``'s in dim; the id lane replicates.

Deliberately NOT supported: per-slot adapter *rank* (lanes are one
stacked shape; rank is a pool constant), adapters on the draft model
(speculative acceptance prices the real draft law, so an un-adapted
draft only costs acceptance rate, never correctness), and adapters on
embeddings / lm_head (target set = the Column/Row projections).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["AdapterConfig", "AdapterPool", "make_lora_weights"]


@dataclass
class AdapterConfig:
    """Engine-facing pool sizing: how many concurrently loaded adapters
    (``max_adapters`` — lane 0 is the base model and does not count) at
    which low-rank width (``rank``, one pool-wide constant: the stacked
    lanes have ONE shape)."""

    max_adapters: int = 4
    rank: int = 4

    def __post_init__(self):
        if self.max_adapters < 1:
            raise ValueError("max_adapters must be >= 1")
        if self.rank < 1:
            raise ValueError("rank must be >= 1")


@dataclass
class _Bank:
    """One target linear's stacked factors."""

    key: str                  # model path of the target layer
    kind: str                 # "column" | "row" (which factor shards)
    in_features: int
    out_features: int
    A: Tensor                 # [L, in, rank]
    B: Tensor                 # [L, rank, out]


class _LoraHook:
    """Installed as ``layer.lora``; called by the Column/Row forward as
    ``out = hook(x, out)``.  Outside an engine step (no staged row ids)
    it is the identity — direct model calls never see adapter math."""

    def __init__(self, pool: "AdapterPool", key: str):
        self._pool = pool
        self._key = key

    def __call__(self, x, out):
        rows = self._pool._rows
        if rows is None:
            return out
        bank = self._pool.banks[self._key]
        xv, ov = x._value(), out._value()
        A = bank.A._value()[rows]                      # [b, in, rank]
        B = bank.B._value()[rows]                      # [b, rank, out]
        delta = jnp.einsum("bsr,bro->bso",
                           jnp.einsum("bsi,bir->bsr", xv, A), B)
        keep = (rows > 0)[:, None, None]
        return Tensor._wrap(jnp.where(keep, ov + delta, ov))


class AdapterPool:
    """Stacked per-target LoRA lanes + the per-slot adapter-id lane.

    Built against a target model: every ``ColumnParallelLinear`` /
    ``RowParallelLinear`` sublayer becomes a target and gets a
    :class:`_LoraHook` installed.  ``num_slots`` sizes the id lane.
    """

    def __init__(self, model, num_slots: int, *, max_adapters: int = 4,
                 rank: int = 4, dtype=None):
        from ..distributed.fleet.meta_parallel.parallel_layers.mp_layers \
            import ColumnParallelLinear, RowParallelLinear

        self.num_slots = int(num_slots)
        self.max_adapters = int(max_adapters)
        self.rank = int(rank)
        self.num_lanes = self.max_adapters + 1        # lane 0 = base
        if dtype is None:
            params = model.parameters()
            dtype = params[0].dtype if params else "float32"
        self.dtype = dtype
        self.banks: Dict[str, _Bank] = {}
        for path, layer in model.named_sublayers():
            if isinstance(layer, ColumnParallelLinear):
                kind = "column"
            elif isinstance(layer, RowParallelLinear):
                kind = "row"
            else:
                continue
            A = Tensor._wrap(jnp.zeros(
                (self.num_lanes, layer._in_features, self.rank),
                dtype=jnp.dtype(dtype)))
            B = Tensor._wrap(jnp.zeros(
                (self.num_lanes, self.rank, layer._out_features),
                dtype=jnp.dtype(dtype)))
            A.persistable = True
            B.persistable = True
            self.banks[path] = _Bank(path, kind, layer._in_features,
                                     layer._out_features, A, B)
            layer.lora = _LoraHook(self, path)
        if not self.banks:
            raise ValueError(
                "AdapterPool found no ColumnParallelLinear/"
                "RowParallelLinear targets in the model")
        self.adapter_ids = Tensor._wrap(
            jnp.zeros((self.num_slots,), dtype=jnp.int32))
        self.adapter_ids.persistable = True
        #: traced per-call row ids ([1] prefill / [slots] decode+verify);
        #: set by the engine's step closures around the model call,
        #: None outside a step (the hooks are then the identity)
        self._rows = None
        self._registry: Dict[str, int] = {}           # name -> lane
        self._versions: Dict[str, int] = {}           # name -> version
        self._free = list(range(1, self.num_lanes))

    # -- registry ----------------------------------------------------------

    @property
    def loaded(self) -> Dict[str, int]:
        """name -> current version, for every loaded adapter."""
        return {n: self._versions[n] for n in self._registry}

    def resolve(self, name: str) -> Tuple[int, int]:
        """``(lane, version)`` of a loaded adapter; KeyError if not."""
        try:
            lane = self._registry[name]
        except KeyError:
            raise KeyError(
                f"adapter {name!r} is not loaded (loaded: "
                f"{sorted(self._registry)})") from None
        return lane, self._versions[name]

    def last_version(self, name: str) -> int:
        """Latest version this pool ever assigned ``name`` (0 if never
        loaded) — survives unload, for machine-readable error context."""
        return self._versions.get(name, 0)

    def salt(self, name: Optional[str]) -> bytes:
        """Prefix-cache tenant salt: b"" for the base model, else
        ``b"name@vN"`` — folded into the chain-hash root so tenant KV
        never cross-hits across adapters OR versions."""
        if name is None:
            return b""
        lane, version = self.resolve(name)
        return f"{name}@v{version}".encode()

    def load(self, name: str, weights: Dict[str, tuple], *,
             scale: float = 1.0) -> Tuple[int, int]:
        """Load (or hot-swap) adapter ``name`` from ``weights``: a dict
        mapping every target path to its ``(A [in, rank], B [rank, out])``
        pair.  ``scale`` is folded into B at write time.  Returns
        ``(lane, version)``; the version bumps on every load of the same
        name (including load-over-loaded hot swaps), which retires the
        old version's prefix-cache salt."""
        missing = sorted(set(self.banks) - set(weights))
        extra = sorted(set(weights) - set(self.banks))
        if missing or extra:
            raise ValueError(
                f"adapter {name!r} weights do not cover the target set "
                f"(missing={missing[:3]}, unexpected={extra[:3]})")
        if name in self._registry:
            lane = self._registry[name]
        else:
            if not self._free:
                raise RuntimeError(
                    f"adapter pool is full ({self.max_adapters} lanes; "
                    f"loaded: {sorted(self._registry)}) — unload one "
                    "first")
            lane = self._free.pop(0)
        for key, bank in self.banks.items():
            A, B = weights[key]
            A = jnp.asarray(np.asarray(A), dtype=jnp.dtype(self.dtype))
            B = jnp.asarray(np.asarray(B),
                            dtype=jnp.dtype(self.dtype)) * float(scale)
            if A.shape != bank.A._value().shape[1:] or \
                    B.shape != bank.B._value().shape[1:]:
                raise ValueError(
                    f"adapter {name!r} target {key!r}: want A "
                    f"{bank.A._value().shape[1:]} / B "
                    f"{bank.B._value().shape[1:]}, got {A.shape} / "
                    f"{B.shape}")
            bank.A._set_data(bank.A._value().at[lane].set(A))
            bank.B._set_data(bank.B._value().at[lane].set(B))
        self._registry[name] = lane
        self._versions[name] = self._versions.get(name, 0) + 1
        return lane, self._versions[name]

    def unload(self, name: str) -> int:
        """Unload ``name``: zero its lane (so a stale id could only ever
        reproduce the base model, never another tenant) and free it.
        Returns the unloaded version; the name's version counter
        survives for a later reload."""
        lane, version = self.resolve(name)
        for bank in self.banks.values():
            bank.A._set_data(bank.A._value().at[lane].set(0.0))
            bank.B._set_data(bank.B._value().at[lane].set(0.0))
        del self._registry[name]
        self._free.append(lane)
        self._free.sort()
        return version

    # -- per-slot staging (host, between steps) ----------------------------

    def stage_slot(self, slot: int, name: Optional[str]) -> None:
        """Write one slot's adapter lane id (admission and
        preempt-resume both land here).  KeyError if ``name`` is no
        longer loaded — the engine turns that into a machine-readable
        request failure."""
        lane = 0 if name is None else self.resolve(name)[0]
        self.adapter_ids._set_data(
            self.adapter_ids._value().at[slot].set(jnp.int32(lane)))

    def reset_slots(self) -> None:
        """Forget per-slot ids (warmup scribbles slot 0); loaded banks
        survive — adapters loaded before warmup stay served."""
        self.adapter_ids._set_data(
            jnp.zeros((self.num_slots,), dtype=jnp.int32))

    # -- traced row binding (inside the step closures) ---------------------

    def set_rows(self, rows) -> None:
        self._rows = rows

    def clear_rows(self) -> None:
        self._rows = None


def make_lora_weights(pool: AdapterPool, seed: int = 0,
                      init_scale: float = 0.02) -> Dict[str, tuple]:
    """Random full-coverage adapter weights for ``pool`` (tests/bench):
    both factors drawn ``N(0, init_scale)`` — deliberately NOT the
    classic B=0 training init, so the adapter visibly changes outputs."""
    rng = np.random.default_rng(seed)
    out = {}
    for key, bank in pool.banks.items():
        out[key] = (
            rng.normal(0.0, init_scale,
                       (bank.in_features, pool.rank)).astype(np.float32),
            rng.normal(0.0, init_scale,
                       (pool.rank, bank.out_features)).astype(np.float32),
        )
    return out
