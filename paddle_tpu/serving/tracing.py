"""Request-lifecycle tracing: the per-request story the aggregate
metrics cannot tell.

``ServingMetrics`` says *how many* requests were preempted and what the
p99 TTFT was; it cannot say that request 17 was admitted into slot 2 on
replica 0, preempted by a high-priority arrival, resumed as a prefix
hit, orphaned when replica 0 was ejected, redispatched to replica 2,
and retired 400 ms late.  :class:`RequestTracer` records exactly that
story as a span/event chain — the Dapper-style lifecycle capture the
serving literature treats as table stakes — for every request moving
through an :class:`~.engine.Engine` or a :class:`~.router.Fleet`:

``submitted → queued → admitted(bucket, slot) → decode steps (batched,
one event per engine step, not per token) → retired(state)``

with *linked* spans for ``preempt``/resume, ``shed``, ``redispatch``,
and fleet ``eject``/``rebuild`` — a preempted or redispatched request's
next attempt is a child span of the interrupted one, so the whole
multi-replica story reconstructs from parent pointers alone.

House invariants, enforced by construction:

- **Pure host-side bookkeeping.**  Nothing here ever touches a traced
  value or enters a compiled program: events record ints/floats the
  scheduler already holds, so tracing adds ZERO executable-cache keys
  (the shape manifest stays byte-identical) and no device→host syncs
  (zero new tpulint suppressions).
- **Monotonic clock.**  Every event is stamped from
  ``time.perf_counter()`` relative to the tracer's start; a wall-clock
  anchor pair is captured once so *exporters* can emit wall-clock
  timestamps without any event ever doing latency math on
  ``time.time()`` (which can step backwards).
- **Near-zero overhead when off.**  The engine's default tracer is the
  module-level :data:`NULL_TRACER` (every method a no-op, ``enabled``
  False so hot-path call sites skip even argument construction); opt in
  per engine/fleet (``tracer=RequestTracer()``) or process-wide via
  ``PADDLE_TPU_TRACE=1``.
- **Bounded memory.**  At most ``max_events`` events are retained; past
  the cap events are counted as ``dropped`` (and the chain validator
  refuses to certify a trace with drops).

:class:`FlightRecorder` is the always-on companion: a bounded ring
buffer of the last N engine-step summaries, dumped automatically when
``health()`` flips unhealthy or the fleet ejects the replica — the
post-mortem the aggregate counters cannot provide, surfaced via
``profiler.flight_record()`` and attached to the fleet's rebuild
record.  The recorder itself now lives in the shared observability
layer (:mod:`paddle_tpu.obs.flight` — the training runtime's
divergence sentry feeds one too) and is re-exported here so serving
imports keep working.

Exporters live in :mod:`paddle_tpu.obs` (Chrome/Perfetto trace JSON,
JSONL event log, metrics text exposition); :func:`validate_trace` is
the chain validator the bench and the chaos tests run.
"""
from __future__ import annotations

import itertools
import os
import time
import weakref
from typing import Dict, List, Optional

from ..obs.flight import FlightRecorder  # noqa: F401  (re-export)

__all__ = ["RequestTracer", "NullTracer", "NULL_TRACER", "FlightRecorder",
           "validate_trace", "TERMINAL_SPAN_STATES", "live_tracers"]

#: weak registry of every live enabled tracer — the crash-dump path
#: (:mod:`paddle_tpu.obs.crashdump`) persists armed traces before a
#: hard process exit, and must find them without holding them alive
_LIVE_TRACERS = weakref.WeakSet()


def live_tracers():
    """Every live :class:`RequestTracer` in the process (weakly held,
    registration order not guaranteed) — the crash-dump surface."""
    return list(_LIVE_TRACERS)

#: States an attempt span may legally end in.  ``preempted`` and
#: ``exported`` are *non-final* ends — the request continues on a child
#: span; everything else ends the attempt for good.
TERMINAL_SPAN_STATES = frozenset({
    "finished", "failed", "cancelled", "rejected", "preempted",
    "exported"})


def _noop(*_args, **_kwargs) -> None:
    return None


class NullTracer:
    """The disabled tracer: every hook a no-op, ``enabled`` False so
    hot-path call sites (the per-step decode event) skip argument
    construction entirely.  One shared instance (:data:`NULL_TRACER`)
    serves every untraced engine — tracing off costs one attribute read
    per lifecycle edge and nothing per decode step."""

    enabled = False
    events: tuple = ()
    dropped = 0

    def __getattr__(self, _name):
        return _noop


#: The shared disabled tracer every Engine/Fleet defaults to.
NULL_TRACER = NullTracer()


class RequestTracer:
    """Host-side span/event recorder for serving request lifecycles.

    One tracer may be shared by a whole fleet (every replica engine
    plus the router): events carry the replica (engine name), spans
    carry parent pointers, and request identity is a ``trace`` id —
    fleet-rooted (``"<fleet>:f<id>"``) when the router submitted the
    request, engine-local (``"<engine>:r<id>"``) otherwise.

    The scheduler is single-threaded, so no locking is needed; the only
    cross-thread writer is the watchdog's ``unhealthy`` event, and
    ``list.append`` is atomic under the GIL.

    Args:
        max_events: retention bound; events past it are dropped (and
            counted — :func:`validate_trace` fails on any drop).
    """

    enabled = True

    def __init__(self, max_events: int = 200_000):
        if max_events < 1:
            raise ValueError(f"max_events must be >= 1, got {max_events}")
        self.max_events = int(max_events)
        #: monotonic origin; every event ``ts`` is seconds since this
        self.t0 = time.perf_counter()
        #: wall-clock anchor captured ONCE for exporters — events
        #: themselves never carry (or compute with) wall-clock time
        self.wall0 = time.time()
        self.events: List[dict] = []
        self.dropped = 0
        self.spans: Dict[int, dict] = {}
        self._span_ids = itertools.count(1)
        # live-request bookkeeping (weak: a tracer must never keep a
        # retired request — or its engine — alive)
        self._req_span = weakref.WeakKeyDictionary()    # Request -> span
        self._req_trace = weakref.WeakKeyDictionary()   # Request -> trace
        self._root_span = weakref.WeakKeyDictionary()   # FleetRequest -> span
        self._last_attempt = weakref.WeakKeyDictionary()  # FleetRequest -> sp
        #: trace ids rooted by a fleet submit: engine-level retires on
        #: them are span ends, not trace terminals (the fleet's
        #: ``_finish`` emits the one final event)
        self._fleet_traces: set = set()
        #: pending adoption set by the router around one add_request
        #: call: ``(fleet_request, trace_id, parent_span)``
        self._pending = None
        _LIVE_TRACERS.add(self)

    # -- construction -------------------------------------------------------

    @classmethod
    def from_env(cls) -> Optional["RequestTracer"]:
        """The env-armed tracer (``PADDLE_TPU_TRACE=1``), or None when
        tracing is off (the default: the engine falls back to
        :data:`NULL_TRACER`)."""
        v = os.environ.get("PADDLE_TPU_TRACE", "").strip().lower()
        if v in ("", "0", "false", "off", "no"):
            return None
        if v in ("1", "true", "on", "yes"):
            return cls()
        raise ValueError(f"PADDLE_TPU_TRACE={v!r}: expected 1/on to "
                         "enable or 0/off to disable")

    # -- core recording -----------------------------------------------------

    def _now(self) -> float:
        return time.perf_counter() - self.t0

    def _event(self, kind: str, trace: Optional[str] = None,
               span: Optional[int] = None, replica: Optional[str] = None,
               **attrs) -> None:
        if len(self.events) >= self.max_events:
            self.dropped += 1
            return
        ev = {"ts": self._now(), "kind": kind}
        if trace is not None:
            ev["trace"] = trace
        if span is not None:
            ev["span"] = span
        if replica is not None:
            ev["replica"] = replica
        if attrs:
            ev.update(attrs)
        self.events.append(ev)

    def _begin_span(self, trace: str, name: str,
                    parent: Optional[int] = None,
                    replica: Optional[str] = None) -> int:
        sid = next(self._span_ids)
        if len(self.spans) >= self.max_events:
            # span table shares the event budget: past the capture
            # window nothing is recorded (and the validator refuses to
            # certify a capped tracer via the drop counter)
            self.dropped += 1
            return sid
        self.spans[sid] = {"id": sid, "trace": trace, "name": name,
                           "parent": parent, "replica": replica,
                           "slot": None, "t_start": self._now(),
                           "t_end": None, "state": None}
        return sid

    def _end_span(self, sid: Optional[int], state: str) -> None:
        sp = self.spans.get(sid)
        if sp is not None and sp["t_end"] is None:
            sp["t_end"] = self._now()
            sp["state"] = state

    def _attempt_span_for(self, req, replica: str) -> int:
        """The request's current attempt span, created lazily (a
        rejection can be the first thing the tracer hears about a
        request).  Consumes the router's pending adoption, so an
        attempt created inside a fleet dispatch joins the fleet trace
        with the right parent."""
        sid = self._req_span.get(req)
        if sid is not None:
            return sid
        parent = None
        if self._pending is not None:
            _freq, trace, parent = self._pending
        else:
            trace = f"{replica}:r{req.request_id}"
        sid = self._begin_span(trace, "attempt", parent=parent,
                               replica=replica)
        self._req_span[req] = sid
        self._req_trace[req] = trace
        if self._pending is not None:
            self._last_attempt[self._pending[0]] = sid
        return sid

    # -- engine-facing hooks ------------------------------------------------

    def on_queued(self, req, replica: str) -> None:
        sid = self._attempt_span_for(req, replica)
        self._event("queued", trace=self._req_trace.get(req), span=sid,
                    replica=replica, request_id=req.request_id,
                    prompt_len=int(req.prompt_ids.size),
                    priority=req.priority,
                    preemptions=req.preemptions,
                    tenant=getattr(req, "tenant", "base"))

    def on_shed(self, req, replica: str, wait_s: float) -> None:
        sid = self._attempt_span_for(req, replica)
        self._event("shed", trace=self._req_trace.get(req), span=sid,
                    replica=replica, request_id=req.request_id,
                    estimated_wait_s=round(wait_s, 6),
                    deadline_s=req.deadline_s)

    def on_admitted(self, req, replica: str, bucket: int, slot: int,
                    prefix_hit: int = 0) -> None:
        sid = self._attempt_span_for(req, replica)
        sp = self.spans.get(sid)
        if sp is not None:
            sp["slot"] = slot
        self._event("admitted", trace=self._req_trace.get(req), span=sid,
                    replica=replica, request_id=req.request_id,
                    bucket=bucket, slot=slot, prefix_hit=prefix_hit,
                    tenant=getattr(req, "tenant", "base"))

    def on_decode_step(self, replica: str, step: int, slots,
                       dt_s: float) -> None:
        """ONE event per engine step (not per token): the slots that
        decoded this step and the step latency."""
        self._event("decode_step", replica=replica, step=step,
                    slots=list(slots), n_active=len(slots),
                    dt_ms=round(dt_s * 1e3, 3))

    def on_verify_step(self, replica: str, step: int, slots,
                       dt_s: float, *, proposed: int,
                       accepted: int) -> None:
        """The speculative variant of :meth:`on_decode_step`: ONE event
        per engine ROUND (k draft steps + one verify step, never one
        per token or per draft step), carrying the round's (proposed,
        accepted) draft-token pair — the acceptance story per round,
        rendered by the Perfetto exporter as an ``accepted_tokens``
        counter track next to ``active_slots``."""
        self._event("verify_step", replica=replica, step=step,
                    slots=list(slots), n_active=len(slots),
                    dt_ms=round(dt_s * 1e3, 3),
                    proposed=int(proposed), accepted=int(accepted))

    def on_retired(self, req, replica: str, state: str,
                   error: Optional[str] = None) -> None:
        """Terminal (engine-level) transition.  Final for the trace
        unless the trace is fleet-rooted — there, the router's
        ``_finish`` emits the single final event, and an engine retire
        (export on ejection included) only ends the attempt span."""
        sid = self._attempt_span_for(req, replica)
        trace = self._req_trace.get(req)
        final = trace not in self._fleet_traces
        end_state = state
        if not final and state == "cancelled" \
                and getattr(req, "error_kind", "request") == "replica":
            end_state = "exported"       # the fleet will replay it
        self._end_span(sid, end_state)
        self._event("retired", trace=trace, span=sid, replica=replica,
                    request_id=req.request_id, state=state, final=final,
                    n_tokens=len(req.output_ids),
                    **({"error": error} if error else {}))

    def on_preempt(self, victim, replica: str) -> None:
        """End the victim's attempt span (``preempted``) and open the
        linked resume span — the child the re-admission and final
        retirement will ride."""
        sid = self._attempt_span_for(victim, replica)
        trace = self._req_trace.get(victim)
        self._end_span(sid, "preempted")
        resume = self._begin_span(trace, "resume", parent=sid,
                                  replica=replica)
        self._req_span[victim] = resume
        self._event("preempt", trace=trace, span=sid, replica=replica,
                    request_id=victim.request_id, resume_span=resume,
                    preemptions=victim.preemptions)

    def on_block_pressure(self, req, replica: str, kind: str = "defer",
                          **attrs) -> None:
        """Paged-pool pressure on this request's admission or decode
        (``defer`` / ``pool_exhausted``)."""
        sid = self._req_span.get(req)
        self._event("block_pressure", trace=self._req_trace.get(req),
                    span=sid, replica=replica, request_id=req.request_id,
                    pressure=kind, **attrs)

    def on_unhealthy(self, replica: str, reason: str) -> None:
        self._event("unhealthy", replica=replica, reason=reason)

    def on_recovered(self, req, replica: str,
                     origin_wall: Optional[float] = None,
                     journal_id: Optional[str] = None) -> None:
        """A crash-recovery replay re-admitted this request from the
        journal.  The attempt span is its cross-process *resume span*;
        the link back to the pre-crash attempt is WALL-anchored
        (``origin_wall`` = the journaled original admission's wall
        stamp) because monotonic clocks do not survive a restart — the
        Perfetto exporter renders it as a flow arrow from a synthetic
        pre-crash instant into this span."""
        sid = self._attempt_span_for(req, replica)
        sp = self.spans.get(sid)
        if sp is not None:
            sp["recovered"] = True
        self._event("recovered", trace=self._req_trace.get(req), span=sid,
                    replica=replica, request_id=req.request_id,
                    journal_id=journal_id,
                    **({"origin_wall": round(origin_wall, 6)}
                       if origin_wall is not None else {}))

    def on_weight_swap(self, replica: str, version: int) -> None:
        """One replica finished its drain-and-swap: every admission on
        it from here serves model ``version``."""
        self._event("weight_swap", replica=replica, version=version)

    def on_adapter_load(self, replica: str, adapter: str,
                        version: int) -> None:
        """A LoRA adapter was loaded (or hot-swapped) into this
        replica's pool; admissions naming it serve ``version`` now."""
        self._event("adapter_load", replica=replica, adapter=adapter,
                    version=version)

    def on_adapter_unload(self, replica: str, adapter: str,
                          version: int) -> None:
        self._event("adapter_unload", replica=replica, adapter=adapter,
                    version=version)

    def on_weight_roll(self, fleet: str, version: int,
                       roll_s: float, replicas: int) -> None:
        """The fleet-level rolling update completed end to end."""
        self._event("weight_roll", replica=fleet, version=version,
                    roll_ms=round(roll_s * 1e3, 3), replicas=replicas)

    # -- fleet-facing hooks -------------------------------------------------

    def on_submitted(self, freq, fleet: str) -> None:
        trace = f"{fleet}:f{freq.request_id}"
        sid = self._begin_span(trace, "request")
        self._req_trace[freq] = trace
        self._root_span[freq] = sid
        if len(self._fleet_traces) < self.max_events:
            # shares the event budget (bounded memory): past the cap
            # nothing about the submit was recorded anyway — the drop
            # counter has already voided the capture
            self._fleet_traces.add(trace)
        self._event("submitted", trace=trace, span=sid,
                    request_id=freq.request_id,
                    prompt_len=int(freq.prompt_ids.size))

    def begin_attempt(self, freq, replica: str) -> None:
        """Arm the adoption window around ONE ``engine.add_request``
        call: the attempt span the engine creates inside it joins this
        fleet trace, parented on the previous attempt (the redispatch
        chain) or the root."""
        trace = self._req_trace.get(freq)
        if trace is None:                # tracer attached mid-flight
            return
        parent = self._last_attempt.get(freq) or self._root_span.get(freq)
        self._pending = (freq, trace, parent)

    def end_attempt(self) -> None:
        self._pending = None

    def on_dispatch(self, freq, replica: str, redispatch: bool = False,
                    affinity: int = 0) -> None:
        self._event("redispatch" if redispatch else "dispatch",
                    trace=self._req_trace.get(freq),
                    span=self._root_span.get(freq), replica=replica,
                    request_id=freq.request_id, affinity=affinity,
                    attempt_span=self._last_attempt.get(freq),
                    redispatches=freq.redispatches)

    def on_fleet_terminal(self, freq, state: str,
                          error: Optional[str] = None) -> None:
        """The ONE final event of a fleet-rooted trace (the router's
        exactly-once ``_finish`` is the caller, so finality inherits
        its guard)."""
        sid = self._root_span.get(freq)
        self._end_span(sid, state)
        self._event("retired", trace=self._req_trace.get(freq), span=sid,
                    request_id=freq.request_id, state=state, final=True,
                    n_tokens=len(freq.output_ids),
                    **({"error": error} if error else {}))

    def on_eject(self, replica: str, reason: str) -> None:
        self._event("eject", replica=replica, reason=reason)

    def on_rebuild(self, replica: str, recovery_s: float,
                   ok: bool = True) -> None:
        self._event("rebuild", replica=replica, ok=ok,
                    recovery_ms=round(recovery_s * 1e3, 3))

    def on_degrade(self, replica: str, old_mp: int, new_mp: int,
                   recovery_s: float) -> None:
        """A shard group was rebuilt DEGRADED at a smaller viable mp
        on its surviving devices (always paired with an on_rebuild
        event carrying the same recovery time)."""
        self._event("degrade", replica=replica, old_mp=int(old_mp),
                    new_mp=int(new_mp),
                    recovery_ms=round(recovery_s * 1e3, 3))

    # -- introspection ------------------------------------------------------

    def traces(self) -> List[str]:
        """Every distinct trace id seen, in first-event order."""
        seen, out = set(), []
        for ev in self.events:
            t = ev.get("trace")
            if t is not None and t not in seen:
                seen.add(t)
                out.append(t)
        return out

    def snapshot(self) -> dict:
        """JSON-ready summary (NOT the event payload — use the
        :mod:`paddle_tpu.obs` exporters for that)."""
        return {"events": len(self.events), "dropped": self.dropped,
                "spans": len(self.spans), "traces": len(self.traces()),
                "max_events": self.max_events}


# -- chain validation --------------------------------------------------------

def validate_trace(tracer: RequestTracer) -> List[str]:
    """The trace-chain validator: every request's story must be closed
    and well-linked.  Returns a list of problems (empty = valid):

    - no dropped events (a capped tracer cannot certify completeness);
    - every event's span exists and belongs to the event's trace;
    - every trace has EXACTLY ONE final ``retired`` event;
    - every span ends, in a legal state, with ``t_end >= t_start``;
    - every child span's parent exists, shares its trace, and started
      first (preempt/resume and redispatch chains link parent→child);
    - every ``preempt`` event's ``resume_span`` exists and is parented
      on the preempted span.
    """
    problems: List[str] = []
    if tracer.dropped:
        problems.append(f"{tracer.dropped} events dropped at the "
                        f"max_events={tracer.max_events} cap: the chain "
                        "is incomplete")
    finals: Dict[str, int] = {}
    for i, ev in enumerate(tracer.events):
        sid = ev.get("span")
        if sid is not None:
            sp = tracer.spans.get(sid)
            if sp is None:
                problems.append(f"event #{i} ({ev['kind']}) references "
                                f"unknown span {sid}")
            elif ev.get("trace") is not None \
                    and sp["trace"] != ev["trace"]:
                problems.append(f"event #{i} ({ev['kind']}) trace "
                                f"{ev['trace']!r} != its span's "
                                f"{sp['trace']!r}")
        if ev["kind"] == "retired" and ev.get("final") \
                and ev.get("trace") is not None:
            finals[ev["trace"]] = finals.get(ev["trace"], 0) + 1
        if ev["kind"] == "preempt":
            rs = tracer.spans.get(ev.get("resume_span"))
            if rs is None:
                problems.append(f"preempt event #{i} has no resume span")
            elif rs["parent"] != ev.get("span"):
                problems.append(
                    f"preempt event #{i}: resume span {rs['id']} is "
                    f"parented on {rs['parent']}, not the preempted "
                    f"span {ev.get('span')}")
    for trace in {ev.get("trace") for ev in tracer.events} - {None}:
        n = finals.get(trace, 0)
        if n != 1:
            problems.append(f"trace {trace!r} has {n} terminal events "
                            "(want exactly 1)")
    for sid, sp in tracer.spans.items():
        if sp["t_end"] is None:
            problems.append(f"span {sid} ({sp['name']}, trace "
                            f"{sp['trace']!r}) never ended")
            continue
        if sp["t_end"] < sp["t_start"]:
            problems.append(f"span {sid} ends before it starts")
        if sp["state"] not in TERMINAL_SPAN_STATES:
            problems.append(f"span {sid} ended in unknown state "
                            f"{sp['state']!r}")
        parent = tracer.spans.get(sp["parent"]) \
            if sp["parent"] is not None else None
        if sp["parent"] is not None:
            if parent is None:
                problems.append(f"span {sid} has unknown parent "
                                f"{sp['parent']}")
            else:
                if parent["trace"] != sp["trace"]:
                    problems.append(
                        f"span {sid} (trace {sp['trace']!r}) parented "
                        f"across traces on {parent['id']} "
                        f"({parent['trace']!r})")
                if sp["t_start"] < parent["t_start"]:
                    problems.append(f"span {sid} starts before its "
                                    f"parent {parent['id']}")
    return problems


# -- flight recorder ---------------------------------------------------------
# FlightRecorder moved to paddle_tpu.obs.flight (the shared observability
# layer — training's divergence sentry feeds one too); re-exported above.
