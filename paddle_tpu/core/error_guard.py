"""In-graph NaN/Inf sentinel — FLAGS_check_nan_inf under jit.

Reference parity: paddle/fluid/framework/details/nan_inf_utils_detail.cu —
the reference scans every kernel output on-device when FLAGS_check_nan_inf
is set, including inside graphs.  The eager path here is a host-side scan
(core/dispatch._check_nan_inf); THIS module is the compiled path: at
dispatch time each traced float output gets a `set_error_if` predicate
(jax error_check threads an error-state value through the jitted program),
and the trace runtime calls `raise_on_error()` after every compiled step —
so a NaN born inside an XLA program surfaces as a FloatingPointError naming
the producing op, exactly like eager.
"""
from __future__ import annotations

import numpy as np

try:  # jax 0.9 ships this as jax._src.error_check (pre-public API)
    from jax._src.error_check import (
        JaxValueError, raise_if_error, set_error_if,
    )

    _AVAILABLE = True
except Exception:  # pragma: no cover - older/newer jax layouts
    _AVAILABLE = False


def available() -> bool:
    return _AVAILABLE


def set_error_if_nonfinite(name: str, arr) -> None:
    """Arm the sentinel for one traced op output (no-op for non-floats)."""
    if not _AVAILABLE:
        return
    import jax.numpy as jnp

    try:
        kind = np.dtype(arr.dtype).kind
    except Exception:
        return
    if kind not in "fc":
        return
    set_error_if(jnp.logical_not(jnp.all(jnp.isfinite(arr))),
                 f"Operator {name} output contains NaN/Inf")


def raise_on_error() -> None:
    """Raise FloatingPointError if any armed sentinel fired since the last
    check (call after running a compiled step)."""
    if not _AVAILABLE:
        return
    try:
        raise_if_error()
    except JaxValueError as e:
        raise FloatingPointError(str(e)) from None
