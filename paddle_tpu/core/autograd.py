"""Eager define-by-run autograd over jax.vjp.

Reference parity: the eager autograd engine (``paddle/fluid/eager`` —
``GradNodeBase`` grad_node_info.h:161, ``egr::RunBackward`` backward.cc:532).
TPU-native design: instead of generated per-op C++ grad nodes, every
differentiable op call records ONE tape node holding the ``jax.vjp`` closure of
its pure-jax primal.  ``backward()`` is a reverse-topological sweep that feeds
cotangents through the stored vjp closures and accumulates leaf grads —
semantically the queue-based BFS of the reference's RunBackward, without any
codegen.  Under ``to_static`` tracing the same tape runs on jax tracers, so a
whole imperative train step (forward + backward + optimizer) compiles to one
XLA program.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp


class _GradState(threading.local):
    def __init__(self):
        self.enabled = True


_state = _GradState()


def is_grad_enabled() -> bool:
    return _state.enabled


@contextlib.contextmanager
def no_grad():
    """Context manager / decorator disabling grad recording (paddle.no_grad)."""
    prev = _state.enabled
    _state.enabled = False
    try:
        yield
    finally:
        _state.enabled = prev


@contextlib.contextmanager
def enable_grad():
    prev = _state.enabled
    _state.enabled = True
    try:
        yield
    finally:
        _state.enabled = prev


def set_grad_enabled(mode: bool):
    _state.enabled = bool(mode)


class TapeNode:
    """One recorded differentiable op (reference: GradNodeBase + captured
    TensorWrappers).  Holds the vjp closure (residuals live inside it), strong
    refs to differentiable input Tensors and to output Tensors (cycle is
    collected by the python GC once user refs drop)."""

    __slots__ = ("vjp_fn", "inputs", "outputs", "name", "released",
                 "materialize", "input_edges", "__weakref__")

    def __init__(self, vjp_fn, inputs, outputs, name="", materialize=True):
        self.vjp_fn = vjp_fn
        self.inputs: List[Any] = inputs  # Tensors (diff inputs only)
        self.outputs: List[Any] = outputs  # Tensors produced
        self.name = name
        self.released = False
        # False (PyLayer set_materialize_grads): outputs with no incoming
        # cotangent pass None to the vjp instead of materialized zeros
        self.materialize = materialize
        # in-place safety (reference: DenseTensor inplace_version,
        # dense_tensor.h:177, and torch-style recorded edges): snapshot
        # each input's producing node; backward raises if the tensor's
        # grad routing changed (an in-place op consumed it afterwards),
        # which would silently send cotangents through the wrong vjp
        self.input_edges = [getattr(t, "_grad_node", None)
                            for t in inputs]

    def release(self):
        self.vjp_fn = None
        self.released = True


def _toposort(root: TapeNode) -> List[TapeNode]:
    """Iterative DFS post-order over the node graph rooted at ``root``."""
    order: List[TapeNode] = []
    seen = set()
    stack = [(root, False)]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for t in node.inputs:
            n = t._grad_node
            if n is not None and id(n) not in seen and not n.released:
                stack.append((n, False))
    return order


def backward(tensors, grad_tensors=None, retain_graph: bool = False):
    """Run reverse-mode accumulation from ``tensors`` (reference:
    egr::RunBackward, eager/backward.cc:532).

    Leaf tensors (no grad node, stop_gradient=False) receive ``.grad``
    accumulation; intermediate cotangents flow through vjp closures.
    """
    from .tensor import Tensor  # local import to avoid cycle

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]

    # Cotangent buffer keyed by tensor id (reference: GradTensorHolder).
    cot: Dict[int, Any] = {}
    keep: Dict[int, Any] = {}  # keep tensors alive while their id is a key

    roots: List[TapeNode] = []
    for t, g in zip(tensors, grad_tensors):
        if g is None:
            g_arr = jnp.ones(t.shape, dtype=t.dtype)
        else:
            g_arr = g._data if isinstance(g, Tensor) else jnp.asarray(g)
        node = t._grad_node
        if node is None:
            if not t.stop_gradient:
                t._accumulate_grad(g_arr)
            continue
        _accum(cot, keep, t, g_arr)
        roots.append(node)

    if not roots:
        return

    # Merge toposorts of all roots.
    order: List[TapeNode] = []
    seen = set()
    for r in roots:
        for n in _toposort(r):
            if id(n) not in seen:
                seen.add(id(n))
                order.append(n)
    # _toposort returns inputs-before-outputs (post-order); reverse sweep needs
    # outputs first.  A node may appear before its consumer across roots, so
    # re-sort globally: consumers must run before producers.  Post-order DFS of
    # each root already guarantees that within a root; across roots we process
    # in reverse of the merged order which preserves it because any shared
    # producer was appended before its consumer in that root's post-order.
    for node in reversed(order):
        if node.released:
            raise RuntimeError(
                "Trying to backward through the graph a second time "
                "(set retain_graph=True if you need to)."
            )
        cts = []
        any_ct = False
        for out in node.outputs:
            c = cot.pop(id(out), None)
            keep.pop(id(out), None)
            if c is None:
                if node.materialize:
                    c = jnp.zeros(out.shape, dtype=out.dtype)
            else:
                any_ct = True
            cts.append(c)
        if not any_ct:
            continue
        for t, edge in zip(node.inputs, node.input_edges):
            if getattr(t, "_grad_node", None) is not edge:
                raise RuntimeError(
                    f"a tensor consumed by op '{node.name}' was later "
                    "modified by an in-place operation, so its backward "
                    "routing is no longer valid; clone() it before the "
                    "in-place op")
        in_cts = node.vjp_fn(tuple(cts) if len(cts) > 1 else cts[0])
        for t, g in zip(node.inputs, in_cts):
            if g is None:
                continue
            if t._grad_node is None:
                if not t.stop_gradient:
                    t._accumulate_grad(g)
            else:
                _accum(cot, keep, t, g)
        if not retain_graph:
            node.release()


def _accum(cot: dict, keep: dict, t, g):
    prev = cot.get(id(t))
    cot[id(t)] = g if prev is None else prev + g
    keep[id(t)] = t


def grad(
    outputs,
    inputs,
    grad_outputs=None,
    retain_graph: Optional[bool] = None,
    create_graph: bool = False,
    allow_unused: bool = False,
):
    """Functional grad API (paddle.grad).  Returns grads of outputs w.r.t.
    inputs without touching ``.grad`` of other leaves."""
    from .tensor import Tensor

    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if create_graph:
        raise NotImplementedError(
            "create_graph=True: use paddle_tpu.incubate.autograd for higher-order"
        )
    # Save/restore raw grad payloads so we can reuse the accumulation path.
    saved = [t._grad for t in inputs]
    saved_sg = [t.stop_gradient for t in inputs]
    for t in inputs:
        t._grad = None
        t.stop_gradient = False
    try:
        backward(outputs, grad_outputs, retain_graph=bool(retain_graph))
        res = []
        for t, s in zip(inputs, saved):
            g = t._grad
            if g is None and not allow_unused:
                raise RuntimeError(
                    "One of the differentiated tensors appears unused; "
                    "pass allow_unused=True to return None for it."
                )
            res.append(Tensor._wrap(g, stop_gradient=True) if g is not None else None)
        return res
    finally:
        for t, s, sg in zip(inputs, saved, saved_sg):
            t._grad = s
            t.stop_gradient = sg
