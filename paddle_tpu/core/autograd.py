"""Eager define-by-run autograd over jax.vjp.

Reference parity: the eager autograd engine (``paddle/fluid/eager`` —
``GradNodeBase`` grad_node_info.h:161, ``egr::RunBackward`` backward.cc:532).
TPU-native design: instead of generated per-op C++ grad nodes, every
differentiable op call records ONE tape node holding the ``jax.vjp`` closure of
its pure-jax primal.  ``backward()`` is a reverse-topological sweep that feeds
cotangents through the stored vjp closures and accumulates leaf grads —
semantically the queue-based BFS of the reference's RunBackward, without any
codegen.  Under ``to_static`` tracing the same tape runs on jax tracers, so a
whole imperative train step (forward + backward + optimizer) compiles to one
XLA program.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp


class _GradState(threading.local):
    def __init__(self):
        self.enabled = True


_state = _GradState()


def is_grad_enabled() -> bool:
    return _state.enabled


@contextlib.contextmanager
def no_grad():
    """Context manager / decorator disabling grad recording (paddle.no_grad)."""
    prev = _state.enabled
    _state.enabled = False
    try:
        yield
    finally:
        _state.enabled = prev


@contextlib.contextmanager
def enable_grad():
    prev = _state.enabled
    _state.enabled = True
    try:
        yield
    finally:
        _state.enabled = prev


def set_grad_enabled(mode: bool):
    _state.enabled = bool(mode)


class TapeNode:
    """One recorded differentiable op (reference: GradNodeBase + captured
    TensorWrappers).  Holds the vjp closure (residuals live inside it), strong
    refs to differentiable input Tensors and to output Tensors (cycle is
    collected by the python GC once user refs drop)."""

    __slots__ = ("vjp_fn", "primal_fn", "input_arrays", "inputs", "outputs",
                 "name", "released", "materialize", "input_edges",
                 "__weakref__")

    def __init__(self, vjp_fn, inputs, outputs, name="", materialize=True,
                 primal_fn=None, input_arrays=None):
        self.vjp_fn = vjp_fn
        # pure function of the diff inputs' ARRAYS (non-diff args baked),
        # kept so grad(create_graph=True) can replay the subgraph as one
        # differentiable jax function — the stored vjp closure alone bakes
        # the primals in, which would silently zero d²/dprimal² terms
        self.primal_fn = primal_fn
        # the diff inputs' arrays AT RECORD TIME: replay must agree with
        # the first-order path even if a leaf was in-place mutated after
        # the forward (vjp residuals captured the old values; reading
        # t._value() at grad time would silently use the new ones)
        self.input_arrays = input_arrays
        self.inputs: List[Any] = inputs  # Tensors (diff inputs only)
        self.outputs: List[Any] = outputs  # Tensors produced
        self.name = name
        self.released = False
        # False (PyLayer set_materialize_grads): outputs with no incoming
        # cotangent pass None to the vjp instead of materialized zeros
        self.materialize = materialize
        # in-place safety (reference: DenseTensor inplace_version,
        # dense_tensor.h:177, and torch-style recorded edges): snapshot
        # each input's producing node; backward raises if the tensor's
        # grad routing changed (an in-place op consumed it afterwards),
        # which would silently send cotangents through the wrong vjp
        self.input_edges = [getattr(t, "_grad_node", None)
                            for t in inputs]

    def release(self):
        self.vjp_fn = None
        self.primal_fn = None
        self.input_arrays = None
        self.released = True


def _toposort(root: TapeNode) -> List[TapeNode]:
    """Iterative DFS post-order over the node graph rooted at ``root``."""
    order: List[TapeNode] = []
    seen = set()
    stack = [(root, False)]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for t in node.inputs:
            n = t._grad_node
            if n is not None and id(n) not in seen and not n.released:
                stack.append((n, False))
    return order


def backward(tensors, grad_tensors=None, retain_graph: bool = False):
    """Run reverse-mode accumulation from ``tensors`` (reference:
    egr::RunBackward, eager/backward.cc:532).

    Leaf tensors (no grad node, stop_gradient=False) receive ``.grad``
    accumulation; intermediate cotangents flow through vjp closures.
    """
    from .tensor import Tensor  # local import to avoid cycle

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]

    # Cotangent buffer keyed by tensor id (reference: GradTensorHolder).
    cot: Dict[int, Any] = {}
    keep: Dict[int, Any] = {}  # keep tensors alive while their id is a key

    roots: List[TapeNode] = []
    for t, g in zip(tensors, grad_tensors):
        if g is None:
            g_arr = jnp.ones(t.shape, dtype=t.dtype)
        else:
            g_arr = g._data if isinstance(g, Tensor) else jnp.asarray(g)
        node = t._grad_node
        if node is None:
            if not t.stop_gradient:
                t._accumulate_grad(g_arr)
            continue
        _accum(cot, keep, t, g_arr)
        roots.append(node)

    if not roots:
        return

    # Merge toposorts of all roots.
    order: List[TapeNode] = []
    seen = set()
    for r in roots:
        for n in _toposort(r):
            if id(n) not in seen:
                seen.add(id(n))
                order.append(n)
    # _toposort returns inputs-before-outputs (post-order); reverse sweep needs
    # outputs first.  A node may appear before its consumer across roots, so
    # re-sort globally: consumers must run before producers.  Post-order DFS of
    # each root already guarantees that within a root; across roots we process
    # in reverse of the merged order which preserves it because any shared
    # producer was appended before its consumer in that root's post-order.
    for node in reversed(order):
        if node.released:
            raise RuntimeError(
                "Trying to backward through the graph a second time "
                "(set retain_graph=True if you need to)."
            )
        cts = []
        any_ct = False
        for out in node.outputs:
            c = cot.pop(id(out), None)
            keep.pop(id(out), None)
            if c is None:
                if node.materialize:
                    c = jnp.zeros(out.shape, dtype=out.dtype)
            else:
                any_ct = True
            cts.append(c)
        if not any_ct:
            continue
        for t, edge in zip(node.inputs, node.input_edges):
            if getattr(t, "_grad_node", None) is not edge:
                raise RuntimeError(
                    f"a tensor consumed by op '{node.name}' was later "
                    "modified by an in-place operation, so its backward "
                    "routing is no longer valid; clone() it before the "
                    "in-place op")
        in_cts = node.vjp_fn(tuple(cts) if len(cts) > 1 else cts[0])
        for t, g in zip(node.inputs, in_cts):
            if g is None:
                continue
            if t._grad_node is None:
                if not t.stop_gradient:
                    t._accumulate_grad(g)
            else:
                _accum(cot, keep, t, g)
        if not retain_graph:
            node.release()


def _accum(cot: dict, keep: dict, t, g):
    prev = cot.get(id(t))
    cot[id(t)] = g if prev is None else prev + g
    keep[id(t)] = t


def _grad_create_graph(outputs, inputs, grad_outputs, allow_unused):
    """``paddle.grad(..., create_graph=True)``: higher-order-capable grads.

    The stored per-node vjp closures bake the primal values in, so
    differentiating THROUGH them would silently drop every d²y/dx² term
    that flows via the primals.  Instead the recorded subgraph between
    ``inputs`` and ``outputs`` is REPLAYED as one pure jax function of
    the input arrays (each TapeNode keeps its primal_fn for exactly
    this), and its jax.vjp runs through the normal op dispatch — the
    returned grads therefore carry a fresh tape node and are themselves
    differentiable to any order.  Implies retain_graph (nothing is
    released).  Reference: eager double-grad tests
    (test_imperative_double_grad.py) / GradNodeBase higher-order path."""
    from .dispatch import apply_op
    from .tensor import Tensor

    # collect the full ancestry (forward topological order)
    order: List[TapeNode] = []
    seen = set()
    for t in outputs:
        n = getattr(t, "_grad_node", None)
        if n is None:
            continue
        if n.released:
            raise RuntimeError(
                "Trying to backward through the graph a second time "
                "(set retain_graph=True if you need to).")
        for nd in _toposort(n):
            if id(nd) not in seen:
                seen.add(id(nd))
                order.append(nd)
    for nd in order:
        for t in nd.inputs:
            up = getattr(t, "_grad_node", None)
            if up is not None and up.released:
                raise RuntimeError(
                    "Trying to backward through the graph a second time "
                    "(set retain_graph=True if you need to).")

    in_ids = {id(t) for t in inputs}
    # prune to nodes DOWNSTREAM of a requested input: anything upstream
    # of every cut point contributes nothing to the grads (its outputs
    # are either seeds or record-time constants), so it is neither
    # replayed nor required to have a replayable primal
    live_ids = set(in_ids)
    live: List[TapeNode] = []
    for nd in order:
        if any(id(t) in live_ids for t in nd.inputs):
            live.append(nd)
            live_ids.update(id(o) for o in nd.outputs)
    for nd in live:
        if nd.primal_fn is None:
            raise NotImplementedError(
                f"create_graph=True through op '{nd.name}' (a PyLayer) "
                "is not supported: it has no replayable primal")

    # connectivity for allow_unused: every live node is an ancestor of
    # the outputs (order is the outputs' ancestry) and seed-crossing
    # paths still flow, so consumption by a live node means connected
    out_ids = {id(o) for o in outputs}
    consumed_by_live = {id(t2) for nd in live for t2 in nd.inputs}
    reachable = [id(t) in consumed_by_live or id(t) in out_ids
                 for t in inputs]
    if not allow_unused and not all(reachable):
        raise RuntimeError(
            "One of the differentiated tensors appears unused; pass "
            "allow_unused=True to return None for it.")

    # record-time arrays for every node input (first-order backward uses
    # the vjp residuals captured at forward time; replay must agree even
    # if a leaf was mutated in place since)
    recorded: Dict[int, Any] = {}
    for nd in order:
        if nd.input_arrays is not None:
            for t, a in zip(nd.inputs, nd.input_arrays):
                recorded.setdefault(id(t), a)

    def replay(*in_arrays):
        seeds = {id(t): a for t, a in zip(inputs, in_arrays)}
        env: Dict[int, Any] = dict(seeds)
        for nd in live:
            args = [env.get(id(t), recorded.get(id(t), t._value()))
                    for t in nd.inputs]
            outs = nd.primal_fn(*args)
            outs = outs if isinstance(outs, tuple) else (outs,)
            for o, a in zip(nd.outputs, outs):
                if id(o) in seeds:
                    # a requested input that is ALSO produced in-graph:
                    # both grads must flow — d/dseed sees the direct
                    # cotangent, d/dupstream flows through the producer.
                    # value: a + seed - stop_grad(seed) == a (the seed is
                    # the recorded value of this very tensor)
                    s = seeds[id(o)]
                    env[id(o)] = a + (s - jax.lax.stop_gradient(s))
                else:
                    env[id(o)] = a
        return tuple(env.get(id(t), recorded.get(id(t), t._value()))
                     for t in outputs)

    n_in = len(inputs)
    cts = []
    for t, g in zip(outputs,
                    grad_outputs or [None] * len(outputs)):
        if g is None:
            cts.append(Tensor._wrap(jnp.ones(t.shape, dtype=t.dtype),
                                    stop_gradient=True))
        else:
            cts.append(g if isinstance(g, Tensor)
                       else Tensor._wrap(jnp.asarray(g)))

    def hi_primal(*arrs):
        xs, ct_arrs = arrs[:n_in], arrs[n_in:]
        _, vjp = jax.vjp(replay, *xs)
        grads = vjp(tuple(ct_arrs))
        # single-output primals must return a bare array: the tape's
        # backward feeds a matching bare cotangent to this node's vjp
        return grads if n_in > 1 else grads[0]

    res = apply_op("grad_replay", hi_primal, [*inputs, *cts],
                   n_outs=n_in)
    res = res if isinstance(res, tuple) else (res,)
    return [r if ok else None for r, ok in zip(res, reachable)]


def grad(
    outputs,
    inputs,
    grad_outputs=None,
    retain_graph: Optional[bool] = None,
    create_graph: bool = False,
    allow_unused: bool = False,
):
    """Functional grad API (paddle.grad).  Returns grads of outputs w.r.t.
    inputs without touching ``.grad`` of other leaves."""
    from .tensor import Tensor

    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if create_graph:
        return _grad_create_graph(outputs, inputs, grad_outputs,
                                  allow_unused)
    # Save/restore raw grad payloads so we can reuse the accumulation path.
    saved = [t._grad for t in inputs]
    saved_sg = [t.stop_gradient for t in inputs]
    for t in inputs:
        t._grad = None
        t.stop_gradient = False
    try:
        backward(outputs, grad_outputs, retain_graph=bool(retain_graph))
        res = []
        for t, s in zip(inputs, saved):
            g = t._grad
            if g is None and not allow_unused:
                raise RuntimeError(
                    "One of the differentiated tensors appears unused; "
                    "pass allow_unused=True to return None for it."
                )
            res.append(Tensor._wrap(g, stop_gradient=True) if g is not None else None)
        return res
    finally:
        for t, s, sg in zip(inputs, saved, saved_sg):
            t._grad = s
            t.stop_gradient = sg
