"""Stateful RNG over jax PRNG keys.

Reference parity: ``phi::Generator`` (phi/core/generator.h) + ``paddle.seed``.
Design: the generator state is a uint32 key held in a **Tensor**, so that under
to_static tracing the state is lifted into a program input/output — random ops
stay functional inside the compiled program while the python API stays
stateful (the same trick the reference plays with generator state vars in
ProgramDesc).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .tensor import Tensor


class Generator:
    def __init__(self, seed: int = 0):
        from . import tensor as tensor_mod

        self._seed = seed
        # external state even if the generator is first touched inside a
        # to_static trace (the state must be a program input, not a constant)
        self._state = tensor_mod.external_tensor(
            lambda: jax.random.key_data(jax.random.PRNGKey(seed)))

    def manual_seed(self, seed: int):
        self._seed = seed
        self._state._set_data(jax.random.key_data(jax.random.PRNGKey(seed)))
        return self

    @property
    def initial_seed(self) -> int:
        return self._seed

    def get_state(self) -> Tensor:
        return Tensor._wrap(self._state._value())

    def set_state(self, state: Tensor):
        self._state._set_data(state._value())

    def split_key(self):
        """Advance state; return a fresh key array for one random op."""
        key = jax.random.wrap_key_data(self._state._value())
        next_key, sub = jax.random.split(key)
        self._state._set_data(jax.random.key_data(next_key))
        return sub


_default_generator: Optional[Generator] = None


def default_generator() -> Generator:
    global _default_generator
    if _default_generator is None:
        _default_generator = Generator(0)
    return _default_generator


def seed(s: int) -> Generator:
    """paddle.seed — reseed the global generator."""
    return default_generator().manual_seed(int(s))


def next_key():
    return default_generator().split_key()


def get_rng_state():
    return default_generator().get_state()


def set_rng_state(state):
    default_generator().set_state(state)
