"""The paddle_tpu Tensor: an imperative façade over jax.Array.

Reference parity: ``phi::DenseTensor`` (dense_tensor.h:37) + the eager
``paddle::experimental::Tensor`` python object (pybind eager_method.cc).
TPU-native design: the payload is an immutable ``jax.Array`` (or jax tracer,
under to_static capture); imperative semantics (in-place ops, ``.grad``,
version counter) live in this thin python shell.  All compute goes through
``paddle_tpu.core.dispatch`` which records the autograd tape.

Every read of the payload goes through ``_value()`` and every write through
``_set_data()`` so that the to_static tracer (jit/trace.py) can lift
externally-created tensors (parameters, optimizer state, RNG state) into
arguments/results of the compiled program — the trace-based equivalent of the
reference's dy2static variable scoping (run_program_op.cc:221).
"""
from __future__ import annotations

from typing import Any, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from . import dtype as dtype_mod
from .device import Place, current_place
from . import autograd

# Set by paddle_tpu.jit.trace while a to_static capture is active.

# Trace-time shape-read taint hook — installed by paddle_tpu.static while a
# Program is being recorded.  Signature: fn(tensor, [int]) -> [int]; returns
# SymbolicDim-wrapped entries for dims derived from a None-declared feed so
# closure-baked attrs can be detected (static/program.py).
_shape_taint_hook = None


class SymbolicDim(int):
    """An int read from a feed-derived tensor's shape during static
    recording, carrying WHICH None-declared feeds it may derive from.
    Ops that bake such a value into a closure attribute are flagged;
    Executor.run raises only when one of THOSE feeds is fed a
    contradicting size (reference programs re-infer shapes at run time
    instead)."""

    def __new__(cls, v, feeds=frozenset()):
        self = super().__new__(cls, v)
        self.feeds = frozenset(feeds)
        return self

    def _mix(self, v, o):
        of = o.feeds if isinstance(o, SymbolicDim) else frozenset()
        return SymbolicDim(v, self.feeds | of)

    # arithmetic keeps the taint so `x.shape[0] * n` style attrs are caught;
    # non-int operands (floats etc.) fall back to ordinary numeric semantics
    # — the taint is lost but the value stays correct (0.5 * dim must not
    # become SymbolicDim(0)).
    @staticmethod
    def _intlike(o):
        import numpy as _np
        return (isinstance(o, (int, _np.integer))
                and not isinstance(o, bool))

    def __add__(self, o):
        if not self._intlike(o):
            return NotImplemented
        return self._mix(int(self) + int(o), o)

    def __radd__(self, o):
        if not self._intlike(o):
            return NotImplemented
        return self._mix(int(o) + int(self), o)

    def __sub__(self, o):
        if not self._intlike(o):
            return NotImplemented
        return self._mix(int(self) - int(o), o)

    def __rsub__(self, o):
        if not self._intlike(o):
            return NotImplemented
        return self._mix(int(o) - int(self), o)

    def __mul__(self, o):
        if not self._intlike(o):
            return NotImplemented
        return self._mix(int(self) * int(o), o)

    def __rmul__(self, o):
        if not self._intlike(o):
            return NotImplemented
        return self._mix(int(o) * int(self), o)

    def __floordiv__(self, o):
        if not self._intlike(o):
            return NotImplemented
        return self._mix(int(self) // int(o), o)

    def __rfloordiv__(self, o):
        if not self._intlike(o):
            return NotImplemented
        return self._mix(int(o) // int(self), o)

    def __mod__(self, o):
        if not self._intlike(o):
            return NotImplemented
        return self._mix(int(self) % int(o), o)

    def __neg__(self): return SymbolicDim(-int(self), self.feeds)

    def __repr__(self):
        return f"SymbolicDim({int(self)}, feeds={sorted(self.feeds)})"


_trace_hook = None

#: serving.sanitize.SyncSanitizer's counting window: when non-None,
#: every host-coercing conversion (numpy/item/tolist/__array__/
#: __float__/__int__/__bool__) reports itself here before converting.
#: Installed only inside a sanitizer decode window — None (one pointer
#: compare per conversion) the rest of the time.
_sync_hook = None


def _active_hook():
    return _trace_hook


def _note_sync(t) -> None:
    h = _sync_hook
    if h is not None:
        h(t)


class Tensor:
    __slots__ = (
        "_data",
        "_grad",
        "_grad_node",
        "stop_gradient",
        "name",
        "persistable",
        "trainable",
        "_version",
        "_backward_hooks",
        # trace-local tags, owner-checked by jit.trace.TraceHook (object
        # identity, never id() — ids of dead tensors get reused)
        "_trace_born",
        "_trace_grad",
        # weakrefs to TapeNodes that consumed this tensor; an in-place op
        # retargets their input entries to the pre-in-place shadow so
        # already-recorded backwards keep routing to the old value
        "_consumers",
        "__weakref__",
    )

    # -- construction -----------------------------------------------------

    def __init__(self, data=None, dtype=None, place=None, stop_gradient=True, name=None):
        if data is None:
            arr = None
        else:
            arr = _to_jax_array(data, dtype, place)
        self._data = arr
        self._grad = None
        self._grad_node = None
        self.stop_gradient = stop_gradient
        self.name = name or ""
        self.persistable = False
        self.trainable = True
        self._version = 0
        self._backward_hooks = None
        self._trace_born = None
        self._trace_grad = None
        self._consumers = None
        h = _trace_hook
        if h is not None:
            h.mark_created(self)

    def _init_fields(self, stop_gradient=True, name=None):
        """Initialize every non-payload slot (shared by _wrap, detach and
        any other raw __new__ construction — keep in sync with __slots__
        so no construction path leaves a slot unset)."""
        self._grad = None
        self._grad_node = None
        self.stop_gradient = stop_gradient
        self.name = name or ""
        self.persistable = False
        self.trainable = True
        self._version = 0
        self._backward_hooks = None
        self._trace_born = None
        self._trace_grad = None
        self._consumers = None

    @staticmethod
    def _wrap(arr, stop_gradient=True, name=None) -> "Tensor":
        t = Tensor.__new__(Tensor)
        t._data = arr
        t._init_fields(stop_gradient=stop_gradient, name=name)
        h = _trace_hook
        if h is not None:
            h.mark_created(t)
        return t

    # -- payload access (trace-aware) -------------------------------------

    def _value(self):
        """The jax array for compute.  Trace hook may lift external tensors."""
        h = _trace_hook
        if h is not None:
            return h.read(self)
        return self._data

    def _set_data(self, arr):
        """In-place payload replacement (all in-place ops funnel here)."""
        h = _trace_hook
        if h is not None:
            h.write(self, arr)
        else:
            self._data = arr
        self._version += 1

    def _accumulate_grad(self, g):
        if self._backward_hooks:
            for fn in self._backward_hooks.values():
                out = fn(Tensor._wrap(g, stop_gradient=True))
                if out is not None:
                    g = out._value() if isinstance(out, Tensor) else jnp.asarray(out)
        h = _trace_hook
        cur = h.read_grad_accum(self) if h is not None else self._grad
        new = g if cur is None else cur + g
        if h is not None:
            h.write_grad(self, new)
        else:
            self._grad = new

    # -- metadata ---------------------------------------------------------

    @property
    def shape(self) -> List[int]:
        s = list(self._value().shape)
        h = _shape_taint_hook
        return h(self, s) if h is not None else s

    @property
    def ndim(self) -> int:
        return self._value().ndim

    @property
    def dtype(self):
        return np.dtype(self._value().dtype)

    @property
    def size(self) -> int:
        return int(np.prod(self._value().shape)) if self._value().shape else 1

    @property
    def place(self) -> Place:
        d = self._data
        if isinstance(d, jax.Array) and hasattr(d, "devices") and not _is_tracer(d):
            try:
                dev = next(iter(d.devices()))
                kind = "tpu" if dev.platform in ("tpu", "axon") else "cpu"
                return Place(kind, dev.id)
            except Exception:
                pass
        return current_place()

    @property
    def grad(self) -> Optional["Tensor"]:
        h = _trace_hook
        g = h.read_grad(self) if h is not None else self._grad
        if g is None:
            return None
        return Tensor._wrap(g, stop_gradient=True, name=self.name + "@GRAD")

    @grad.setter
    def grad(self, value):
        if value is None:
            self._clear_grad()
        else:
            g = value._value() if isinstance(value, Tensor) else jnp.asarray(value)
            h = _trace_hook
            if h is not None:
                h.write_grad(self, g)
            else:
                self._grad = g

    def _clear_grad(self):
        h = _trace_hook
        if h is not None:
            h.write_grad(self, None)
        else:
            self._grad = None

    def clear_grad(self):
        self._clear_grad()

    def clear_gradient(self, set_to_zero=False):
        if set_to_zero:
            g = self.grad
            if g is not None:
                zero = jnp.zeros_like(g._value())
                h = _trace_hook
                if h is not None:
                    h.write_grad(self, zero)
                else:
                    self._grad = zero
        else:
            self._clear_grad()

    @property
    def is_leaf(self) -> bool:
        return self._grad_node is None

    def inplace_version(self) -> int:
        return self._version

    # -- conversion -------------------------------------------------------

    def numpy(self) -> np.ndarray:
        _note_sync(self)
        return np.asarray(self._value())

    def item(self, *args):
        _note_sync(self)
        v = self._value()
        if args:
            return np.asarray(v).item(*args)
        return np.asarray(v).item()

    def tolist(self):
        _note_sync(self)
        return np.asarray(self._value()).tolist()

    def __array__(self, dtype=None):
        _note_sync(self)
        a = np.asarray(self._value())
        return a.astype(dtype) if dtype is not None else a

    def __float__(self):
        return float(self.item())

    def __int__(self):
        return int(self.item())

    def __bool__(self):
        _note_sync(self)
        return bool(self._value())

    def __format__(self, spec):
        if not spec:
            return str(self)
        v = self._value()
        if v.ndim == 0:
            _note_sync(self)
            return format(v.item(), spec)
        raise TypeError(
            "format spec on a non-scalar Tensor; call .numpy() first")

    def __len__(self):
        s = self._value().shape
        if not s:
            raise TypeError("len() of a 0-d tensor")
        return s[0]

    def __iter__(self):
        # without this, python falls back to the legacy __getitem__
        # iteration protocol, which never terminates because jax clamps
        # out-of-range indices instead of raising IndexError.  Validate
        # the rank EAGERLY (plain method returning a generator), so
        # iter(scalar) raises immediately like len() does.
        s = self._value().shape
        if not s:
            raise TypeError("iteration over a 0-d tensor")
        return (self[i] for i in range(s[0]))

    def __hash__(self):
        return id(self)

    # -- autograd ---------------------------------------------------------

    def backward(self, grad_tensor=None, retain_graph=False):
        autograd.backward([self], [grad_tensor], retain_graph=retain_graph)

    def detach(self) -> "Tensor":
        """A tensor SHARING this tensor's storage with autograd cut off
        (reference semantics: detach returns a view — writes through
        either alias are visible to both; `dense_tensor.h:63`
        shallow-copy sharing).  Implemented as a view object delegating
        its payload to the base tensor, since jax arrays are immutable
        and "storage" here is the rebindable payload slot."""
        base = self._base if isinstance(self, _DetachedView) else self
        v = _DetachedView.__new__(_DetachedView)
        v._base = base
        v._init_fields(stop_gradient=True, name=self.name)
        h = _trace_hook
        if h is not None:
            h.mark_created(v)
        return v

    def detach_(self) -> "Tensor":
        self._grad_node = None
        self.stop_gradient = True
        return self

    _hook_counter = 0

    def register_hook(self, hook):
        """Register a grad hook (reference: egr RegisterGradientHook)."""
        if self._backward_hooks is None:
            self._backward_hooks = {}
        Tensor._hook_counter += 1
        key = Tensor._hook_counter
        self._backward_hooks[key] = hook
        tensor = self

        class _Handle:
            def remove(self):
                tensor._backward_hooks.pop(key, None)

        return _Handle()

    def _rebind_from(self, out: "Tensor"):
        """Adopt ``out``'s payload and autograd position (in-place op result).
        The producing TapeNode's output entry is retargeted to ``self`` so the
        backward sweep finds cotangents under this tensor's identity."""
        old_node = self._grad_node
        old_stop = self.stop_gradient
        node = out._grad_node
        if node is not None and any(t is self for t in node.inputs):
            # the producing op consumed `self` PRE-in-place: its input
            # entry must keep the old autograd position, or the node
            # becomes self-referential and upstream grads are dropped
            shadow = Tensor.__new__(Tensor)
            shadow._data = self._data
            shadow._grad = None
            shadow._grad_node = old_node
            shadow.stop_gradient = old_stop
            shadow.name = ""
            shadow.persistable = False
            shadow.trainable = False
            shadow._version = self._version   # pre-in-place version
            shadow._backward_hooks = None
            shadow._trace_born = None
            shadow._trace_grad = None
            shadow._consumers = None
            if old_node is None and not old_stop:
                # leaf requiring grad: cotangents for the pre-in-place
                # value must land on THIS tensor's .grad (reference
                # in-place-on-leaf semantics)
                target = self

                def _route(g, _t=target):
                    _t._accumulate_grad(g._value())
                    return g

                shadow._backward_hooks = {0: _route}
            if old_node is not None:
                # the old producer now emits the PRE-in-place identity
                old_node.outputs = [shadow if o is self else o
                                    for o in old_node.outputs]
            node.inputs = [shadow if t is self else t
                           for t in node.inputs]
            # every EARLIER consumer of `self` recorded the pre-in-place
            # value (vjp residuals are captured by value at forward time),
            # so their backward must deliver cotangents to the old autograd
            # position — retarget their input entries to the shadow
            # (reference: torch's version-counter raises here; capturing by
            # value lets us keep these programs valid AND correct)
            if self._consumers:
                live = []
                for ref in self._consumers:
                    n = ref()
                    if n is None or n.released:
                        continue
                    if n is not node:
                        n.inputs = [shadow if t is self else t
                                    for t in n.inputs]
                    else:
                        live.append(ref)
                self._consumers = live or None
        self._set_data(out._value())
        self._version += 1     # stale backward reads now raise
        self._grad_node = node
        if node is not None:
            node.outputs = [self if o is out else o for o in node.outputs]
        if not out.stop_gradient:
            self.stop_gradient = False
        # static-graph recording: later consumers of `self` must resolve
        # to `out`'s SSA slot, not self's pre-in-place producer
        from . import dispatch as _dispatch_mod

        if _dispatch_mod._static_record_hook is not None:
            _dispatch_mod._static_record_hook(
                "__alias__", None, [out], {}, [self])
        return self

    # -- in-place / value ops ---------------------------------------------

    def set_value(self, value):
        if isinstance(value, Tensor):
            arr = value._value()
        else:
            arr = _to_jax_array(value, self.dtype, None)
        arr = jnp.asarray(arr, dtype=self._value().dtype)
        if tuple(arr.shape) != tuple(self._value().shape):
            raise ValueError(
                f"set_value shape mismatch: {arr.shape} vs {self._value().shape}"
            )
        self._set_data(arr)
        return self

    def copy_(self, other, blocking=True):
        return self.set_value(other)

    def fill_(self, value):
        self._set_data(jnp.full_like(self._value(), value))
        return self

    def zero_(self):
        self._set_data(jnp.zeros_like(self._value()))
        return self

    # -- misc -------------------------------------------------------------

    def clone(self) -> "Tensor":
        from . import dispatch

        return dispatch.apply_op("clone", lambda x: x + 0, [self])

    def to(self, *args, **kwargs):
        # to(dtype) / to(device) / to(device, dtype)
        dtype = kwargs.get("dtype")
        device = kwargs.get("device")
        for a in args:
            if isinstance(a, str) and a.split(":")[0] in ("cpu", "tpu", "gpu"):
                device = a
            else:
                dtype = a
        out = self
        if dtype is not None:
            out = out.astype(dtype)
        if device is not None:
            from .device import set_device, current_place

            kind = device.split(":")[0]
            kind = "tpu" if kind in ("gpu", "tpu") else "cpu"
            arr = jax.device_put(out._value(), Place(kind, 0).jax_device)
            out = Tensor._wrap(arr, stop_gradient=out.stop_gradient)
        return out

    def cpu(self):
        return self.to("cpu")

    def pin_memory(self):
        return self

    def cuda(self, *a, **k):
        return self.to("tpu")

    def __repr__(self):
        sg = self.stop_gradient
        d = self._value()
        if _is_tracer(d):
            body = f"<traced {d.aval}>"
        else:
            _note_sync(self)
            body = np.array2string(np.asarray(d), precision=6, separator=", ")
        return (
            f"Tensor(shape={self.shape}, dtype={dtype_mod.dtype_name(self.dtype)}, "
            f"place={self.place}, stop_gradient={sg},\n       {body})"
        )

    # astype / math dunders etc. are attached by paddle_tpu.ops at import
    # time via register_tensor_method().


class _DetachedView(Tensor):
    """detach() result: shares the base tensor's payload slot (reference:
    detach returns a storage-sharing view) with its own autograd state.

    The ``_data`` property shadows the base-class slot so EVERY consumer
    — including code reading ``t._data`` directly — sees the base's
    current payload; writes through either alias are visible to both.
    ``_value``/``_set_data`` route through the base so trace-time reads
    and writes carry the BASE identity (the tracer knows the base, not
    the view).  One divergence from the reference: a write through the
    view does not bump the base's inplace version, so a stale-backward
    through earlier consumers computes with their captured pre-write
    residuals instead of raising — values are correct either way."""

    __slots__ = ("_base",)

    @property
    def _data(self):
        return self._base._data

    @_data.setter
    def _data(self, arr):
        self._base._data = arr

    def _value(self):
        return self._base._value()

    def _set_data(self, arr):
        self._base._set_data(arr)
        self._version += 1


def _is_tracer(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _to_jax_array(data, dtype=None, place=None):
    dt = dtype_mod.convert_dtype(dtype) if dtype is not None else None
    if isinstance(data, Tensor):
        arr = data._value()
        return jnp.asarray(arr, dtype=dt) if dt is not None else arr
    if isinstance(data, (jax.Array,)) or _is_tracer(data):
        return jnp.asarray(data, dtype=dt) if dt is not None else data
    a = np.asarray(data)
    if dt is None and a.dtype == np.float64:
        dt = dtype_mod.get_default_dtype()
    dev = None
    if place is not None:
        dev = place.jax_device if isinstance(place, Place) else None
    arr = jnp.asarray(a, dtype=dt)
    if dev is not None:
        arr = jax.device_put(arr, dev)
    return arr


def to_tensor(data, dtype=None, place=None, stop_gradient=True) -> Tensor:
    """paddle.to_tensor (reference: python/paddle/tensor/creation.py)."""
    if isinstance(place, str):
        kind = place.split(":")[0]
        place = Place("tpu" if kind in ("gpu", "tpu") else "cpu", 0)
    arr = _to_jax_array(data, dtype, place)
    return Tensor._wrap(arr, stop_gradient=stop_gradient)


def register_tensor_method(name, fn):
    """Attach an op as a Tensor method (used by paddle_tpu.ops)."""
    setattr(Tensor, name, fn)


def external_tensor(value, dtype=None) -> Tensor:
    """Create a Tensor treated as *external persistent state* even when
    constructed inside a to_static trace (lazily-created optimizer
    accumulators, scheduler scalars, RNG state — anything that must become a
    program input rather than a baked constant).  The payload is forced
    concrete (ensure_compile_time_eval) because under jax's stackless tracing
    any jnp op inside a trace yields a tracer."""
    with jax.ensure_compile_time_eval():
        if callable(value):
            arr = value()
        else:
            arr = _to_jax_array(np.asarray(value), dtype, None)
    t = Tensor.__new__(Tensor)
    t._data = arr
    t._grad = None
    t._grad_node = None
    t.stop_gradient = True
    t.name = ""
    t.persistable = True
    t.trainable = False
    t._version = 0
    t._backward_hooks = None
    t._trace_born = None
    t._trace_grad = None
    t._consumers = None
    return t
