"""Dtype system for paddle_tpu.

Mirrors the reference's common scalar types (``paddle/phi/common/data_type.h``,
exposed in python as ``paddle.float32`` etc.) but is simply a thin veneer over
numpy/ml_dtypes dtypes so that every paddle_tpu dtype *is* a jax-compatible
``np.dtype``.  bfloat16 is first-class (TPU native compute type).
"""
from __future__ import annotations

import numpy as np
import ml_dtypes

# Canonical dtype objects (np.dtype instances; jax accepts these directly).
bfloat16 = np.dtype(ml_dtypes.bfloat16)
float16 = np.dtype(np.float16)
float32 = np.dtype(np.float32)
float64 = np.dtype(np.float64)
int8 = np.dtype(np.int8)
int16 = np.dtype(np.int16)
int32 = np.dtype(np.int32)
int64 = np.dtype(np.int64)
uint8 = np.dtype(np.uint8)
uint16 = np.dtype(np.uint16)
uint32 = np.dtype(np.uint32)
uint64 = np.dtype(np.uint64)
bool_ = np.dtype(np.bool_)
complex64 = np.dtype(np.complex64)
complex128 = np.dtype(np.complex128)
float8_e4m3fn = np.dtype(ml_dtypes.float8_e4m3fn)
float8_e5m2 = np.dtype(ml_dtypes.float8_e5m2)

_NAME_TO_DTYPE = {
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "float16": float16,
    "fp16": float16,
    "half": float16,
    "float32": float32,
    "fp32": float32,
    "float": float32,
    "float64": float64,
    "fp64": float64,
    "double": float64,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int": int32,
    "int64": int64,
    "long": int64,
    "uint8": uint8,
    "uint16": uint16,
    "uint32": uint32,
    "uint64": uint64,
    "bool": bool_,
    "complex64": complex64,
    "complex128": complex128,
    "float8_e4m3fn": float8_e4m3fn,
    "float8_e5m2": float8_e5m2,
}

_FLOATING = {bfloat16, float16, float32, float64, float8_e4m3fn, float8_e5m2}
_COMPLEX = {complex64, complex128}
_INTEGRAL = {int8, int16, int32, int64, uint8, uint16, uint32, uint64}

_default_dtype = float32

# TPU-native canonicalization: 64-bit types are not XLA-native on TPU and jax
# runs with x64 disabled, so 64-bit dtypes canonicalize to their 32-bit
# counterparts (the reference keeps true int64; we document the difference).
_CANONICAL = {int64: int32, uint64: uint32, float64: float32, complex128: complex64}

import warnings as _warnings

_warnings.filterwarnings(
    "ignore", message="Explicitly requested dtype.*truncated", category=UserWarning
)


def canonicalize(dtype):
    d = convert_dtype(dtype)
    return _CANONICAL.get(d, d)


def convert_dtype(dtype) -> np.dtype:
    """Normalize any dtype spec (str, np.dtype, jnp dtype, paddle dtype) to
    np.dtype, canonicalizing 64-bit types to 32-bit (TPU-native; see
    ``_CANONICAL``)."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        try:
            d = _NAME_TO_DTYPE[dtype]
        except KeyError:
            raise ValueError(f"Unknown dtype name: {dtype!r}")
    else:
        d = np.dtype(dtype)
    return _CANONICAL.get(d, d)


def dtype_name(dtype) -> str:
    d = convert_dtype(dtype)
    if d == bfloat16:
        return "bfloat16"
    return d.name


def is_floating_point(dtype) -> bool:
    return convert_dtype(dtype) in _FLOATING


def is_complex(dtype) -> bool:
    return convert_dtype(dtype) in _COMPLEX


def is_integer(dtype) -> bool:
    return convert_dtype(dtype) in _INTEGRAL


def set_default_dtype(dtype):
    """Set the default floating dtype (reference: paddle.set_default_dtype)."""
    global _default_dtype
    d = convert_dtype(dtype)
    if d not in _FLOATING:
        raise TypeError(f"default dtype must be floating, got {dtype}")
    _default_dtype = d


def get_default_dtype() -> np.dtype:
    return _default_dtype
