"""Device / Place abstraction.

Reference parity: ``paddle/phi/common/place.h`` Place classes and the python
``paddle.device`` module (set_device/get_device).  On TPU there is one device
kind that matters; CPU is the host/test backend.  A Place wraps a jax.Device.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax


class Place:
    """Device identity: a backend kind + ordinal (reference: phi::Place)."""

    __slots__ = ("kind", "index")

    def __init__(self, kind: str, index: int = 0):
        self.kind = kind
        self.index = index

    def __repr__(self):
        return f"Place({self.kind}:{self.index})"

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.kind == other.kind
            and self.index == other.index
        )

    def __hash__(self):
        return hash((self.kind, self.index))

    def is_tpu_place(self):
        return self.kind == "tpu"

    def is_cpu_place(self):
        return self.kind == "cpu"

    @property
    def jax_device(self) -> jax.Device:
        return _jax_device_for(self.kind, self.index)


class CPUPlace(Place):
    def __init__(self, index: int = 0):
        super().__init__("cpu", index)


class TPUPlace(Place):
    def __init__(self, index: int = 0):
        super().__init__("tpu", index)


# Accelerator platform names that map to the "tpu" place kind. "axon" is a
# tunneled TPU platform seen in some environments.
_TPU_PLATFORMS = ("tpu", "axon")

_current_place: Optional[Place] = None


@functools.lru_cache(maxsize=None)
def _devices_by_kind(kind: str):
    if kind == "cpu":
        try:
            return jax.devices("cpu")
        except RuntimeError:
            return []
    devs = []
    for plat in _TPU_PLATFORMS:
        try:
            devs = jax.devices(plat)
        except RuntimeError:
            continue
        if devs:
            break
    return devs


def _jax_device_for(kind: str, index: int) -> jax.Device:
    devs = _devices_by_kind(kind)
    if not devs:
        raise RuntimeError(f"no {kind} devices available")
    return devs[index % len(devs)]


def _default_place() -> Place:
    d = jax.devices()[0]
    kind = "tpu" if d.platform in _TPU_PLATFORMS else "cpu"
    return Place(kind, 0)


def set_device(device: str) -> Place:
    """paddle.device.set_device('tpu') / 'cpu' / 'tpu:0'."""
    global _current_place
    if ":" in device:
        kind, idx = device.split(":")
        idx = int(idx)
    else:
        kind, idx = device, 0
    kind = kind.lower()
    if kind in ("gpu", "cuda", "xpu", "npu"):
        # Accelerator alias: on this framework the accelerator is the TPU.
        kind = "tpu"
    if kind not in ("cpu", "tpu"):
        raise ValueError(f"unsupported device {device!r}")
    _current_place = Place(kind, idx)
    return _current_place


def get_device() -> str:
    p = current_place()
    return f"{p.kind}:{p.index}"


def current_place() -> Place:
    global _current_place
    if _current_place is None:
        _current_place = _default_place()
    return _current_place


def is_compiled_with_tpu() -> bool:
    return len(_devices_by_kind("tpu")) > 0


def device_count(kind: Optional[str] = None) -> int:
    if kind is None:
        kind = current_place().kind
    return len(_devices_by_kind(kind))


# ---------------------------------------------------------------------------
# Memory statistics (reference: paddle/fluid/memory/stats.cc surfaced as
# paddle.device.cuda.max_memory_allocated etc.).  On TPU the allocator is
# XLA's (BFC on HBM); PJRT exposes its counters via Device.memory_stats().
# ---------------------------------------------------------------------------

def _resolve_device(device=None) -> jax.Device:
    if isinstance(device, jax.Device):
        return device
    if isinstance(device, int):
        return jax.devices()[device]
    if isinstance(device, Place):
        return _jax_device_for(device.kind, device.index or 0)
    return jax.devices()[0]


def memory_stats(device=None) -> dict:
    """Raw allocator counters for one device (PJRT memory_stats; {} when
    the backend exposes none, e.g. CPU)."""
    return _resolve_device(device).memory_stats() or {}


def memory_allocated(device=None) -> int:
    """Bytes currently allocated on the device (reference:
    paddle.device.cuda.memory_allocated)."""
    return int(memory_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None) -> int:
    """Peak bytes allocated (reference: cuda.max_memory_allocated)."""
    return int(memory_stats(device).get("peak_bytes_in_use", 0))


def memory_reserved(device=None) -> int:
    """Bytes reserved by the allocator pool (reference:
    cuda.memory_reserved); 0 when the backend doesn't expose pool
    counters (counters like bytes_limit describe CAPACITY, not
    reservations, and must not be reported here)."""
    return int(memory_stats(device).get("pool_bytes", 0))


def max_memory_reserved(device=None) -> int:
    s = memory_stats(device)
    return int(s.get("peak_pool_bytes", s.get("pool_bytes", 0)))


def synchronize(device=None):
    """Block until all queued work on the device finished (reference:
    paddle.device.cuda.synchronize)."""
    import jax.numpy as jnp

    d = _resolve_device(device)
    jax.device_put(jnp.zeros(()), d).block_until_ready()


class _AcceleratorNamespace:
    """paddle.device.tpu.* — the accelerator-scoped stats API (the
    reference's paddle.device.cuda.* shape)."""

    memory_stats = staticmethod(memory_stats)
    memory_allocated = staticmethod(memory_allocated)
    max_memory_allocated = staticmethod(max_memory_allocated)
    memory_reserved = staticmethod(memory_reserved)
    max_memory_reserved = staticmethod(max_memory_reserved)
    synchronize = staticmethod(synchronize)

    @staticmethod
    def device_count() -> int:
        return len(_devices_by_kind("tpu"))


tpu = _AcceleratorNamespace()
# source compatibility for reference code reaching for .cuda on an
# accelerator: same counters, backed by the TPU/PJRT allocator
cuda = tpu


class CUDAPlace(Place):
    """API-compat CUDA place (reference phi/common/place.h GPUPlace).
    This build targets TPU via XLA; constructing one is allowed (so
    ported code parses), and placing tensors on it fails in device
    resolution with the standard no-gpu-devices error."""

    def __init__(self, device_id=0):
        super().__init__("gpu", device_id)


class CUDAPinnedPlace(Place):
    def __init__(self):
        super().__init__("gpu_pinned", 0)


class NPUPlace(Place):
    def __init__(self, device_id=0):
        super().__init__("npu", device_id)


class XPUPlace(Place):
    def __init__(self, device_id=0):
        super().__init__("xpu", device_id)


class NPUPlaceAlias(Place):
    pass


class MLUPlace(Place):
    def __init__(self, device_id=0):
        super().__init__("mlu", device_id)


class IPUPlace(Place):
    def __init__(self, device_id=0):
        super().__init__("ipu", device_id)


# -- capability predicates (reference device/__init__.py): this build
# targets TPU via XLA, so every vendor-specific predicate is False and
# vendor device enumeration returns the XLA device list ---------------

def is_compiled_with_cuda():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_npu():
    return False


def is_compiled_with_mlu():
    return False


def is_compiled_with_ipu():
    return False


def is_compiled_with_cinn():
    return False


def get_cudnn_version():
    return None


def get_all_device_type():
    """Device types visible to XLA (reference returns Place types)."""
    return sorted({d.platform for d in jax.devices()})


def get_all_custom_device_type():
    return [t for t in get_all_device_type() if t not in ("cpu",)]


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return [s for s in get_available_device()
            if not s.startswith("cpu")]
