"""Typed runtime flag registry.

Reference parity: the ~56 ``PADDLE_DEFINE_EXPORTED`` gflags in
``paddle/fluid/platform/flags.cc`` plus python ``paddle.get_flags/set_flags``
(``python/paddle/fluid/framework.py:7112``).  Here: a single typed registry,
env-seeded (``FLAGS_*``), readable and writable from python.
"""
from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional


@dataclass
class _Flag:
    name: str
    default: Any
    type: type
    help: str
    value: Any = None
    on_change: Optional[Callable[[Any], None]] = None


_registry: Dict[str, _Flag] = {}
_lock = threading.Lock()


def _parse(tp: type, raw: str):
    if tp is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    return tp(raw)


def define_flag(name: str, default, help: str = "", on_change=None):
    """Register a flag; env var FLAGS_<name> overrides the default."""
    tp = type(default)
    env = os.environ.get(f"FLAGS_{name}")
    value = _parse(tp, env) if env is not None else default
    with _lock:
        _registry[name] = _Flag(name, default, tp, help, value, on_change)
    return value


def _norm(name: str) -> str:
    """Public API accepts the reference's 'FLAGS_'-prefixed names."""
    return name[6:] if name.startswith("FLAGS_") else name


def get_flags(names=None) -> Dict[str, Any]:
    with _lock:
        if names is None:
            return {k: f.value for k, f in _registry.items()}
        if isinstance(names, str):
            names = [names]
        return {n: _registry[_norm(n)].value for n in names}


def get_flag(name: str):
    return _registry[name].value


def set_flags(flags: Dict[str, Any]):
    with _lock:
        for name, val in flags.items():
            name = _norm(name)
            if name not in _registry:
                raise KeyError(f"unknown flag {name!r}")
            f = _registry[name]
            f.value = _parse(f.type, val) if isinstance(val, str) else f.type(val)
            if f.on_change:
                f.on_change(f.value)


# Core flags (subset of reference's platform/flags.cc relevant on TPU).
define_flag("check_nan_inf", False, "scan op outputs for NaN/Inf each eager op")
define_flag("benchmark", False, "sync after each op for timing")
define_flag("low_precision_op_list", False, "log ops run under AMP autocast")
define_flag("use_flash_attention", True, "use Pallas flash-attention kernels")
define_flag("allocator_strategy", "xla", "memory allocator strategy (XLA-managed)")
define_flag("tracer_mkldnn_ops_on", "", "unused; API parity only")
define_flag("cache_jit_programs", True, "cache compiled to_static programs")
define_flag("eager_op_jit", True, "jit-compile eager per-op dispatch")
