"""Custom op / custom kernel registration — the out-of-tree extension point.

Reference parity: paddle/phi/core/custom_kernel.h:49 (out-of-tree kernels
registered into the factory for existing ops) and
python/paddle/utils/cpp_extension (user-defined ops compiled and bound).

TPU-native design: a "kernel" is a pure jax-traceable function — typically a
Pallas TPU kernel, but any jax composition works.  Two registration forms:

- ``register_op(name, fn, vjp=None)``: a NEW op.  It enters the same
  ``apply_op`` dispatch as built-ins (tape recording, AMP hook, nan/inf
  sentinel all apply); ``vjp`` installs a custom gradient; optionally binds
  a Tensor method.  This replaces the reference's compile-a-.so flow —
  there is nothing to compile, XLA/Mosaic does it at trace time.
- ``register_kernel(op_name, fn, backend=None)``: override the primal of an
  EXISTING op for a backend (e.g. hand-written Pallas softmax on "tpu"
  while other backends keep the stock path) — custom_kernel.h's semantics.
  Dispatch consults the override table on every apply_op call.
"""
from __future__ import annotations

import functools
from typing import Callable, Dict, Optional, Tuple

import jax

__all__ = ["register_op", "register_kernel", "unregister_kernel",
           "get_kernel_override"]

# (op_name, backend_or_None) -> primal fn
_KERNELS: Dict[Tuple[str, Optional[str]], Callable] = {}


def register_kernel(op_name: str, fn: Callable = None, *,
                    backend: Optional[str] = None):
    """Install `fn` as the kernel for `op_name` (optionally only on
    `backend`, e.g. "tpu"/"cpu").  Usable as a decorator::

        @register_kernel("softmax", backend="tpu")
        def fast_softmax(x, axis=-1): ...
    """
    def _do(f):
        _KERNELS[(op_name, backend)] = f
        return f

    if fn is None:
        return _do
    return _do(fn)


def unregister_kernel(op_name: str, backend: Optional[str] = None):
    _KERNELS.pop((op_name, backend), None)


def get_kernel_override(op_name: str) -> Optional[Callable]:
    if not _KERNELS:
        return None
    try:
        backend = jax.default_backend()
    except Exception:
        backend = None
    return _KERNELS.get((op_name, backend)) or _KERNELS.get((op_name, None))


def register_op(name: str, fn: Callable, vjp: Optional[Callable] = None,
                tensor_method: bool = False, n_outs: int = 1) -> Callable:
    """Create a new framework op from a jax-level function.

    ``fn(*arrays, **kwargs) -> array(s)``.  With ``vjp``, the pair is wired
    as ``jax.custom_vjp`` (``vjp(residual_inputs, cotangents) -> input
    cotangents``: signature ``vjp(primal_args_tuple, out_grads) -> tuple``).
    Returns the Tensor-level callable (also reachable via
    ``get_kernel_override`` dispatch if name collides with a built-in).
    """
    from .dispatch import apply_op

    kernel = fn
    if vjp is not None:
        @jax.custom_vjp
        def kernel(*arrays, **kwargs):
            return fn(*arrays, **kwargs)

        def _fwd(*arrays, **kwargs):
            return fn(*arrays, **kwargs), arrays

        def _bwd(res, g):
            out = vjp(res, g)
            return tuple(out) if isinstance(out, (tuple, list)) else (out,)

        kernel.defvjp(_fwd, _bwd)

    @functools.wraps(fn)
    def op_fn(*tensors, **kwargs):
        return apply_op(name, kernel, list(tensors), kwargs, n_outs=n_outs)

    register_kernel(name, kernel)
    if tensor_method:
        from .tensor import register_tensor_method

        register_tensor_method(name, op_fn)
    return op_fn
