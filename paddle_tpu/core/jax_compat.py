"""Version-compat shims over JAX surfaces that moved between releases.

The framework tracks JAX across the window where several public names
migrated out of ``jax.experimental``; importing them directly pins us to
one side of the move and an environment on the other side loses the
ENTIRE package (r05: ``from jax import shard_map`` errored all 45 test
modules at collection under JAX 0.4.x).  Rule: any jax attribute that has
moved homes is imported from here, never from jax directly.
"""
from __future__ import annotations

import inspect

import jax

__all__ = ["shard_map", "pvary", "jax_export", "distributed_client_exists",
           "pallas_tpu_compiler_params", "SUPPORTS_PARTIAL_MANUAL"]


def _resolve_shard_map():
    # jax >= 0.6: top-level jax.shard_map; 0.4.x/0.5.x: experimental home.
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm
    from jax.experimental.shard_map import shard_map as sm  # noqa: N813

    return sm


_raw_shard_map = _resolve_shard_map()
_SM_PARAMS = frozenset(inspect.signature(_raw_shard_map).parameters)

# Partial-manual shard_map (manual on some mesh axes, GSPMD-auto on the
# rest) only became fully functional alongside the new `axis_names` API:
# the 0.4.x `auto=` path raises NotImplementedError eagerly and loses
# axis_index/PartitionId under jit on CPU.  Pipeline schedules and ring
# attention require it; callers gate on this instead of crashing deep in
# XLA (tests skip with a reason, dispatch falls back where one exists).
SUPPORTS_PARTIAL_MANUAL = "axis_names" in _SM_PARAMS


def shard_map(f, **kwargs):
    """``jax.shard_map`` across the API move.

    The new API selects partial-manual mode with ``axis_names`` (the axes
    that ARE manual); the old one with ``auto`` (the complement).  Written
    against the new spelling.  On old JAX, a call that is manual on EVERY
    mesh axis translates cleanly (auto is empty); a genuinely
    partial-manual call raises the clear capability error here rather
    than emitting the broken ``auto=`` path (see SUPPORTS_PARTIAL_MANUAL).
    """
    if "axis_names" in kwargs and "axis_names" not in _SM_PARAMS:
        manual = frozenset(kwargs.pop("axis_names"))
        auto = frozenset(kwargs["mesh"].axis_names) - manual
        if auto:
            raise RuntimeError(
                f"partial-manual shard_map (manual on {sorted(manual)}, "
                f"auto on {sorted(auto)}) requires the jax.shard_map "
                "axis_names API — upgrade JAX "
                "(gate callers on jax_compat.SUPPORTS_PARTIAL_MANUAL)")
    return _raw_shard_map(f, **kwargs)


def pvary(x, axis_names):
    """Mark an array varying over manual mesh axes, across three API
    generations: ``jax.lax.pcast(..., to="varying")`` (current),
    ``jax.lax.pvary`` (its deprecated predecessor), identity on old JAX —
    which never tracked per-axis variance inside shard_map, so no marking
    is needed there."""
    fn = getattr(jax.lax, "pcast", None)
    if fn is not None:
        return fn(x, tuple(axis_names), to="varying")
    fn = getattr(jax.lax, "pvary", None)
    if fn is None:
        return x
    return fn(x, tuple(axis_names))


def pallas_tpu_compiler_params():
    """``pltpu.CompilerParams`` (guide-current name) falling back to the
    pre-0.6 ``TPUCompilerParams`` spelling.  A function, not a constant:
    importing pallas is deferred until a kernel module actually needs it."""
    from jax.experimental.pallas import tpu as pltpu

    return getattr(pltpu, "CompilerParams", None) \
        or pltpu.TPUCompilerParams


def _resolve_export():
    # jax.export exists from ~0.4.30 but as a lazily-imported submodule:
    # plain attribute access (jax.export.export) raises AttributeError
    # until something imports it — so import it properly, with the
    # experimental home as the pre-0.4.30 fallback.
    try:
        from jax import export as ex
    except ImportError:  # pragma: no cover - very old jax
        from jax.experimental import export as ex
    return ex


jax_export = _resolve_export()


def distributed_client_exists() -> bool:
    """True if a jax.distributed coordinator client is already up.

    ``jax._src.distributed.global_state`` is private and has moved/changed
    shape before; treat any layout change as "unknown" → False, so the
    caller attempts initialize() and JAX itself reports double-init.
    """
    try:
        from jax._src.distributed import global_state

        return global_state.client is not None
    except Exception:
        return False
