"""Op dispatch: pure-jax primal + tape recording.

Reference parity: the generated ``*_final_state_dygraph_function`` layer
(eager_gen.py:858) — forward compute, AMP cast, grad-node construction — and
the phi kernel dispatch (kernel_factory.h:271).  TPU-native design: every op
is a pure function on jax arrays; XLA is the kernel library, so there is no
registry/dispatch-by-place.  ``apply_op`` runs the primal (through jax.vjp if
any differentiable input requires grad) and records one TapeNode.
"""
from __future__ import annotations

import weakref
from typing import Any, Callable, List, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from . import dtype as dtype_mod
from .autograd import TapeNode, is_grad_enabled
from .tensor import Tensor
from .flags import get_flag

_CHECK_NAN_OPS_SKIP = {"isnan", "isinf", "isfinite", "nan_to_num"}


def _unwrap(x):
    if isinstance(x, Tensor):
        return x._value()
    return x


def _is_diff_dtype(arr) -> bool:
    try:
        return dtype_mod.is_floating_point(np.dtype(arr.dtype)) or dtype_mod.is_complex(
            np.dtype(arr.dtype)
        )
    except Exception:
        return False


# AMP autocast hook — installed by paddle_tpu.amp (reference: eager
# amp_auto_cast.h).  Signature: fn(op_name, tensor_args) -> tensor_args.
_amp_cast_hook = None

# Static-graph recording hook — installed by paddle_tpu.static while a
# Program is being built (reference: LayerHelper.append_op into the
# default ProgramDesc).  Signature:
# fn(op_name, primal, tensor_args, kwargs, out_tensors) -> None.
_static_record_hook = None

# Name of the most recently dispatched op — read by the fault-tolerance
# watchdog when a step stalls, so the hang report names the op that was
# in flight (a blocked collective shows up here as its dispatching op).
_last_op_name: str = None


def last_dispatched_op():
    return _last_op_name


def no_static_record():
    """Context manager suspending static-Program recording — for code
    that EXECUTES ops while a program records (composite control-flow
    internals, Executor train replay): the sub-dispatches must not leak
    into the program as stray top-level ops."""
    import contextlib

    @contextlib.contextmanager
    def _cm():
        global _static_record_hook
        h = _static_record_hook
        _static_record_hook = None
        try:
            yield
        finally:
            _static_record_hook = h

    return _cm()


def apply_op(
    name: str,
    primal: Callable,
    tensor_args: Sequence[Any],
    kwargs: dict = None,
    n_outs: int = 1,
):
    """Execute op ``primal(*arrays, **kwargs)`` over Tensor/array args.

    - non-Tensor args are passed through as-is (static attrs go in kwargs)
    - records a TapeNode via jax.vjp over the *differentiable Tensor* inputs
    - returns Tensor (or tuple of Tensors if n_outs > 1)
    """
    kwargs = kwargs or {}
    global _last_op_name
    _last_op_name = name
    if _amp_cast_hook is not None:
        tensor_args = _amp_cast_hook(name, tensor_args)

    from .custom_kernel import get_kernel_override

    _override = get_kernel_override(name)
    if _override is not None:
        primal = _override

    arrays = [_unwrap(a) for a in tensor_args]

    diff_idx: List[int] = []
    if is_grad_enabled():
        for i, a in enumerate(tensor_args):
            if (
                isinstance(a, Tensor)
                and not a.stop_gradient
                and _is_diff_dtype(arrays[i])
            ):
                diff_idx.append(i)

    if not diff_idx:
        out = primal(*arrays, **kwargs)
        outs_w = _wrap_outs(name, out, n_outs, stop_gradient=True)
        if _static_record_hook is not None:
            _static_record_hook(name, primal, tensor_args, kwargs,
                                outs_w if isinstance(outs_w, tuple)
                                else (outs_w,))
        return outs_w

    def _primal_on_diff(*diff_arrays):
        full = list(arrays)
        for j, i in enumerate(diff_idx):
            full[i] = diff_arrays[j]
        return primal(*full, **kwargs)

    outs, vjp_fn = jax.vjp(_primal_on_diff, *[arrays[i] for i in diff_idx])
    out_tensors = _wrap_outs(name, outs, n_outs, stop_gradient=False)
    outs_list = list(out_tensors) if isinstance(out_tensors, tuple) else [out_tensors]
    node = TapeNode(
        vjp_fn,
        inputs=[tensor_args[i] for i in diff_idx],
        outputs=outs_list,
        name=name,
        primal_fn=_primal_on_diff,
        input_arrays=[arrays[i] for i in diff_idx],
    )
    for t in outs_list:
        t._grad_node = node
    node_ref = weakref.ref(node)
    for i in diff_idx:
        t = tensor_args[i]
        lst = t._consumers
        if lst is None:
            lst = t._consumers = []
        lst.append(node_ref)
        # amortized prune: long-lived tensors (parameters) would otherwise
        # accumulate one dead weakref per consuming op forever
        n = len(lst)
        if n >= 64 and (n & (n - 1)) == 0:
            t._consumers = [r for r in lst if r() is not None]
    if _static_record_hook is not None:
        _static_record_hook(name, primal, tensor_args, kwargs,
                            tuple(outs_list))
    return out_tensors


def _wrap_outs(name, out, n_outs, stop_gradient):
    if get_flag("check_nan_inf") and name not in _CHECK_NAN_OPS_SKIP:
        _check_nan_inf(name, out)
    if n_outs == 1 and not isinstance(out, (tuple, list)):
        return Tensor._wrap(out, stop_gradient=stop_gradient)
    outs = tuple(Tensor._wrap(o, stop_gradient=stop_gradient) for o in out)
    return outs


def _check_nan_inf(name, out):
    """FLAGS_check_nan_inf parity (reference: details/nan_inf_utils_detail.cc
    for the host scan; .cu for the in-graph scan — see core/error_guard)."""
    outs = out if isinstance(out, (tuple, list)) else (out,)
    for o in outs:
        if isinstance(o, jax.core.Tracer):
            # compiled path: arm an in-graph sentinel; the trace runtime
            # raises after the step (error_guard.raise_on_error)
            from . import error_guard

            error_guard.set_error_if_nonfinite(name, o)
            continue
        try:
            a = np.asarray(o)
        except Exception:
            continue
        if a.dtype.kind in "fc" and not np.isfinite(a).all():
            raise FloatingPointError(f"Operator {name} output contains NaN/Inf")


