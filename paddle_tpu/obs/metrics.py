"""Prometheus-style text exposition of the existing serving snapshots.

``Engine.stats()`` / ``Fleet.stats()`` already export everything a
dashboard needs as nested JSON; this module flattens those SAME dicts
into the ``name{labels} value`` text format scrapers ingest — no new
counters, no second bookkeeping path that could drift from the real
one.  Numeric leaves become samples, booleans become 0/1, the
``state``-like strings become ``*_info`` gauges with the string as a
label, and everything else is skipped.

::

    from paddle_tpu import obs
    print(obs.render_metrics(engine.stats(), labels={"engine": "r0"}))
    # paddle_tpu_serving_queue_depth{engine="r0"} 0
    # paddle_tpu_serving_requests_completed{engine="r0"} 12
    ...

:func:`render_all_metrics` walks every live engine, fleet, AND training
loop through ``paddle_tpu.profiler`` — the process-wide ``/metrics``
endpoint body: ONE exposition covers both stacks (serving snapshots
under ``paddle_tpu_serving*``, the training observatory — timeline
counters, compile ledger, cost ledger, sentry counters — under
``paddle_tpu_train``).
"""
from __future__ import annotations

import re
from typing import Dict, Iterator, Optional, Tuple

__all__ = ["render_metrics", "render_all_metrics"]

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _metric_name(*parts: str) -> str:
    return "_".join(_NAME_RE.sub("_", str(p)).strip("_")
                    for p in parts if str(p) != "")


def _label_value(v) -> str:
    """Escape per the Prometheus exposition spec: backslash, double
    quote, and newline are the three characters that must be escaped
    inside a label value."""
    return (str(v).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _labels(labels: Optional[Dict[str, str]]) -> str:
    if not labels:
        return ""
    body = ",".join(f'{k}="{_label_value(v)}"'
                    for k, v in sorted(labels.items()))
    return "{" + body + "}"


def _walk(node, path: Tuple[str, ...]) -> Iterator[Tuple[Tuple[str, ...],
                                                         object]]:
    if isinstance(node, dict):
        for k, v in node.items():
            yield from _walk(v, path + (str(k),))
    elif isinstance(node, (list, tuple)):
        # lists (per-replica tables etc.) are indexed into the name
        for i, v in enumerate(node):
            yield from _walk(v, path + (str(i),))
    else:
        yield path, node


def render_metrics(snapshot: dict, *, prefix: str = "paddle_tpu_serving",
                   labels: Optional[Dict[str, str]] = None) -> str:
    """Flatten one ``stats()`` snapshot into exposition text.  ``name``
    keys found in the snapshot become an ``engine`` label by default so
    the same metric name aggregates across engines."""
    labels = dict(labels or {})
    if not labels and isinstance(snapshot.get("name"), str):
        labels["engine"] = snapshot["name"]
    lab = _labels(labels)
    lines = []
    for path, v in _walk(snapshot, ()):
        if path and path[-1] == "name":
            continue
        if isinstance(v, bool):
            v = int(v)
        if isinstance(v, (int, float)):
            lines.append(f"{_metric_name(prefix, *path)}{lab} {v}")
        elif isinstance(v, str) and path and path[-1] in (
                "state", "engine_state", "replica_state",
                "kv_block_invariants", "kv_layout",
                "fingerprint", "chip", "bound"):
            name = _metric_name(prefix, *path) + "_info"
            il = _labels({**labels, "value": v})
            lines.append(f"{name}{il} 1")
    return "\n".join(lines) + ("\n" if lines else "")


def render_all_metrics(prefix: str = "paddle_tpu_serving") -> str:
    """The process-wide ``/metrics`` body: every live engine's,
    fleet's, and training loop's snapshot, flattened (via
    ``paddle_tpu.profiler``).  Training metrics render under the
    ``paddle_tpu_train`` prefix regardless of ``prefix`` (one scrape
    covers both stacks without name collisions)."""
    from .. import profiler

    chunks = []
    for name, snap in profiler.serving_stats().items():
        chunks.append(render_metrics(snap, prefix=prefix,
                                     labels={"engine": name}))
    for name, snap in profiler.serving_fleet().items():
        chunks.append(render_metrics(snap, prefix=prefix + "_fleet",
                                     labels={"fleet": name}))
    for name, snap in profiler.train_stats().items():
        chunks.append(render_metrics(snap, prefix="paddle_tpu_train",
                                     labels={"loop": name}))
    return "".join(chunks)
