"""Compile ledger — every XLA compile, named, timed, and attributed.

The zero-recompile discipline is this repo's core performance invariant
(docs/SERVING.md, docs/ANALYSIS.md): after warmup, a steady-state
executable-cache miss is a *bug* that costs seconds of wall time per
occurrence.  The serving engine already counts misses
(``stats()["compile_cache"]``); training, until now, could only see
them as unexplained step-time spikes.

:class:`CompileLedger` subscribes to the executable-cache miss path
(:func:`paddle_tpu.jit.subscribe_compiles`) and records **every**
compile as a structured record:

====================  ======================================================
``fn``                qualname of the compiled function
``key``               short digest of the full cache key (spec + mode bits)
``arg_specs``         ``dtype[shape]`` list of the tensor arguments
``seconds``           wall time: trace + build + the first call (jax.jit
                      compiles lazily, so the first execution pays XLA)
``site``              attributed call site (innermost non-framework frame)
``executed``          False for trace-only discovery
                      (``get_concrete_program`` — no executable built)
``steady_state``      True when the miss happened after
                      :meth:`CompileLedger.mark_steady` — a named anomaly
====================  ======================================================

so cumulative compile time is a first-class metric
(``stats()["compiles"]``, surfaced through ``profiler.train_stats()``)
and a steady-state miss is a *named* event — function, shapes, call
site — instead of a silent latency cliff.

Pure host-side bookkeeping: attaching a ledger changes no cache key and
performs no device transfer; with no ledger attached the miss path pays
one falsy check.
"""
from __future__ import annotations

from typing import Dict, List, Optional

__all__ = ["CompileLedger"]


class CompileLedger:
    """Subscriber-side ledger of executable-cache misses.

    Use as a context manager or with explicit
    :meth:`attach`/:meth:`detach`::

        ledger = CompileLedger()
        with ledger:
            warmup()              # recorded, pre-steady
            ledger.mark_steady()  # everything after this is an anomaly
            train(...)
        assert ledger.steady_state_misses == 0

    Args:
        name: ledger label (the ``profiler.train_stats()`` key context).
        max_records: retention bound; past it records are dropped and
            counted (the counters keep counting).
    """

    def __init__(self, name: str = "train", max_records: int = 4096):
        if max_records < 1:
            raise ValueError(f"max_records must be >= 1, got {max_records}")
        self.name = name
        self.max_records = int(max_records)
        self.records: List[dict] = []
        self.dropped = 0
        self.compiles = 0
        self.total_seconds = 0.0
        self.steady_state_misses = 0
        self._steady = False
        self._attached = False

    # -- subscription -------------------------------------------------------

    def attach(self) -> "CompileLedger":
        if not self._attached:
            from ..jit import subscribe_compiles

            subscribe_compiles(self._on_compile)
            self._attached = True
        return self

    def detach(self) -> None:
        if self._attached:
            from ..jit import unsubscribe_compiles

            unsubscribe_compiles(self._on_compile)
            self._attached = False

    def __enter__(self) -> "CompileLedger":
        return self.attach()

    def __exit__(self, *_exc) -> bool:
        self.detach()
        return False

    # -- recording ----------------------------------------------------------

    def _on_compile(self, record: dict) -> None:
        self.compiles += 1
        self.total_seconds += record["seconds"]
        rec = dict(record, steady_state=self._steady)
        if self._steady:
            self.steady_state_misses += 1
        if len(self.records) >= self.max_records:
            self.dropped += 1
            return
        self.records.append(rec)

    def mark_steady(self) -> None:
        """Everything compiled from here on is a steady-state miss — a
        named anomaly.  The training loops call this after the first
        completed step (by then every program of a fixed-shape step has
        been built); call it after ``warmup()`` when driving manually."""
        self._steady = True

    def reset_steady(self) -> None:
        """Back out of steady state (e.g. an OOM retry at a new batch
        size legitimately recompiles).  Already-counted anomalies stay
        counted."""
        self._steady = False

    @property
    def steady(self) -> bool:
        return self._steady

    # -- introspection ------------------------------------------------------

    def anomalies(self) -> List[dict]:
        """The steady-state miss records — each one names the function,
        arg specs, and call site that recompiled when nothing should."""
        return [r for r in self.records if r.get("steady_state")]

    def stats(self) -> dict:
        """JSON-ready counters (``profiler.train_stats()`` surface).
        ``by_function`` aggregates count/seconds per compiled function;
        steady-state anomalies ride along fully named."""
        by_fn: Dict[str, dict] = {}
        for r in self.records:
            agg = by_fn.setdefault(r["fn"], {"count": 0, "seconds": 0.0})
            agg["count"] += 1
            agg["seconds"] = round(agg["seconds"] + r["seconds"], 6)
        return {
            "compiles": self.compiles,
            "total_seconds": round(self.total_seconds, 6),
            "steady_state_misses": self.steady_state_misses,
            "records_dropped": self.dropped,
            "by_function": by_fn,
            "anomalies": [
                {k: r[k] for k in ("fn", "key", "arg_specs", "seconds",
                                   "site")}
                for r in self.anomalies()],
        }
