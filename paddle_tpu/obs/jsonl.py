"""JSONL event-log export of a serving RequestTracer.

One JSON object per line, in event order — the grep/jq-friendly form of
the same chain the Perfetto exporter renders.  Wall-clock timestamps
are attached HERE, at export, from the tracer's one-shot anchor pair
(``t0``/``wall0``): events themselves are stamped monotonically
(``time.perf_counter``), so no latency anywhere is ever computed across
a wall-clock step — wall time exists only in exported records, as the
clock-discipline audit (ISSUE 9) requires.
"""
from __future__ import annotations

import json
from typing import Iterator

__all__ = ["jsonl_lines", "write_jsonl"]


def jsonl_lines(tracer) -> Iterator[str]:
    """Yield one JSON line per event: the monotonic ``ts`` (seconds
    since tracer start) plus the derived ``wall`` timestamp."""
    wall0 = tracer.wall0
    for ev in tracer.events:
        yield json.dumps({"wall": round(wall0 + ev["ts"], 6), **ev},
                         sort_keys=False)


def write_jsonl(tracer, path: str) -> int:
    """Write the event log to ``path``; returns the line count."""
    n = 0
    with open(path, "w") as f:
        for line in jsonl_lines(tracer):
            f.write(line + "\n")
            n += 1
    return n
