"""Crash artifact persistence: the post-mortem must outlive the process.

The :class:`~.flight.FlightRecorder` exists to explain the moments
before a failure, and the serving :class:`~..serving.tracing.RequestTracer`
holds the per-request story — but both live in process RAM, so the two
paths that *kill* the process (``StepWatchdog`` hard-exit via
``os._exit``, divergence-sentry escalation) used to destroy exactly the
artifact they exist for.  :func:`persist_crash_artifacts` freezes every
live flight ring and every armed tracer into one JSON file *before* the
process dies:

- destination: ``$PADDLE_TPU_TRACE_DIR`` when set, else a ``crash/``
  sibling inside the most recently opened request journal's directory
  (the journal is the durable surface a recovering process reads first,
  so its crash dumps belong next to it), else nowhere (the function is
  a no-op — crash persistence is best-effort and must never block the
  exit path);
- content: the firing reason, wall time, pid, every registered flight
  recorder's ring (frozen via ``dump()`` so the snapshot carries the
  dump), and every live tracer's full event/span payload (wall-anchored
  through the tracer's one-shot anchor, so a post-mortem Perfetto
  export still lines up with logs).

Every failure in here is swallowed: a crash handler that crashes is
worse than no handler.
"""
from __future__ import annotations

import json
import os
import time
from typing import List, Optional

__all__ = ["persist_crash_artifacts", "register_journal_dir",
           "crash_dir"]

#: journal directories registered at RequestJournal construction,
#: newest last — the fallback crash destination
_JOURNAL_DIRS: List[str] = []


def register_journal_dir(path: str) -> None:
    """Remember a journal directory as a crash-dump destination (its
    ``crash/`` sibling).  Called by ``RequestJournal.__init__``."""
    p = os.path.abspath(str(path))
    if p in _JOURNAL_DIRS:
        _JOURNAL_DIRS.remove(p)
    _JOURNAL_DIRS.append(p)
    del _JOURNAL_DIRS[:-8]               # bounded


def unregister_journal_dir(path: str) -> None:
    """Forget a journal directory (``RequestJournal.close``): a cleanly
    closed journal's directory may be deleted by its owner, and a later
    crash must not resurrect it as a dump destination.  A *crashed*
    process never closes, which is exactly when the registration should
    still be live."""
    p = os.path.abspath(str(path))
    if p in _JOURNAL_DIRS:
        _JOURNAL_DIRS.remove(p)


def crash_dir() -> Optional[str]:
    """Where crash artifacts go: ``$PADDLE_TPU_TRACE_DIR``, else
    ``<newest journal>/crash``, else None (nowhere configured)."""
    d = os.environ.get("PADDLE_TPU_TRACE_DIR")
    if d:
        return d
    if _JOURNAL_DIRS:
        return os.path.join(_JOURNAL_DIRS[-1], "crash")
    return None


def persist_crash_artifacts(reason: str,
                            extra: Optional[dict] = None
                            ) -> Optional[str]:
    """Freeze flight rings + armed tracers to disk; returns the written
    path, or None when no destination is configured or anything failed
    (best-effort by contract — the caller is about to ``os._exit``)."""
    try:
        d = crash_dir()
        if d is None:
            return None
        os.makedirs(d, exist_ok=True)
        payload = {"reason": str(reason),
                   "wall_time": round(time.time(), 6),
                   "pid": os.getpid()}
        try:
            from .. import profiler

            # capture every live ring WITHOUT banking a dump (peek):
            # mutating recorder state from the crash path would
            # manufacture events the live process's consumers assert on
            rings = {}
            for ref in list(getattr(profiler, "_flight_recorders", ())):
                rec = ref()
                if rec is not None:
                    try:
                        rings.setdefault(rec.name, []).append(
                            rec.peek(f"crash: {reason}"))
                    except Exception:    # noqa: BLE001 — best effort
                        pass
            payload["flight_rings"] = rings
            # plus the registered snapshots (banked dumps included)
            payload["flight"] = profiler.flight_record()
        except Exception:                # noqa: BLE001 — best effort
            pass
        try:
            from ..serving import tracing

            traces = []
            for tr in tracing.live_tracers():
                traces.append({
                    "wall0": tr.wall0,
                    "dropped": tr.dropped,
                    "events": list(tr.events),
                    "spans": {str(k): dict(v)
                              for k, v in tr.spans.items()},
                })
            if traces:
                payload["traces"] = traces
        except Exception:                # noqa: BLE001 — best effort
            pass
        if extra:
            payload.update(extra)
        path = os.path.join(
            d, f"crash-{os.getpid()}-{int(time.time() * 1e3)}.json")
        with open(path, "w", encoding="utf-8") as f:
            json.dump(payload, f, default=str)
            f.flush()
            os.fsync(f.fileno())
        return path
    except Exception:                    # noqa: BLE001 — never block exit
        return None
