"""paddle_tpu.obs — observability exporters for the serving stack.

A thin, dependency-free export layer over
:class:`paddle_tpu.serving.tracing.RequestTracer` and the
``Engine.stats()`` / ``Fleet.stats()`` snapshots:

- :func:`chrome_trace` / :func:`write_chrome_trace` — Chrome/Perfetto
  trace-event JSON (load in https://ui.perfetto.dev or
  ``chrome://tracing``): one track group (process) per replica, one
  thread per slot plus a scheduler track, spans as complete events,
  preempt/redispatch links as flow arrows, per-step batch occupancy as
  a counter track;
- :func:`write_jsonl` / :func:`jsonl_lines` — one JSON object per
  event, wall-clock timestamps added AT EXPORT from the tracer's
  anchor pair (events themselves are stamped monotonically and never
  do wall-clock math);
- :func:`render_metrics` / :func:`render_all_metrics` — Prometheus-
  style text exposition of the existing ``stats()`` snapshots (no new
  counters: this is the same dict, flattened for scrapers).

Everything here is host-side and read-only: exporting never touches an
engine, a traced value, or a compiled program.

:class:`~.flight.FlightRecorder` also lives here — the always-on
bounded step-summary ring both the serving engine and the training
runtime feed (frozen into a post-mortem dump on unhealthy/eject/
sentry-escalation/watchdog events).
"""
from .flight import FlightRecorder  # noqa: F401
from .perfetto import chrome_trace, write_chrome_trace  # noqa: F401
from .jsonl import jsonl_lines, write_jsonl  # noqa: F401
from .metrics import render_metrics, render_all_metrics  # noqa: F401

__all__ = ["FlightRecorder", "chrome_trace", "write_chrome_trace",
           "jsonl_lines", "write_jsonl", "render_metrics",
           "render_all_metrics", "validate_trace"]


def __getattr__(name):
    # lazy: serving.tracing imports obs.flight at module top, so an
    # eager import here would be circular (obs partially initialized
    # when tracing asks back for it)
    if name == "validate_trace":
        from ..serving.tracing import validate_trace

        return validate_trace
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
